#!/usr/bin/env python
"""Attention-tiling microbenchmark (VERDICT r3 item 2 groundwork).

Times, on the real chip, one encoder-shaped attention op under the
candidate tilings so the crossover table in ``ops/attention.py`` is
measured, not argued:

* ``einsum``   — XLA batched einsum attention
* ``fusedKh``  — re-tiled Pallas kernel, K flat (batch, head) tiles/step

Timing uses k-rep fori_loop differencing (median of trials) so the
~100 ms tunnel RTT and its jitter cancel out.  The headline decision is
made on the IN-CONTEXT numbers from bench_fwd.py, not these — see the
table in ops/attention.py.

``--long-seq`` switches to the ring-vs-dense crossover scenario
(PR 14 long-context serving): one attention op per sequence length,
dense full-softmax vs ``parallel.ring.ring_attention`` sharded over an
``sp`` mesh axis, reporting p50 per-call ms AND the compiled
executable's per-device memory (argument+output+temp bytes from XLA
``memory_analysis`` — the O(s^2) score materialization is the term the
ring divides by sp^2).  The committed record is ``BENCH_attn.json``;
the crossover sequence length is where the ring first wins on p50
while its per-device peak stays flat.  Needs ``--sp`` devices: on CPU
the bench respawns itself under ``--xla_force_host_platform_device_
count`` (same recipe as the mesh audit).
"""

from __future__ import annotations

import argparse
import sys
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def einsum_attention(q, k, v, bias, scale):
    logits = (
        jnp.einsum("bqnd,bknd->bnqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    logits = logits + bias[:, None, None, :]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum(
        "bnqk,bknd->bqnd", probs, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def timed_ms(fn, params, reps_hi=201, trials=3):
    """Amortized per-call ms via k=1 vs k=reps_hi fori_loop difference
    (median of ``trials`` so ~100 ms tunnel jitter cannot swamp sub-ms
    kernels)."""

    @functools.partial(jax.jit, static_argnames=("k",))
    def rep(args, k):
        def body(i, acc):
            # chain acc into the input so XLA can neither hoist the body
            # out of the loop nor run iterations concurrently (acc*1e-20
            # is not foldable: x*0 != 0 for floats)
            eps = (acc * 1e-20).astype(args[0].dtype)
            out = fn(args[0] + eps, *args[1:])
            return acc + jnp.sum(out.astype(jnp.float32))

        return jax.lax.fori_loop(0, k, body, 0.0)

    float(rep(params, 1))
    float(rep(params, reps_hi))
    samples = []
    for _ in range(trials):
        t0 = time.perf_counter()
        float(rep(params, 1))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(rep(params, reps_hi))
        thi = time.perf_counter() - t0
        samples.append(max((thi - t1) / (reps_hi - 1) * 1e3, 1e-3))
    samples.sort()
    return samples[len(samples) // 2]


def ring_vs_dense_crossover(seqs, sp, b, nh, hd, reps_hi=5, trials=3):
    """One attention op per sequence length, dense vs ring-over-sp:
    p50 per-call ms (k-rep differencing, fewer reps — long sequences
    are slow everywhere) and per-device compiled memory.  Returns the
    per-seq table plus the first sequence length where ring wins."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from llm_weighted_consensus_tpu.parallel.compat import shard_map
    from llm_weighted_consensus_tpu.parallel.ring import ring_attention

    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
    qkv_spec = P(None, "sp", None, None)
    bias_spec = P(None, "sp")
    ring_fn = jax.jit(
        shard_map(
            lambda q, k, v, bias, scale: ring_attention(
                q, k, v, bias, scale, "sp"
            ),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec, bias_spec, P()),
            out_specs=qkv_spec,
            check_vma=False,
        )
    )

    def compiled_bytes(fn, *xs):
        mem = jax.jit(fn).lower(*xs).compile().memory_analysis()
        return {
            "peak_bytes": int(
                mem.argument_size_in_bytes
                + mem.output_size_in_bytes
                + mem.temp_size_in_bytes
            ),
            "temp_bytes": int(mem.temp_size_in_bytes),
        }

    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    rng = np.random.default_rng(0)
    scale = 1.0 / float(hd) ** 0.5
    rows = {}
    crossover = None
    for s in seqs:
        if s % sp:
            continue
        shape = (b, s, nh, hd)
        q = jnp.asarray(rng.standard_normal(shape), dtype)
        k = jnp.asarray(rng.standard_normal(shape), dtype)
        v = jnp.asarray(rng.standard_normal(shape), dtype)
        bias = jnp.zeros((b, s), jnp.float32)

        dense = compiled_bytes(
            lambda q, k, v: einsum_attention(q, k, v, bias, scale), q, k, v
        )
        dense["p50_ms"] = timed_ms(
            lambda q, k, v: einsum_attention(q, k, v, bias, scale),
            (q, k, v), reps_hi=reps_hi, trials=trials,
        )

        sharding = NamedSharding(mesh, qkv_spec)
        qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
        bs = jax.device_put(bias, NamedSharding(mesh, bias_spec))
        scale_arr = jnp.float32(scale)
        ref = np.asarray(
            einsum_attention(q, k, v, bias, scale), np.float32
        )
        out = np.asarray(ring_fn(qs, ks, vs, bs, scale_arr), np.float32)
        np.testing.assert_allclose(out, ref, atol=3e-2, rtol=3e-2)
        # memory_analysis on the sharded executable is PER-DEVICE —
        # exactly the "does the score tile fit one chip" question
        ring = compiled_bytes(
            lambda q, k, v, t: ring_fn(q, k, v, bs, t),
            qs, ks, vs, scale_arr,
        )
        ring["p50_ms"] = timed_ms(
            lambda q, k, v, t: ring_fn(q, k, v, bs, t),
            (qs, ks, vs, scale_arr), reps_hi=reps_hi, trials=trials,
        )
        rows[f"s={s}"] = {"dense": dense, f"ring_sp{sp}": ring}
        if crossover is None and ring["p50_ms"] < dense["p50_ms"]:
            crossover = s
        print(json.dumps({f"s={s}": rows[f"s={s}"]}), flush=True)
    return rows, crossover


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--b", type=int, default=64)
    p.add_argument("--nh", type=int, default=16)
    p.add_argument("--hd", type=int, default=64)
    p.add_argument("--seqs", default="128,256,512")
    p.add_argument("--ks", default="8,16,32")
    # probe covers backend init + one real block_until_ready dispatch
    # (bench.probe_backend), so a healthy backend answers in seconds and
    # a wedged tunnel records tpu-unavailable in 45 s, not 240+600 s
    p.add_argument("--probe-timeout", type=float, default=45.0)
    p.add_argument(
        "--long-seq",
        action="store_true",
        help="ring-vs-dense long-context crossover instead of the "
        "tiling sweep: p50 + per-device compiled memory per sequence "
        "length (--long-seqs), ring sharded over --sp devices",
    )
    p.add_argument("--long-seqs", default="256,512,1024,2048")
    p.add_argument("--sp", type=int, default=4)
    p.add_argument("--long-b", type=int, default=1)
    p.add_argument("--long-nh", type=int, default=4)
    args = p.parse_args()
    # wedge-proofing: shared bounded-probe preamble (bench.probe_or_exit)
    # AFTER argparse so --help stays instant
    from bench import probe_or_exit

    probe_or_exit(args.probe_timeout)

    if args.long_seq and jax.device_count() < args.sp:
        # the ring needs --sp devices; a CPU backend exposes one by
        # default, so respawn under the forced-host-device-count env
        # (the parent backend is already initialized and cannot grow)
        import os
        import subprocess

        from llm_weighted_consensus_tpu.parallel.dist import force_cpu_env

        env = force_cpu_env(dict(os.environ), n_devices=args.sp)
        return subprocess.run(
            [sys.executable, __file__] + sys.argv[1:], env=env
        ).returncode

    if args.long_seq:
        seqs = [int(x) for x in args.long_seqs.split(",")]
        rows, crossover = ring_vs_dense_crossover(
            seqs, args.sp, args.long_b, args.long_nh, args.hd
        )
        print(json.dumps({
            "metric": "ring-vs-dense attention crossover "
            "(p50 ms + per-device peak bytes per seq length)",
            "backend": jax.default_backend(),
            "sp": args.sp,
            "b": args.long_b,
            "nh": args.long_nh,
            "hd": args.hd,
            "crossover_seq": crossover,
            "results": rows,
        }))
        return

    from llm_weighted_consensus_tpu.ops.attention import fused_attention_tiled

    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    rng = np.random.default_rng(0)
    results = {}
    for s in [int(x) for x in args.seqs.split(",")]:
        shape = (args.b, s, args.nh, args.hd)
        q = jnp.asarray(rng.standard_normal(shape), dtype)
        k = jnp.asarray(rng.standard_normal(shape), dtype)
        v = jnp.asarray(rng.standard_normal(shape), dtype)
        bias = jnp.zeros((args.b, s), jnp.float32)
        scale = 1.0 / float(args.hd) ** 0.5

        row = {}
        ref = einsum_attention(q, k, v, bias, scale)
        row["einsum"] = timed_ms(
            lambda q, k, v: einsum_attention(q, k, v, bias, scale), (q, k, v)
        )
        for kk in [int(x) for x in args.ks.split(",")]:
            if (args.b * args.nh) % kk:
                continue
            try:
                out = fused_attention_tiled(
                    q, k, v, bias, scale, heads_per_step=kk
                )
                np.testing.assert_allclose(
                    np.asarray(out, np.float32), np.asarray(ref, np.float32),
                    atol=3e-2, rtol=3e-2,
                )
                row[f"fused{kk}h"] = timed_ms(
                    lambda q, k, v, kk=kk: fused_attention_tiled(
                        q, k, v, bias, scale, heads_per_step=kk
                    ),
                    (q, k, v),
                )
            except Exception as e:  # noqa: BLE001 - report and move on
                row[f"fused{kk}h"] = f"ERROR: {type(e).__name__}: {e}"[:200]
        results[f"s={s}"] = row
        print(json.dumps({f"s={s}": row}), flush=True)

    print(json.dumps({"backend": jax.default_backend(), "results": results}))


if __name__ == "__main__":
    sys.exit(main())
