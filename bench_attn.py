#!/usr/bin/env python
"""Attention-tiling microbenchmark (VERDICT r3 item 2 groundwork).

Times, on the real chip, one encoder-shaped attention op under the
candidate tilings so the crossover table in ``ops/attention.py`` is
measured, not argued:

* ``einsum``   — XLA batched einsum attention
* ``fusedKh``  — re-tiled Pallas kernel, K flat (batch, head) tiles/step

Timing uses k-rep fori_loop differencing (median of trials) so the
~100 ms tunnel RTT and its jitter cancel out.  The headline decision is
made on the IN-CONTEXT numbers from bench_fwd.py, not these — see the
table in ops/attention.py.
"""

from __future__ import annotations

import argparse
import sys
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def einsum_attention(q, k, v, bias, scale):
    logits = (
        jnp.einsum("bqnd,bknd->bnqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    logits = logits + bias[:, None, None, :]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum(
        "bnqk,bknd->bqnd", probs, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def timed_ms(fn, params, reps_hi=201, trials=3):
    """Amortized per-call ms via k=1 vs k=reps_hi fori_loop difference
    (median of ``trials`` so ~100 ms tunnel jitter cannot swamp sub-ms
    kernels)."""

    @functools.partial(jax.jit, static_argnames=("k",))
    def rep(args, k):
        def body(i, acc):
            # chain acc into the input so XLA can neither hoist the body
            # out of the loop nor run iterations concurrently (acc*1e-20
            # is not foldable: x*0 != 0 for floats)
            eps = (acc * 1e-20).astype(args[0].dtype)
            out = fn(args[0] + eps, *args[1:])
            return acc + jnp.sum(out.astype(jnp.float32))

        return jax.lax.fori_loop(0, k, body, 0.0)

    float(rep(params, 1))
    float(rep(params, reps_hi))
    samples = []
    for _ in range(trials):
        t0 = time.perf_counter()
        float(rep(params, 1))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(rep(params, reps_hi))
        thi = time.perf_counter() - t0
        samples.append(max((thi - t1) / (reps_hi - 1) * 1e3, 1e-3))
    samples.sort()
    return samples[len(samples) // 2]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--b", type=int, default=64)
    p.add_argument("--nh", type=int, default=16)
    p.add_argument("--hd", type=int, default=64)
    p.add_argument("--seqs", default="128,256,512")
    p.add_argument("--ks", default="8,16,32")
    p.add_argument("--probe-timeout", type=float, default=240.0)
    args = p.parse_args()
    # wedge-proofing: shared bounded-probe preamble (bench.probe_or_exit)
    # AFTER argparse so --help stays instant
    from bench import probe_or_exit

    probe_or_exit(args.probe_timeout)

    from llm_weighted_consensus_tpu.ops.attention import fused_attention_tiled

    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    rng = np.random.default_rng(0)
    results = {}
    for s in [int(x) for x in args.seqs.split(",")]:
        shape = (args.b, s, args.nh, args.hd)
        q = jnp.asarray(rng.standard_normal(shape), dtype)
        k = jnp.asarray(rng.standard_normal(shape), dtype)
        v = jnp.asarray(rng.standard_normal(shape), dtype)
        bias = jnp.zeros((args.b, s), jnp.float32)
        scale = 1.0 / float(args.hd) ** 0.5

        row = {}
        ref = einsum_attention(q, k, v, bias, scale)
        row["einsum"] = timed_ms(
            lambda q, k, v: einsum_attention(q, k, v, bias, scale), (q, k, v)
        )
        for kk in [int(x) for x in args.ks.split(",")]:
            if (args.b * args.nh) % kk:
                continue
            try:
                out = fused_attention_tiled(
                    q, k, v, bias, scale, heads_per_step=kk
                )
                np.testing.assert_allclose(
                    np.asarray(out, np.float32), np.asarray(ref, np.float32),
                    atol=3e-2, rtol=3e-2,
                )
                row[f"fused{kk}h"] = timed_ms(
                    lambda q, k, v, kk=kk: fused_attention_tiled(
                        q, k, v, bias, scale, heads_per_step=kk
                    ),
                    (q, k, v),
                )
            except Exception as e:  # noqa: BLE001 - report and move on
                row[f"fused{kk}h"] = f"ERROR: {type(e).__name__}: {e}"[:200]
        results[f"s={s}"] = row
        print(json.dumps({f"s={s}": row}), flush=True)

    print(json.dumps({"backend": jax.default_backend(), "results": results}))


if __name__ == "__main__":
    sys.exit(main())
