#!/usr/bin/env python
"""Full-forward breakdown: where the bge-large N=64/s=128 milliseconds go.

Times ``bert.embed`` on the real chip with each cost candidate swapped
out (monkeypatched) so the device-only budget is attributable:
attention impl (einsum vs tiled Pallas), GELU (exact erf vs tanh vs
identity), layernorm (real vs identity).  Grounds VERDICT r3 item 1.
"""

from __future__ import annotations

import argparse
import sys
import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def timed_ms(fn, args_, reps_hi=51, trials=3):
    @functools.partial(jax.jit, static_argnames=("k",))
    def rep(args, k):
        def body(i, acc):
            eps = (acc * 1e-20).astype(jnp.int32)
            out = fn(args[0], args[1] + eps, *args[2:])
            return acc + jnp.sum(out.astype(jnp.float32))

        return jax.lax.fori_loop(0, k, body, 0.0)

    float(rep(args_, 1))
    float(rep(args_, reps_hi))
    samples = []
    for _ in range(trials):
        t0 = time.perf_counter()
        float(rep(args_, 1))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(rep(args_, reps_hi))
        thi = time.perf_counter() - t0
        samples.append(max((thi - t1) / (reps_hi - 1) * 1e3, 1e-3))
    samples.sort()
    return round(samples[len(samples) // 2], 3)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="bge-large-en")
    p.add_argument("--b", type=int, default=64)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--probe-timeout", type=float, default=240.0)
    args = p.parse_args()
    # wedge-proofing: shared bounded-probe preamble (bench.probe_or_exit)
    # AFTER argparse so --help stays instant
    from bench import probe_or_exit

    probe_or_exit(args.probe_timeout)

    import dataclasses

    from llm_weighted_consensus_tpu.models import bert
    from llm_weighted_consensus_tpu.models.configs import PRESETS

    config = PRESETS[args.model]
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    params = bert.init_params(jax.random.PRNGKey(0), config, dtype=dtype)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(
        rng.integers(0, config.vocab_size, (args.b, args.seq)), jnp.int32
    )
    mask = jnp.ones((args.b, args.seq), jnp.int32)

    real_gelu = bert._gelu_erf
    real_ln = bert._layer_norm

    def run(cfg):
        return timed_ms(
            lambda p_, i_, m_: bert.embed.__wrapped__(
                p_, i_, m_, cfg, pooling="cls", normalize=True
            ),
            (params, ids, mask),
        )

    out = {}
    for impl in ("einsum", "fused"):
        cfg = dataclasses.replace(config, attention_impl=impl)
        out[f"attn={impl}"] = run(cfg)

    cfg = dataclasses.replace(config, attention_impl="einsum")
    bert._gelu_erf = lambda x: jax.nn.gelu(x, approximate=True)
    out["gelu=tanh"] = run(cfg)
    bert._gelu_erf = lambda x: x
    out["gelu=identity"] = run(cfg)
    bert._gelu_erf = real_gelu

    bert._layer_norm = lambda x, p_, eps: x
    out["ln=identity"] = run(cfg)
    bert._layer_norm = real_ln

    out["backend"] = jax.default_backend()
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
