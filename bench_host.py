#!/usr/bin/env python
"""Device-free host-path benchmark: tokenize + ballot + merge per request.

The r5 review claimed the host half of a consensus request (WordPiece
tokenization of the candidate texts, ballot construction, J judge streams
merged through the score engine) dropped from 42.4 ms to 6.6 ms, but no
harness made that claim driver-measurable without a TPU.  This bench runs
the REAL host path — ``WordPieceTokenizer.encode_batch``, the seeded
``PrefixTree`` ballot, ``ScoreClient.create_streaming`` with the full
per-judge stream merge and weighted tally — against scripted in-memory
upstream judges (tests/fakes.py transport), so it needs no device, no
network, and no jax.

Device-free is enforced, not aspirational: the tokenizer module is loaded
standalone (bypassing ``models/__init__`` which imports the jax encoders)
and the final record carries ``"jax_imported": false`` asserted from
``sys.modules``.

Per request: tokenize N candidate texts to the serving seq length, then
stream one full consensus (initial candidate chunk, J judge ballots,
final tally frame) through the engine.  Prints ONE JSON line with
p50/p99 per-request ms and a tokenize / score-engine breakdown.

Run: python bench_host.py            (8 judges x N=64, 50 requests)
     python bench_host.py --requests 5   (smoke)
"""

from __future__ import annotations

import argparse
import asyncio
import importlib.util
import json
import os
import random
import statistics
import sys
import time


def _load_tokenizer_module():
    """Load models/tokenizer.py WITHOUT importing the models package
    (whose __init__ imports the jax encoders)."""
    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(
        here, "llm_weighted_consensus_tpu", "models", "tokenizer.py"
    )
    spec = importlib.util.spec_from_file_location("_lwc_host_tokenizer", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def host_tokenizer():
    """Same vocab as bench.bench_tokenizer, built against the standalone
    tokenizer module (bench.bench_tokenizer itself would import jax)."""
    from bench import BENCH_WORDS

    tok_mod = _load_tokenizer_module()
    alphanum = "abcdefghijklmnopqrstuvwxyz0123456789"
    tokens = (
        ["[PAD]", "[UNK]", "[CLS]", "[SEP]"]
        + BENCH_WORDS
        + list(alphanum)
        + ["##" + c for c in alphanum]
    )
    vocab = {t: i for i, t in enumerate(dict.fromkeys(tokens))}
    return tok_mod.WordPieceTokenizer(vocab)


def build_engine(
    judges: int, n: int, requests: int, seed: int, host_fastpath: bool = False
):
    """A ScoreClient over scripted judge streams: ``requests`` consensus
    calls' worth of scripts (judges make exactly one attempt each — no
    retries), plus the params/model objects they score against."""
    from llm_weighted_consensus_tpu import archive, registry
    from llm_weighted_consensus_tpu.ballot import PrefixTree, branch_limit
    from llm_weighted_consensus_tpu.clients.chat import (
        ApiBase,
        BackoffPolicy,
        DefaultChatClient,
    )
    from llm_weighted_consensus_tpu.clients.score import ScoreClient

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"),
    )
    from fakes import FakeTransport, Script, chunk_obj

    # replay the seeded ballot the engine will build (rng_factory below
    # hands it the same stream) so judges vote real keys
    rng = random.Random(seed)
    tree = PrefixTree.build(rng, n, branch_limit(None))
    keys = {idx: key for key, idx in tree.key_indices(rng)}

    def judge_script(key):
        return Script(
            [
                chunk_obj("I pick ", model="up-model"),
                chunk_obj(f"{key} as best.", model="up-model", finish="stop"),
            ]
        )

    vote_rng = random.Random(seed + 1)
    scripts = []
    for _ in range(requests):
        # a contested vote: each judge picks among the top few candidates
        for _ in range(judges):
            scripts.append(judge_script(keys[vote_rng.randrange(3)]))

    transport = FakeTransport(scripts)
    chat = DefaultChatClient(
        transport,
        [ApiBase("https://up.example", "key")],
        backoff=BackoffPolicy(max_elapsed_ms=0),
    )
    client = ScoreClient(
        chat,
        registry.InMemoryModelRegistry(),
        archive_fetcher=archive.InMemoryArchive(),
        rng_factory=lambda: random.Random(seed),
        host_fastpath=host_fastpath,
    )
    model_json = {
        "llms": [
            {
                "model": f"judge-{j}",
                "weight": {"type": "static", "weight": 1 + j % 3},
            }
            for j in range(judges)
        ]
    }
    return client, model_json


def analysis_time_record() -> dict:
    """--analysis-time: wall time of the full-package invariant checker
    (the tier-1 analysis gate): AST lint — per-function rules AND the
    whole-program concurrency audit (LWC014-016: lock registry, guarded
    fields, lock-order DAG, blocking under lock) — budgeted within the
    original 30 s with the jaxpr audit, plus the simulated-mesh
    sharding/resource audit with its own 60 s budget.  The AST lint
    runs in-process (stdlib only); the jaxpr and mesh audits run in
    subprocesses so this process keeps its device-free / no-jax
    guarantee."""
    import subprocess

    from llm_weighted_consensus_tpu.analysis import (
        apply_baseline,
        load_baseline,
        run_lint,
    )
    from llm_weighted_consensus_tpu.analysis.rules import ALL_RULES

    conc_names = {"LWC014", "LWC015", "LWC016"}
    t0 = time.perf_counter()
    findings = run_lint(
        rules=[r for r in ALL_RULES if r.name not in conc_names]
    )
    lint_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    findings += run_lint(
        rules=[r for r in ALL_RULES if r.name in conc_names]
    )
    concurrency_s = time.perf_counter() - t0
    kept, _suppressed, stale = apply_baseline(findings, load_baseline())

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    t0 = time.perf_counter()
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys\n"
            "from llm_weighted_consensus_tpu.analysis.jaxpr_audit import "
            "run_jaxpr_audit\n"
            "sys.exit(1 if run_jaxpr_audit() else 0)",
        ],
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    jaxpr_s = time.perf_counter() - t0

    # run_mesh_audit self-respawns with the 8-virtual-device env; calling
    # it via -c (not in-process) keeps this bench jax-free either way
    mesh_budget_s = 60
    t0 = time.perf_counter()
    mesh_proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import sys\n"
            "from llm_weighted_consensus_tpu.analysis.mesh_audit import "
            "run_mesh_audit\n"
            "sys.exit(1 if run_mesh_audit() else 0)",
        ],
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        timeout=mesh_budget_s * 2,
    )
    mesh_s = time.perf_counter() - t0

    total_s = lint_s + concurrency_s + jaxpr_s + mesh_s
    return {
        "metric": (
            "full-package analysis wall time "
            "(AST lint + concurrency audit + jaxpr audit + mesh audit)"
        ),
        "value": round(total_s, 3),
        "unit": "s",
        "lint_seconds": round(lint_s, 3),
        "concurrency_seconds": round(concurrency_s, 3),
        "jaxpr_seconds": round(jaxpr_s, 3),
        "mesh_seconds": round(mesh_s, 3),
        "lint_findings": len(kept),
        "stale_baseline": len(stale),
        "jaxpr_clean": proc.returncode == 0,
        "mesh_clean": mesh_proc.returncode == 0,
        "budget_seconds": 30,
        "within_budget": lint_s + concurrency_s + jaxpr_s < 30,
        "mesh_budget_seconds": mesh_budget_s,
        "mesh_within_budget": mesh_s < mesh_budget_s,
        "jax_imported": "jax" in sys.modules,
        "note": (
            "lint + concurrency audit in-process (stdlib ast only), "
            "jaxpr + mesh audits in JAX_PLATFORMS=cpu subprocesses so "
            "the host bench process stays jax-free"
        ),
    }


def metrics_overhead_record(args) -> dict:
    """--metrics-overhead: the cost of the phase-histogram observe()
    hot path (ISSUE 11 satellite), against the PR 5 discipline that
    always-on observability stays under a 2% p50 inflation budget.

    Two measurements, both device-free:

    1. ns/op of ``Histogram.observe`` alone and of the aggregator's
       lock-guarded ``observe_phase`` (the call the instrumentation
       sites actually make, from the event loop and executor threads).
    2. The real host consensus path driven with its instrumentation
       live (clients/score.py observes host_tally + upstream_judge per
       request), reading the aggregator's counters for observes/request.

    The reported overhead is the share of the host-path p50 spent
    inside observe calls — deterministic, unlike an A/B of two noisy
    p50s at the 1% scale the budget cares about."""
    from bench import BASELINE_BASIS, make_requests
    from llm_weighted_consensus_tpu.obs import phases as phases_mod
    from llm_weighted_consensus_tpu.obs.histogram import Histogram
    from llm_weighted_consensus_tpu.types.score_request import (
        ChatCompletionCreateParams as ScoreParams,
    )

    # -- 1. the raw increment, minus the loop's own cost ----------------------
    values = [0.05 * (1 + (i % 997)) for i in range(1000)]
    reps = 300_000

    def loop_ns(fn) -> float:
        t0 = time.perf_counter()
        for i in range(reps):
            fn(values[i % 1000])
        return (time.perf_counter() - t0) / reps * 1e9

    baseline_ns = loop_ns(lambda v: None)
    hist = Histogram()
    observe_ns = max(0.0, loop_ns(hist.observe) - baseline_ns)
    agg = phases_mod.PhaseAggregator()
    observe_phase_ns = max(
        0.0,
        loop_ns(lambda v: agg.observe_phase("host_tally", v)) - baseline_ns,
    )

    # -- 2. observes/request on the real host path ----------------------------
    n_requests = min(args.requests, 20)
    client, model_json = build_engine(
        args.judges, args.n, n_requests + 1, args.seed
    )
    texts_per_request = make_requests(n_requests, args.n, seed=args.seed)

    async def score_one(texts):
        params = ScoreParams.from_json_obj(
            {
                "messages": [{"role": "user", "content": "pick the best"}],
                "model": model_json,
                "choices": texts,
            }
        )
        stream = await client.create_streaming(None, params)
        return [item async for item in stream]

    loop = asyncio.new_event_loop()
    loop.run_until_complete(score_one(texts_per_request[0]))  # warm
    phases_mod.reset_phases()
    total_ms = []
    for texts in texts_per_request:
        t0 = time.perf_counter()
        loop.run_until_complete(score_one(texts))
        total_ms.append((time.perf_counter() - t0) * 1e3)
    loop.close()
    snap = phases_mod.phases_snapshot()
    observes = sum(
        row["count"] for row in snap.values() if isinstance(row, dict)
    )
    per_request = observes / max(1, n_requests)
    p50_ms = round(statistics.median(total_ms), 3)
    overhead_pct = round(
        per_request * observe_phase_ns / (p50_ms * 1e6) * 100.0, 4
    )
    budget_pct = 2.0
    record = {
        "metric": "phase-histogram observe() share of host-path p50",
        "value": overhead_pct,
        "unit": "%",
        "budget_pct": budget_pct,
        "within_budget": overhead_pct <= budget_pct,
        "observe_ns": round(observe_ns, 1),
        "observe_phase_ns": round(observe_phase_ns, 1),
        "observes_per_request": round(per_request, 2),
        "host_p50_ms": p50_ms,
        "requests": n_requests,
        "judges": args.judges,
        "n_candidates": args.n,
        "jax_imported": "jax" in sys.modules,
        "baseline_basis": BASELINE_BASIS,
        "note": (
            "overhead = observes/request x lock-guarded observe ns / "
            "host p50: the deterministic form of the <=2% p50 inflation "
            "bar (an A/B of two p50s is noise at this scale); observe "
            "sites: clients/score.py host_tally + upstream_judge"
        ),
    }
    return record


def quality_overhead_record(args) -> dict:
    """--quality-overhead: the cost of the consensus-quality observe
    hot path (ISSUE 12 satellite), against the same discipline as
    --metrics-overhead: always-on observability stays under a 2% p50
    inflation budget.

    Same deterministic form, both measurements device-free:

    1. ns/op of the lock-guarded ``QualityAggregator.observe_outcome``
       on a synthetic panel-shaped outcome (args.judges ballots over
       args.n candidates — the worst realistic shape: every judge
       voted, so calibration bins, the drift window, and all pairwise
       kappa cells update).
    2. The real host consensus path driven with the tally-seam
       observation live (clients/score.py emits exactly one Outcome
       per scored request), for the p50 denominator.

    The reported overhead is the share of the host-path p50 spent
    inside observe_outcome."""
    from decimal import Decimal

    from bench import BASELINE_BASIS, make_requests
    from llm_weighted_consensus_tpu.obs import quality as quality_mod
    from llm_weighted_consensus_tpu.types.score_request import (
        ChatCompletionCreateParams as ScoreParams,
    )

    # -- 1. ns/op on the panel-shaped outcome, minus the loop's own cost ------
    n = max(2, args.n)
    judges = max(2, args.judges)
    ballots = []
    for j in range(judges):
        # distinct per-judge vote mass so argmax, bins, and kappa
        # marginals all exercise their real branches (float votes,
        # exactly as the seam hands them over)
        top = (j * 7) % n
        rest = 0.4 / (n - 1)
        vote = [rest] * n
        vote[top] = 0.6
        ballots.append(
            quality_mod.JudgeBallot(
                model=f"bench-judge-{j}",
                model_index=j,
                weight=Decimal(1),
                vote=vote,
            )
        )
    outcome = quality_mod.Outcome(
        winner=0,
        margin=0.25,
        weight_sum=Decimal(judges),
        n_choices=n,
        degraded=False,
        quorum_degraded=False,
        all_failed=False,
        trace_id="bench-trace",
        judges=ballots,
    )
    reps = 50_000

    def loop_ns(fn) -> float:
        t0 = time.perf_counter()
        for _ in range(reps):
            fn(outcome)
        return (time.perf_counter() - t0) / reps * 1e9

    baseline_ns = loop_ns(lambda o: None)
    agg = quality_mod.QualityAggregator()
    observe_outcome_ns = max(0.0, loop_ns(agg.observe_outcome) - baseline_ns)

    # -- 2. host-path p50 with the tally-seam observation live ----------------
    n_requests = min(args.requests, 20)
    client, model_json = build_engine(
        args.judges, args.n, n_requests + 1, args.seed
    )
    texts_per_request = make_requests(n_requests, args.n, seed=args.seed)

    async def score_one(texts):
        params = ScoreParams.from_json_obj(
            {
                "messages": [{"role": "user", "content": "pick the best"}],
                "model": model_json,
                "choices": texts,
            }
        )
        stream = await client.create_streaming(None, params)
        return [item async for item in stream]

    loop = asyncio.new_event_loop()
    loop.run_until_complete(score_one(texts_per_request[0]))  # warm
    quality_mod.reset_quality()
    total_ms = []
    for texts in texts_per_request:
        t0 = time.perf_counter()
        loop.run_until_complete(score_one(texts))
        total_ms.append((time.perf_counter() - t0) * 1e3)
    loop.close()
    observed = quality_mod.quality_snapshot()["requests"]
    p50_ms = round(statistics.median(total_ms), 3)
    overhead_pct = round(observe_outcome_ns / (p50_ms * 1e6) * 100.0, 4)
    budget_pct = 2.0
    return {
        "metric": "quality observe_outcome share of host-path p50",
        "value": overhead_pct,
        "unit": "%",
        "budget_pct": budget_pct,
        "within_budget": overhead_pct <= budget_pct,
        "observe_outcome_ns": round(observe_outcome_ns, 1),
        "outcomes_per_request": round(observed / max(1, n_requests), 2),
        "host_p50_ms": p50_ms,
        "requests": n_requests,
        "judges": args.judges,
        "n_candidates": args.n,
        "jax_imported": "jax" in sys.modules,
        "baseline_basis": BASELINE_BASIS,
        "note": (
            "overhead = lock-guarded observe_outcome ns / host p50 "
            "(exactly one outcome per scored request): the "
            "deterministic form of the <=2% p50 inflation bar; observe "
            "site: clients/score.py tally seam"
        ),
    }


def overlap_overhead_record(args) -> dict:
    """--overlap-overhead: the pure-Python bookkeeping cost of the
    deferred-readiness dispatch seam (ISSUE 13 tentpole), against the
    same discipline as --metrics-overhead: the waiter/pool machinery
    must stay under a 2% share of the host-path p50.

    Two measurements, both device-free (models/dispatch_seam.py is
    jax-free at import; the no-op ``wait`` below keeps it that way):

    1. ns per seam cycle: DispatchSink + deferred_readiness scope +
       one PendingDispatch append + drain_sink with a no-op waiter —
       everything the two-hop pipeline adds over the old inline
       bracket except the actual device wait (which overlaps useful
       work by design and is not host overhead).
    2. ns per staging cycle: StagingPool acquire + release of a warm
       serving-shaped (n, seq) int32 buffer — the per-dispatch cost of
       host buffer reuse (2 cycles/request: ids + mask).
    3. The real host consensus path for the p50 denominator.

    The reported overhead is the share of the host-path p50 spent in
    seam + staging bookkeeping per request (one dispatch group per
    request — the worst case: no batching amortization)."""
    from bench import BASELINE_BASIS, make_requests
    from llm_weighted_consensus_tpu.types.score_request import (
        ChatCompletionCreateParams as ScoreParams,
    )

    import numpy as np

    # standalone load (models/__init__ imports the jax encoders; the
    # seam module itself is jax-free at import by contract)
    here = os.path.dirname(os.path.abspath(__file__))
    spec = importlib.util.spec_from_file_location(
        "_lwc_host_dispatch_seam",
        os.path.join(
            here, "llm_weighted_consensus_tpu", "models", "dispatch_seam.py"
        ),
    )
    seam = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(seam)

    # -- 1. seam cycle ns, minus the loop's own cost --------------------------
    reps = 200_000

    def loop_ns(fn) -> float:
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps * 1e9

    noop_wait = lambda out: None  # noqa: E731

    def seam_cycle():
        sink = seam.DispatchSink()
        with seam.deferred_readiness(sink):
            sink.add(
                seam.PendingDispatch(
                    "bench(b=1)", time.perf_counter(), None, wait=noop_wait
                )
            )
        seam.drain_sink(
            sink,
            observe_device=lambda label, ms: None,
            observe_interval=lambda s, e: None,
        )

    baseline_ns = loop_ns(lambda: None)
    seam_cycle_ns = max(0.0, loop_ns(seam_cycle) - baseline_ns)

    pool = seam.StagingPool(per_bucket=2)
    shape = (max(1, args.n), args.seq)
    pool.release(pool.acquire(shape, np.int32))  # warm: hit path

    def staging_cycle():
        pool.release(pool.acquire(shape, np.int32))

    staging_cycle_ns = max(0.0, loop_ns(staging_cycle) - baseline_ns)

    # -- 2. host-path p50 denominator -----------------------------------------
    n_requests = min(args.requests, 20)
    client, model_json = build_engine(
        args.judges, args.n, n_requests + 1, args.seed
    )
    texts_per_request = make_requests(n_requests, args.n, seed=args.seed)

    async def score_one(texts):
        params = ScoreParams.from_json_obj(
            {
                "messages": [{"role": "user", "content": "pick the best"}],
                "model": model_json,
                "choices": texts,
            }
        )
        stream = await client.create_streaming(None, params)
        return [item async for item in stream]

    loop = asyncio.new_event_loop()
    loop.run_until_complete(score_one(texts_per_request[0]))  # warm
    total_ms = []
    for texts in texts_per_request:
        t0 = time.perf_counter()
        loop.run_until_complete(score_one(texts))
        total_ms.append((time.perf_counter() - t0) * 1e3)
    loop.close()
    p50_ms = round(statistics.median(total_ms), 3)
    # 1 dispatch group/request (worst case) = 1 seam cycle + 2 staging
    # cycles (ids + mask buffers)
    per_request_ns = seam_cycle_ns + 2 * staging_cycle_ns
    overhead_pct = round(per_request_ns / (p50_ms * 1e6) * 100.0, 4)
    budget_pct = 2.0
    return {
        "metric": "dispatch-seam bookkeeping share of host-path p50",
        "value": overhead_pct,
        "unit": "%",
        "budget_pct": budget_pct,
        "within_budget": overhead_pct <= budget_pct,
        "seam_cycle_ns": round(seam_cycle_ns, 1),
        "staging_cycle_ns": round(staging_cycle_ns, 1),
        "staging_pool": pool.stats(),
        "host_p50_ms": p50_ms,
        "requests": n_requests,
        "judges": args.judges,
        "n_candidates": args.n,
        "jax_imported": "jax" in sys.modules,
        "baseline_basis": BASELINE_BASIS,
        "note": (
            "overhead = (seam cycle + 2 staging cycles) ns / host p50 "
            "at 1 dispatch group/request: the deterministic form of "
            "the <=2% p50 inflation bar for the ISSUE 13 waiter/pool "
            "machinery; the device wait itself overlaps useful work "
            "and is excluded by design (no-op waiter)"
        ),
    }


def witness_overhead_record(args) -> dict:
    """--witness-overhead: the cost of LockWitness proxies on the
    registered locks (the analysis-v3 runtime lockdep, LOCK_WITNESS=1),
    against the same discipline as the other always-on observability:
    under a 2% share of the host-path p50 when enabled.

    Two measurements, both device-free:

    1. ns of a wrapped ``with lock:`` cycle minus a raw one — the
       witness's true marginal cost per acquisition (threading.local
       stack push/pop + the guarded edge/count update);
    2. the real host consensus path with the witness wrapping the
       phase aggregator's lock — the hottest registered lock on the
       host path — counting REAL acquisitions per request from the
       witness's own ledger for the numerator.

    The reported overhead is acquisitions/request x marginal ns /
    host p50 — deterministic, like --metrics-overhead, instead of an
    A/B of two noisy p50s at the fractions of a percent in play."""
    import threading

    from bench import BASELINE_BASIS, make_requests
    from llm_weighted_consensus_tpu.analysis.witness import LockWitness
    from llm_weighted_consensus_tpu.obs import phases as phases_mod
    from llm_weighted_consensus_tpu.types.score_request import (
        ChatCompletionCreateParams as ScoreParams,
    )

    # -- 1. marginal ns per wrapped acquisition -------------------------------
    reps = 200_000

    def loop_ns(fn) -> float:
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps * 1e9

    witness = LockWitness()
    raw = threading.Lock()
    proxy = witness.wrap_lock("PhaseAggregator._lock", threading.Lock())

    def raw_cycle():
        with raw:
            pass

    def wrapped_cycle():
        with proxy:
            pass

    baseline_ns = loop_ns(lambda: None)
    raw_ns = max(0.0, loop_ns(raw_cycle) - baseline_ns)
    wrapped_ns = max(0.0, loop_ns(wrapped_cycle) - baseline_ns)
    witness_ns = max(0.0, wrapped_ns - raw_ns)

    # -- 2. real acquisitions/request + host-path p50 -------------------------
    n_requests = min(args.requests, 20)
    client, model_json = build_engine(
        args.judges, args.n, n_requests + 1, args.seed
    )
    texts_per_request = make_requests(n_requests, args.n, seed=args.seed)

    live = LockWitness()
    agg = phases_mod._AGG
    agg._lock = live.wrap_lock("PhaseAggregator._lock", agg._lock)

    async def score_one(texts):
        params = ScoreParams.from_json_obj(
            {
                "messages": [{"role": "user", "content": "pick the best"}],
                "model": model_json,
                "choices": texts,
            }
        )
        stream = await client.create_streaming(None, params)
        return [item async for item in stream]

    loop = asyncio.new_event_loop()
    loop.run_until_complete(score_one(texts_per_request[0]))  # warm
    before = live.snapshot()["acquisitions"]
    total_ms = []
    for texts in texts_per_request:
        t0 = time.perf_counter()
        loop.run_until_complete(score_one(texts))
        total_ms.append((time.perf_counter() - t0) * 1e3)
    loop.close()
    snap = live.snapshot()
    agg._lock = agg._lock._lock  # unwrap: leave the aggregator pristine
    per_request = (snap["acquisitions"] - before) / max(1, n_requests)
    p50_ms = round(statistics.median(total_ms), 3)
    overhead_pct = round(
        per_request * witness_ns / (p50_ms * 1e6) * 100.0, 4
    )
    budget_pct = 2.0
    return {
        "metric": "lock-witness proxy share of host-path p50",
        "value": overhead_pct,
        "unit": "%",
        "budget_pct": budget_pct,
        "within_budget": overhead_pct <= budget_pct,
        "witness_ns": round(witness_ns, 1),
        "raw_lock_ns": round(raw_ns, 1),
        "wrapped_lock_ns": round(wrapped_ns, 1),
        "acquisitions_per_request": round(per_request, 2),
        "violations": len(snap["violations"]),
        "host_p50_ms": p50_ms,
        "requests": n_requests,
        "judges": args.judges,
        "n_candidates": args.n,
        "jax_imported": "jax" in sys.modules,
        "baseline_basis": BASELINE_BASIS,
        "note": (
            "overhead = acquisitions/request x marginal witness ns / "
            "host p50, acquisitions counted by the live witness on the "
            "phase aggregator's lock: the deterministic form of the "
            "<=2% p50 inflation bar for LOCK_WITNESS=1"
        ),
    }


def ingest_bounds_record(args) -> dict:
    """--ingest-bounds: the per-chunk cost of the SSE byte-budget
    accounting (ISSUE 19 ingest plane), against the same 2% p50
    inflation discipline as every always-on hot-path feature.

    Two measurements, both device-free:

    1. ns/frame of the SSE parser over a realistic judge-stream frame
       sequence, uncapped vs capped at the serving defaults
       (``SSE_MAX_EVENT_BYTES``).  The capped delta is the whole cost
       of the budget plane on the happy path: one size accumulation and
       one compare per data line, one residue check per newline-less
       feed.
    2. Upstream frames/request on the real host path (J judges x
       frames/judge), reading the host-path p50 the same engine pays.

    Reported overhead = frames/request x capped-delta ns / host p50 —
    deterministic, like --metrics-overhead, instead of an A/B of two
    noisy end-to-end p50s at the sub-1% scale."""
    from bench import BASELINE_BASIS, make_requests
    from llm_weighted_consensus_tpu.clients.sse import SSEParser
    from llm_weighted_consensus_tpu.types.score_request import (
        ChatCompletionCreateParams as ScoreParams,
    )

    # -- 1. parser ns/frame, uncapped vs serving-default caps -----------------
    # frame shapes the judge streams actually carry: a delta chunk, a
    # finish chunk, a [DONE] terminator (fakes.py sse_frames shape)
    payload = json.dumps(
        {
            "id": "bench",
            "choices": [
                {
                    "index": 0,
                    "delta": {"content": "I pick a candidate key"},
                }
            ],
        }
    ).encode()
    frames = [b"data: " + payload + b"\n\n"] * 2 + [b"data: [DONE]\n\n"]
    reps = 30_000

    def parse_ns(make_parser) -> float:
        t0 = time.perf_counter()
        for _ in range(reps):
            parser = make_parser()
            for frame in frames:
                for _event in parser.feed(frame):
                    pass
            parser.flush()
        return (time.perf_counter() - t0) / (reps * len(frames)) * 1e9

    uncapped_ns = parse_ns(SSEParser)
    capped_ns = parse_ns(
        lambda: SSEParser(
            max_buffer_bytes=4 * 1024 * 1024,
            max_event_bytes=4 * 1024 * 1024,
        )
    )
    overhead_ns = max(0.0, capped_ns - uncapped_ns)

    # -- 2. frames/request and p50 on the real host path ----------------------
    n_requests = min(args.requests, 20)
    client, model_json = build_engine(
        args.judges, args.n, n_requests + 1, args.seed
    )
    texts_per_request = make_requests(n_requests, args.n, seed=args.seed)

    async def score_one(texts):
        params = ScoreParams.from_json_obj(
            {
                "messages": [{"role": "user", "content": "pick the best"}],
                "model": model_json,
                "choices": texts,
            }
        )
        stream = await client.create_streaming(None, params)
        return [item async for item in stream]

    loop = asyncio.new_event_loop()
    loop.run_until_complete(score_one(texts_per_request[0]))  # warm
    total_ms = []
    for texts in texts_per_request[1:]:
        t0 = time.perf_counter()
        loop.run_until_complete(score_one(texts))
        total_ms.append((time.perf_counter() - t0) * 1e3)
    loop.close()
    p50_ms = round(statistics.median(total_ms), 3)
    # every judge leg streams the scripted frame sequence; the byte
    # accounting runs once per upstream frame per leg
    frames_per_request = args.judges * len(frames)
    overhead_pct = round(
        frames_per_request * overhead_ns / (p50_ms * 1e6) * 100.0, 4
    )
    budget_pct = 2.0
    record = {
        "metric": "SSE byte-budget accounting share of host-path p50",
        "value": overhead_pct,
        "unit": "%",
        "budget_pct": budget_pct,
        "within_budget": overhead_pct <= budget_pct,
        "uncapped_ns_per_frame": round(uncapped_ns, 1),
        "capped_ns_per_frame": round(capped_ns, 1),
        "overhead_ns_per_frame": round(overhead_ns, 1),
        "frames_per_request": frames_per_request,
        "host_p50_ms": p50_ms,
        "requests": n_requests,
        "judges": args.judges,
        "n_candidates": args.n,
        "jax_imported": "jax" in sys.modules,
        "baseline_basis": BASELINE_BASIS,
        "note": (
            "overhead = upstream frames/request x (capped - uncapped) "
            "parser ns/frame / host p50: the ingest byte budgets "
            "(SSE_MAX_EVENT_BYTES residue + event accounting, "
            "clients/sse.py) must stay effectively free on the happy "
            "path — trips are the exceptional path and priced "
            "separately in tests/test_hostile_ingest.py"
        ),
    }
    return record


def _host_speed_canary(reps: int = 2000) -> float:
    """Median per-rep cost (µs) of a FIXED pure-python workload — the
    machine-speed reference for the budget gate.  The mix is the same
    primitives the gated phases spend their time in (compact json
    encode/decode, a precompiled regex scan, dict/list churn), so host
    CPU throttling that slows the phases slows the canary by the same
    factor.  It touches none of the engine's code, so a code regression
    cannot hide inside it.  The default reps span ~1-2 s — long enough
    to integrate over the second-granularity throttle bursts this box
    exhibits instead of sampling one by luck."""
    import re as re_mod

    pat = re_mod.compile(r"\b(cand_[0-9]+)\b")
    text = " ".join(f"cand_{i} token{i}" for i in range(64))
    obj = {
        "choices": [
            {"index": i, "delta": {"content": f"tok {i}"}}
            for i in range(16)
        ]
    }

    # task-switch component: a bounded queue forces producer/consumer
    # alternation per item — the same call_soon hop merge_streams pays
    # per chunk, and the piece that degrades hardest under CPU steal
    # (the scheduler-sensitive phases inflate more than straight-line
    # code, so a pure-CPU canary undertracks them)
    async def _pump(n):
        q = asyncio.Queue(maxsize=1)

        async def producer():
            for i in range(n):
                await q.put(i)

        task = asyncio.ensure_future(producer())
        for _ in range(n):
            await q.get()
        await task

    loop = asyncio.new_event_loop()
    try:
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(8):
                blob = json.dumps(obj, separators=(",", ":"))
                json.loads(blob)
                pat.findall(text)
                sorted(
                    range(256), key=lambda v: (v * 2654435761) & 0xFFFF
                )
            loop.run_until_complete(_pump(8))
            samples.append((time.perf_counter() - t0) * 1e6)
    finally:
        loop.close()
    return statistics.median(samples)


def hostpath_record(args, write_budgets: bool = False) -> dict:
    """--hostpath: per-chunk host-path p50 per phase (ingest / merge /
    tally / encode), HOST_FASTPATH unset vs set, over REAL engine
    streams at J x N scripted judges.

    The per-chunk host path is what the serving loop pays per streamed
    frame: the merge hop that moves the chunk across judge streams, the
    ballot scan when a judge's final payload lands, and the wire encode
    of the merged frame.  Per-REQUEST phases (the weighted tally and
    final-frame build) are reported as their own p50 — they land in the
    stream's tail, not its steady state.  The headline is the per-chunk
    p50 ratio (slow lane / fast lane); the tier-1 gate checks the fast
    lane's phase p50s against the committed analysis/host_budgets.json
    band (>=25% regression on any phase fails)."""
    import re as re_mod

    from bench import BASELINE_BASIS, make_requests
    from llm_weighted_consensus_tpu.ballot import PrefixTree, branch_limit
    from llm_weighted_consensus_tpu.ballot.vote import extract_vote
    from llm_weighted_consensus_tpu.clients.score import merge_streams
    from llm_weighted_consensus_tpu.obs import phases as phases_mod
    from llm_weighted_consensus_tpu.serve import frames
    from llm_weighted_consensus_tpu.types.score_request import (
        ChatCompletionCreateParams as ScoreParams,
    )

    n_requests = min(args.requests, 20)
    texts_per_request = make_requests(n_requests + 1, args.n, seed=args.seed)

    # the judge ballot, replayed exactly as build_engine scripts it, for
    # the ingest-phase scan (each judge's final content carries one key)
    rng = random.Random(args.seed)
    tree = PrefixTree.build(rng, args.n, branch_limit(None))
    key_indices = tree.key_indices(rng)
    keys = [k for k, _ in key_indices]
    w_src, wo_src = PrefixTree.regex_patterns(keys)
    key_by_idx = {idx: k for k, idx in key_indices}
    vote_rng = random.Random(args.seed + 1)
    contents = [
        f"I pick {key_by_idx[vote_rng.randrange(3)]} as best."
        for _ in range(args.judges)
    ]

    def measure_lane(fastpath: bool) -> dict:
        client, model_json = build_engine(
            args.judges,
            args.n,
            n_requests + 1,
            args.seed,
            host_fastpath=fastpath,
        )

        async def score_one(texts):
            params = ScoreParams.from_json_obj(
                {
                    "messages": [
                        {"role": "user", "content": "pick the best"}
                    ],
                    "model": model_json,
                    "choices": texts,
                }
            )
            stream = await client.create_streaming(None, params)
            return [item async for item in stream]

        loop = asyncio.new_event_loop()
        # warmup + capture one REAL stream's chunks for the encode phase
        chunks = loop.run_until_complete(score_one(texts_per_request[0]))
        # tally: the engine's own host_tally phase (weighted fold +
        # final-frame build), one EXACT value per request — reset the
        # phase store around each request so its ``sum_ms`` (count=1)
        # is the raw observation, then take the median.  Reading the
        # aggregate histogram's p50 instead would quantize to the
        # log-spaced buckets, which step ~19-41% apiece: one bucket up
        # overshoots the whole 25% budget band while the true p50
        # moved a few percent.
        tally_samples = []
        for texts in texts_per_request[1:]:
            phases_mod.reset_phases()
            loop.run_until_complete(score_one(texts))
            row = phases_mod.phases_snapshot().get("host_tally") or {}
            tally_samples.append(row.get("sum_ms", 0.0))
        loop.close()
        tally_ms = (
            round(statistics.median(tally_samples), 3)
            if tally_samples
            else 0.0
        )

        # encode: FrameEncoder over the captured stream, per-frame p50
        # over reps (fresh encoder per rep = fresh splice cache, exactly
        # one stream's worth of state; median per frame + gc paused so
        # collector pauses don't smear into the phase figure)
        import gc

        reps = 120
        per_frame = [[] for _ in chunks]
        gc.disable()
        try:
            for _ in range(reps):
                enc = frames.FrameEncoder(fastpath)
                for i, item in enumerate(chunks):
                    t0 = time.perf_counter()
                    enc.encode(item)
                    per_frame[i].append(time.perf_counter() - t0)
                if fastpath:
                    assert enc.fallbacks == 0, (
                        f"fast lane fell back {enc.fallbacks}x "
                        f"on a real stream"
                    )
        finally:
            gc.enable()
        frame_us = [statistics.median(t) * 1e6 for t in per_frame]

        # ingest: one ballot scan per judge final payload, patterns held
        # the way the stream holds them (str -> re's cache per call on
        # the slow lane; a per-stream compiled object on the fast lane)
        if fastpath:
            pats = (re_mod.compile(w_src), re_mod.compile(wo_src))
        else:
            pats = (w_src, wo_src)
        ingest_samples = []
        for _ in range(300):
            t0 = time.perf_counter()
            for content in contents:
                extract_vote(tree, pats[0], pats[1], args.n, content, None)
            ingest_samples.append(
                (time.perf_counter() - t0) * 1e6 / args.judges
            )
        ingest_us = statistics.median(ingest_samples)

        # merge: one queue hop per chunk through merge_streams over J
        # scripted judge streams (lane-independent by design — the
        # single-pending-set merge is unconditional; measured per lane
        # anyway so a regression on either lane shows)
        per_judge = max(1, (len(chunks) - 2) // args.judges + 1)

        async def one_judge():
            for i in range(per_judge):
                yield i

        async def drain():
            t0 = time.perf_counter()
            n_items = 0
            async for _ in merge_streams(
                [one_judge() for _ in range(args.judges)]
            ):
                n_items += 1
            return (time.perf_counter() - t0) * 1e6 / n_items

        loop = asyncio.new_event_loop()
        merge_samples = [
            loop.run_until_complete(drain()) for _ in range(120)
        ]
        loop.close()
        merge_us = statistics.median(merge_samples)

        # per-chunk host path: merge hop + encode for every frame, plus
        # the ballot scan on the frames that deliver a judge's final
        # payload (the last per_judge-th frames before the aggregate)
        per_chunk = []
        n_frames = len(chunks)
        for i, enc_us in enumerate(frame_us):
            cost = merge_us + enc_us
            if n_frames - 1 - args.judges <= i < n_frames - 1:
                cost += ingest_us
            per_chunk.append(cost)
        per_chunk_p50 = statistics.median(per_chunk)

        return {
            "per_chunk_p50_us": round(per_chunk_p50, 2),
            "ingest_p50_us": round(ingest_us, 2),
            "merge_p50_us": round(merge_us, 2),
            "tally_p50_ms": tally_ms,
            "encode_p50_us": round(statistics.median(frame_us), 2),
            "encode_stream_total_us": round(sum(frame_us), 1),
            "frames_per_stream": n_frames,
        }

    # machine-speed canary, sampled BEFORE, BETWEEN, and AFTER the
    # ~60 s of lane measurement: the gate scales by the slowest sample
    # (the most-throttled view of the window the phases were measured
    # in — throttle bursts last seconds, so end-points alone can miss
    # a mid-run burst); --write-budgets records the fastest (the
    # healthy-floor baseline)
    canary_pre = _host_speed_canary()
    slow = measure_lane(False)
    canary_mid = _host_speed_canary()
    fast = measure_lane(True)
    ratio = round(
        slow["per_chunk_p50_us"] / fast["per_chunk_p50_us"], 2
    )

    # /v1/embeddings response assembly (models/embedder.py
    # wire_response): per-element float(v) before, one bulk tolist()
    # now — values identical (tolist applies the same item() widening)
    import numpy as np

    emb = np.arange(args.n * 768, dtype=np.float32).reshape(args.n, 768)
    emb = (emb % 97) / 97.0

    def _t(fn, reps=30):
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            samples.append((time.perf_counter() - t0) * 1e3)
        return statistics.median(samples)

    before_ms = _t(lambda: [[float(v) for v in row] for row in emb])
    after_ms = _t(lambda: np.asarray(emb).tolist())
    assert [[float(v) for v in row] for row in emb] == np.asarray(
        emb
    ).tolist(), "bulk tolist must be value-identical to per-element float()"
    embed_assembly = {
        "shape": f"{args.n}x768 f32",
        "before_per_element_float_ms": round(before_ms, 3),
        "after_bulk_tolist_ms": round(after_ms, 3),
        "speedup": round(before_ms / after_ms, 1),
    }

    budgets_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "llm_weighted_consensus_tpu",
        "analysis",
        "host_budgets.json",
    )
    gated_phases = (
        "per_chunk_p50_us",
        "ingest_p50_us",
        "merge_p50_us",
        "tally_p50_ms",
        "encode_p50_us",
    )
    if write_budgets:
        budgets = {
            "band": 1.25,
            "judges": args.judges,
            "n_candidates": args.n,
            "note": (
                "fast-lane (HOST_FASTPATH=1) host-path p50 budgets from "
                "bench_host.py --hostpath --write-budgets; tier-1 fails "
                "when a measured phase p50 exceeds budget x band "
                "(a >=25% host-path regression).  Re-baseline by "
                "re-running --write-budgets and committing the diff "
                "(DESIGN.md 'Host fast path')."
            ),
            "phases": {k: fast[k] for k in gated_phases},
            "canary_us": round(
                min(canary_pre, canary_mid, _host_speed_canary()), 2
            ),
        }
        with open(budgets_path, "w") as fh:
            json.dump(budgets, fh, indent=2, sort_keys=True)
            fh.write("\n")
        within_budget = True
        budget_detail = {"written": budgets_path}
        machine_scale = 1.0
        canary_us = budgets["canary_us"]
    else:
        with open(budgets_path) as fh:
            budgets = json.load(fh)
        band = budgets["band"]
        # machine-speed scaling: shared-host CPU throttling swings this
        # box well past the 1.25 band (observed ~1.4x for minutes at a
        # stretch), which fails EVERY phase at once with no code change.
        # Re-measure the fixed canary workload now and widen the limits
        # by the same global slowdown (capped, never narrowed): a true
        # host-path regression inflates its phase WITHOUT moving the
        # canary, so phase-relative regressions still trip.
        canary_us = max(canary_pre, canary_mid, _host_speed_canary())
        baseline_canary = budgets.get("canary_us")
        if baseline_canary:
            machine_scale = min(2.0, max(1.0, canary_us / baseline_canary))
        else:
            machine_scale = 1.0
        budget_detail = {}
        within_budget = True
        for k in gated_phases:
            limit = budgets["phases"][k] * band * machine_scale
            ok = fast[k] <= limit
            budget_detail[k] = {
                "measured": fast[k],
                "limit": round(limit, 2),
                "ok": ok,
            }
            within_budget = within_budget and ok

    record = {
        "metric": (
            f"host-path per-chunk p50 ratio (HOST_FASTPATH unset / set), "
            f"{args.judges} judges x N={args.n}"
        ),
        "value": ratio,
        "unit": "x",
        "min_ratio": 2.0,
        "meets_min_ratio": ratio >= 2.0,
        "within_budget": within_budget,
        "budget_band": budgets["band"],
        "budget_detail": budget_detail,
        "canary_us": round(canary_us, 2),
        "machine_scale": round(machine_scale, 3),
        "slow_lane": slow,
        "fast_lane": fast,
        "embed_assembly": embed_assembly,
        "requests": n_requests,
        "judges": args.judges,
        "n_candidates": args.n,
        "jax_imported": "jax" in sys.modules,
        "baseline_basis": BASELINE_BASIS,
        "note": (
            "per-chunk host path = merge hop + frame encode per streamed "
            "frame (+ ballot scan on judge-final frames), p50 over one "
            "REAL stream's frames; tally (weighted fold + final-frame "
            "build) is per-request and reported separately.  Encode is "
            "splice serialization (types/base.py) vs full to_json_obj + "
            "dumps; byte identity across lanes is pinned in "
            "tests/test_host_fastpath.py.  The budget gate bands the "
            "fast lane only — the slow lane is the baseline being "
            "escaped, not a budget."
        ),
    }
    return record


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--judges", type=int, default=8)
    ap.add_argument("--n", type=int, default=64, help="candidates/request")
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--requests", type=int, default=50)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument(
        "--analysis-time",
        action="store_true",
        help="measure the tier-1 analysis gate instead of the host path",
    )
    ap.add_argument(
        "--hostpath",
        action="store_true",
        help=(
            "measure per-chunk host-path phase p50s (ingest/merge/tally/"
            "encode) for HOST_FASTPATH unset vs set against the "
            "committed analysis/host_budgets.json band"
        ),
    )
    ap.add_argument(
        "--write-budgets",
        action="store_true",
        help=(
            "with --hostpath: re-baseline analysis/host_budgets.json "
            "from this run's fast-lane p50s instead of checking the band"
        ),
    )
    ap.add_argument(
        "--metrics-overhead",
        action="store_true",
        help=(
            "measure the phase-histogram observe() hot path against the "
            "2%% p50 inflation budget instead of the host path"
        ),
    )
    ap.add_argument(
        "--quality-overhead",
        action="store_true",
        help=(
            "measure the consensus-quality observe_outcome hot path "
            "against the 2%% p50 inflation budget instead of the host path"
        ),
    )
    ap.add_argument(
        "--overlap-overhead",
        action="store_true",
        help=(
            "measure the deferred-readiness seam + staging-pool "
            "bookkeeping against the 2%% p50 inflation budget instead "
            "of the host path"
        ),
    )
    ap.add_argument(
        "--witness-overhead",
        action="store_true",
        help=(
            "measure the LOCK_WITNESS=1 proxy cost on the registered "
            "locks against the 2%% p50 inflation budget instead of the "
            "host path"
        ),
    )
    ap.add_argument(
        "--ingest-bounds",
        action="store_true",
        help=(
            "measure the SSE byte-budget accounting (capped vs uncapped "
            "parser) against the 2%% p50 inflation budget instead of "
            "the host path"
        ),
    )
    args = ap.parse_args()

    if args.ingest_bounds:
        record = ingest_bounds_record(args)
        assert record["jax_imported"] is False, (
            "host bench must stay device-free"
        )
        print(json.dumps(record), flush=True)
        assert record["within_budget"], (
            f"ingest byte accounting costs {record['value']}% of host "
            f"p50, budget {record['budget_pct']}%"
        )
        return

    if args.hostpath:
        record = hostpath_record(args, write_budgets=args.write_budgets)
        assert record["jax_imported"] is False, (
            "host bench must stay device-free"
        )
        print(json.dumps(record), flush=True)
        assert record["within_budget"], (
            f"fast-lane host-path p50 regressed >= "
            f"{round((record['budget_band'] - 1) * 100)}% past the "
            f"committed budget: {record['budget_detail']}"
        )
        return

    if args.witness_overhead:
        record = witness_overhead_record(args)
        assert record["jax_imported"] is False, (
            "host bench must stay device-free"
        )
        print(json.dumps(record), flush=True)
        assert record["within_budget"], (
            f"lock-witness proxies cost {record['value']}% of host p50, "
            f"budget {record['budget_pct']}%"
        )
        return

    if args.overlap_overhead:
        record = overlap_overhead_record(args)
        assert record["jax_imported"] is False, (
            "host bench must stay device-free"
        )
        print(json.dumps(record), flush=True)
        assert record["within_budget"], (
            f"dispatch-seam bookkeeping costs {record['value']}% of host "
            f"p50, budget {record['budget_pct']}%"
        )
        return

    if args.quality_overhead:
        record = quality_overhead_record(args)
        assert record["jax_imported"] is False, (
            "host bench must stay device-free"
        )
        print(json.dumps(record), flush=True)
        assert record["within_budget"], (
            f"observe_outcome costs {record['value']}% of host p50, "
            f"budget {record['budget_pct']}%"
        )
        return

    if args.metrics_overhead:
        record = metrics_overhead_record(args)
        assert record["jax_imported"] is False, (
            "host bench must stay device-free"
        )
        print(json.dumps(record), flush=True)
        assert record["within_budget"], (
            f"observe() hot path costs {record['value']}% of host p50, "
            f"budget {record['budget_pct']}%"
        )
        return

    if args.analysis_time:
        record = analysis_time_record()
        assert record["jax_imported"] is False, (
            "host bench must stay device-free"
        )
        print(json.dumps(record), flush=True)
        assert record["within_budget"], (
            f"lint {record['lint_seconds']}s + concurrency "
            f"{record['concurrency_seconds']}s + jaxpr "
            f"{record['jaxpr_seconds']}s blew the "
            f"{record['budget_seconds']}s budget"
        )
        assert record["mesh_within_budget"], (
            f"mesh audit took {record['mesh_seconds']}s, budget "
            f"{record['mesh_budget_seconds']}s"
        )
        return

    from bench import BASELINE_BASIS, make_requests
    from llm_weighted_consensus_tpu.types.score_request import (
        ChatCompletionCreateParams as ScoreParams,
    )

    tok = host_tokenizer()
    client, model_json = build_engine(
        args.judges, args.n, args.requests, args.seed
    )
    texts_per_request = make_requests(args.requests, args.n, seed=args.seed)

    async def score_one(texts):
        params = ScoreParams.from_json_obj(
            {
                "messages": [{"role": "user", "content": "pick the best"}],
                "model": model_json,
                "choices": texts,
            }
        )
        stream = await client.create_streaming(None, params)
        return [item async for item in stream]

    loop = asyncio.new_event_loop()
    tokenize_ms, score_ms, total_ms = [], [], []
    chunks_seen = 0
    # warmup: first call pays lazy imports / codepath warm
    loop.run_until_complete(score_one(texts_per_request[0][: args.n]))
    # re-arm scripts consumed by warmup
    client, model_json = build_engine(
        args.judges, args.n, args.requests, args.seed
    )
    for texts in texts_per_request:
        t0 = time.perf_counter()
        ids, mask = tok.encode_batch(texts, args.seq)
        t1 = time.perf_counter()
        items = loop.run_until_complete(score_one(texts))
        t2 = time.perf_counter()
        assert ids.shape == (args.n, args.seq) and mask.shape == ids.shape
        chunks_seen += len(items)
        tokenize_ms.append((t1 - t0) * 1e3)
        score_ms.append((t2 - t1) * 1e3)
        total_ms.append((t2 - t0) * 1e3)
    loop.close()

    def pct(xs, q):
        return round(
            statistics.quantiles(xs, n=100)[q - 1] if len(xs) >= 2 else xs[0],
            3,
        )

    record = {
        "metric": (
            f"host path ms/request (tokenize + ballot + merge), "
            f"{args.judges} judges x N={args.n}"
        ),
        "value": pct(total_ms, 50),
        "unit": "ms",
        "p50_ms": pct(total_ms, 50),
        "p99_ms": pct(total_ms, 99),
        "breakdown": {
            "tokenize_p50_ms": pct(tokenize_ms, 50),
            "score_engine_p50_ms": pct(score_ms, 50),
        },
        "requests": args.requests,
        "judges": args.judges,
        "n_candidates": args.n,
        "seq": args.seq,
        "stream_chunks_per_request": chunks_seen / max(1, args.requests),
        "jax_imported": "jax" in sys.modules,
        "baseline_basis": BASELINE_BASIS,
        "note": (
            "real host path (WordPiece encode_batch, seeded PrefixTree "
            "ballot, ScoreClient stream merge + weighted tally) over "
            "scripted in-memory judges; no device, no network, no jax"
        ),
    }
    assert record["jax_imported"] is False, "host bench must stay device-free"
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
