"""llm-weighted-consensus-tpu: TPU-native weighted consensus over LLM panels.

A brand-new framework with the capabilities of ObjectiveAI/llm-weighted-consensus
(the Rust reference surveyed in SURVEY.md), rebuilt TPU-first:

* ``types``    — pure wire-type core + streaming merge algebra
* ``identity`` — judge/panel canonicalization, validation, content-addressed ids
* ``ballot``   — randomized prefix-tree ballots + vote extraction
* ``clients``  — asyncio SSE chat client, consensus engine, multichat fan-out
* ``archive``  — completions archive (checkpoint/resume analog) + batch re-score
* ``weights``  — static / training-table weight resolution (TPU embedding path)
* ``models``   — on-TPU encoders (BGE-class BERT, DeBERTa reward model)
* ``ops``      — JAX/Pallas consensus kernels (cosine vote, tally, top-k)
* ``parallel`` — device mesh, shardings, collectives, batch pmap
* ``serve``    — SSE HTTP gateway + env config
* ``train``    — trained-weight / encoder training steps

Pure-core modules import no IO or JAX; device modules import JAX lazily.
"""

__version__ = "0.1.0"

from . import errors, types, utils  # noqa: F401
