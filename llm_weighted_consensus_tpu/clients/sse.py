"""Incremental server-sent-events decoder.

Parses a raw byte stream into SSE ``data:`` payloads.  This is hot loop #1
of the serving path (SURVEY §3.5): per-token work on every judge stream.
The pure-Python implementation here has a C++ twin in ``native/`` (same
frame semantics, used when the extension is built); both are exercised by
tests/test_sse.py.

Frame semantics (the subset OpenAI-compatible providers emit, matching what
reqwest-eventsource accepts in the reference — chat client.rs:334-434):
``data:`` field lines accumulate per event (joined by newline), events end at
a blank line, ``:`` comment lines and other fields (``event:``/``id:``/
``retry:``) are ignored, and both LF and CRLF line endings are accepted.
"""

from __future__ import annotations

from typing import Iterator, Optional


class SSEParser:
    """Push bytes in, pull decoded event data strings out."""

    def __init__(self) -> None:
        self._buffer = bytearray()
        self._data_lines: list = []

    def feed(self, data: bytes) -> Iterator[str]:
        """Consume a chunk of bytes; yield completed event payloads."""
        self._buffer.extend(data)
        while True:
            nl = self._buffer.find(b"\n")
            if nl < 0:
                return
            line = bytes(self._buffer[:nl])
            del self._buffer[: nl + 1]
            if line.endswith(b"\r"):
                line = line[:-1]
            event = self._feed_line(line)
            if event is not None:
                yield event

    def _feed_line(self, line: bytes) -> Optional[str]:
        if not line:
            # dispatch event
            if self._data_lines:
                event = "\n".join(self._data_lines)
                self._data_lines = []
                return event
            return None
        if line.startswith(b":"):
            return None  # comment
        field, _, value = line.partition(b":")
        if value.startswith(b" "):
            value = value[1:]
        if field == b"data":
            self._data_lines.append(value.decode("utf-8", errors="replace"))
        # other fields (event/id/retry) are ignored
        return None

    def flush(self) -> Optional[str]:
        """End-of-stream: dispatch any trailing un-terminated event."""
        if self._data_lines:
            event = "\n".join(self._data_lines)
            self._data_lines = []
            return event
        return None
