"""Incremental server-sent-events decoder.

Parses a raw byte stream into SSE ``data:`` payloads.  This is hot loop #1
of the serving path (SURVEY §3.5): per-token work on every judge stream.
The pure-Python ``SSEParser`` has a C++ twin (``native/sse_parser.cpp``,
loaded through ctypes as ``NativeSSEParser``); ``make_parser`` picks the
native one when the shared library builds/loads, falling back silently
otherwise.  Both are run over one corpus by tests/test_native.py (split
feeds, CRLF, comments, flush).  Set ``LWC_NATIVE_SSE=0`` to force the
Python parser.

Frame semantics (the subset OpenAI-compatible providers emit, matching what
reqwest-eventsource accepts in the reference — chat client.rs:334-434):
``data:`` field lines accumulate per event (joined by newline), events end at
a blank line, ``:`` comment lines and other fields (``event:``/``id:``/
``retry:``) are ignored, and both LF and CRLF line endings are accepted.

Byte budgets (ISSUE 19 ingest plane): both parsers accept a
``max_buffer_bytes`` cap on the newline-less residue and a
``max_event_bytes`` cap on one event's accumulated ``data:`` payload
(value bytes plus joining newlines).  A hostile upstream streaming a
newline-less flood or one giant line trips a typed
:class:`~..errors.IngestCapError` instead of growing the buffer without
bound.  Trip semantics are part of the Python/native parity contract
(tests/test_native.py): events completed before the offending line still
surface, the oversized state is dropped (buffer/open event cleared), and
the parser stays usable for subsequent feeds.  ``0`` disables a cap.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional

from ..errors import IngestCapError


class SSEParser:
    """Push bytes in, pull decoded event data strings out."""

    def __init__(
        self, max_buffer_bytes: int = 0, max_event_bytes: int = 0
    ) -> None:
        self._buffer = bytearray()
        self._data_lines: list = []
        # accumulated byte size of the open event (value bytes + joining
        # newlines) — the quantity max_event_bytes caps.  Counted on the
        # raw bytes, pre-decode, so the native twin trips on the exact
        # same boundary.
        self._event_bytes = 0
        self.max_buffer_bytes = int(max_buffer_bytes)
        self.max_event_bytes = int(max_event_bytes)
        self.cap_trips = 0
        # events dispatched over this parser's lifetime — read by the
        # chat client at stream end as a judge-span trace attribute
        self.events_parsed = 0

    def feed(self, data: bytes) -> Iterator[str]:
        """Consume a chunk of bytes; yield completed event payloads.

        Raises :class:`IngestCapError` (after yielding any events that
        completed first) when a byte budget trips."""
        self._buffer.extend(data)
        while True:
            nl = self._buffer.find(b"\n")
            if nl < 0:
                if (
                    self.max_buffer_bytes
                    and len(self._buffer) > self.max_buffer_bytes
                ):
                    observed = len(self._buffer)
                    # drop the oversized residue: the parser must stay
                    # bounded AND usable if the caller keeps feeding
                    self._buffer = bytearray()
                    self.cap_trips += 1
                    raise IngestCapError(
                        "sse_buffer", self.max_buffer_bytes, observed
                    )
                return
            line = bytes(self._buffer[:nl])
            del self._buffer[: nl + 1]
            if line.endswith(b"\r"):
                line = line[:-1]
            event = self._feed_line(line)
            if event is not None:
                yield event

    def _feed_line(self, line: bytes) -> Optional[str]:
        if not line:
            # dispatch event
            if self._data_lines:
                event = "\n".join(self._data_lines)
                self._data_lines = []
                self._event_bytes = 0
                self.events_parsed += 1
                return event
            return None
        if line.startswith(b":"):
            return None  # comment
        field, _, value = line.partition(b":")
        if value.startswith(b" "):
            value = value[1:]
        if field == b"data":
            grown = self._event_bytes + len(value) + (
                1 if self._data_lines else 0
            )
            if self.max_event_bytes and grown > self.max_event_bytes:
                # drop the oversized open event; the offending line is
                # already consumed, so parsing can resume cleanly
                self._data_lines = []
                self._event_bytes = 0
                self.cap_trips += 1
                raise IngestCapError(
                    "sse_event", self.max_event_bytes, grown
                )
            self._event_bytes = grown
            self._data_lines.append(value.decode("utf-8", errors="replace"))
        # other fields (event/id/retry) are ignored
        return None

    def flush(self) -> Optional[str]:
        """End-of-stream: the remaining buffered bytes count as a final
        (newline-less) line, then any open event is dispatched — streams
        cut mid-event still surface their last frame."""
        if self._buffer:
            line = bytes(self._buffer)
            self._buffer = bytearray()
            if line.endswith(b"\r"):
                line = line[:-1]
            # the residual may itself be the dispatching blank line (stream
            # cut between CR and LF): surface that event too
            event = self._feed_line(line)
            if event is not None:
                return event
        if self._data_lines:
            event = "\n".join(self._data_lines)
            self._data_lines = []
            self._event_bytes = 0
            self.events_parsed += 1
            return event
        return None


# -- native twin --------------------------------------------------------------

_native_lib = None
_native_tried = False

# trip kinds returned by sse_parser_take_trip (native/sse_parser.cpp)
_TRIP_BUFFER = 1
_TRIP_EVENT = 2


def load_native_library():
    """The C++ parser out of the framework-wide native library
    (utils.native builds/loads the single .so for all native components).
    Blocking on first call — call from sync startup code
    (DefaultChatClient.__init__ does), never from the event loop;
    ``make_parser`` afterwards only reads the cache.  Returns None — and
    remembers the failure — when the library can't be built or loaded, or
    when ``LWC_NATIVE_SSE=0``."""
    global _native_lib, _native_tried
    if _native_tried:
        return _native_lib
    _native_tried = True
    if os.environ.get("LWC_NATIVE_SSE", "1").lower() in ("0", "false", "no"):
        return None
    from ..utils.native import load_library

    lib = load_library()
    if lib is None:
        return None
    try:
        lib.sse_parser_new.restype = ctypes.c_void_p
        lib.sse_parser_new.argtypes = []
        lib.sse_parser_free.argtypes = [ctypes.c_void_p]
        lib.sse_parser_feed.restype = ctypes.c_size_t
        lib.sse_parser_feed.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.sse_parser_next_event.restype = ctypes.c_void_p
        lib.sse_parser_next_event.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.sse_parser_flush.restype = ctypes.c_size_t
        lib.sse_parser_flush.argtypes = [ctypes.c_void_p]
        # byte-budget ABI (ISSUE 19); a prebuilt .so predating the caps
        # raises AttributeError here, disabling the native path entirely
        # rather than serving an uncappable parser
        lib.sse_parser_set_caps.restype = None
        lib.sse_parser_set_caps.argtypes = [
            ctypes.c_void_p,
            ctypes.c_size_t,
            ctypes.c_size_t,
        ]
        lib.sse_parser_take_trip.restype = ctypes.c_int
        lib.sse_parser_take_trip.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        _native_lib = lib
    except Exception:
        _native_lib = None
    return _native_lib


class NativeSSEParser:
    """ctypes wrapper over native/sse_parser.cpp — same interface and frame
    semantics as ``SSEParser``, caps included (parity-tested in
    tests/test_native.py)."""

    def __init__(
        self,
        lib=None,
        max_buffer_bytes: int = 0,
        max_event_bytes: int = 0,
    ) -> None:
        self._lib = lib or load_native_library()
        if self._lib is None:
            raise RuntimeError("native SSE parser unavailable")
        self._handle = self._lib.sse_parser_new()
        self.max_buffer_bytes = int(max_buffer_bytes)
        self.max_event_bytes = int(max_event_bytes)
        if self.max_buffer_bytes or self.max_event_bytes:
            self._lib.sse_parser_set_caps(
                self._handle, self.max_buffer_bytes, self.max_event_bytes
            )
        self.cap_trips = 0
        self.events_parsed = 0  # same contract as SSEParser

    def _drain(self) -> Iterator[str]:
        out_len = ctypes.c_size_t()
        while True:
            ptr = self._lib.sse_parser_next_event(
                self._handle, ctypes.byref(out_len)
            )
            if not ptr:
                return
            self.events_parsed += 1
            yield ctypes.string_at(ptr, out_len.value).decode(
                "utf-8", errors="replace"
            )

    def _raise_if_tripped(self) -> None:
        observed = ctypes.c_size_t()
        kind = self._lib.sse_parser_take_trip(
            self._handle, ctypes.byref(observed)
        )
        if kind == 0:
            return
        self.cap_trips += 1
        if kind == _TRIP_BUFFER:
            raise IngestCapError(
                "sse_buffer", self.max_buffer_bytes, observed.value
            )
        raise IngestCapError(
            "sse_event", self.max_event_bytes, observed.value
        )

    def _drain_then_trip(self) -> Iterator[str]:
        # events completed before the offending line surface first, then
        # the trip raises — byte-identical to the Python generator, which
        # yields as it parses and raises at the offending line
        yield from self._drain()
        self._raise_if_tripped()

    def feed(self, data: bytes) -> Iterator[str]:
        self._lib.sse_parser_feed(self._handle, data, len(data))
        return self._drain_then_trip()

    def flush(self) -> Optional[str]:
        n = self._lib.sse_parser_flush(self._handle)
        event = next(self._drain(), None) if n else None
        self._raise_if_tripped()
        return event

    def close(self) -> None:
        if self._handle is not None:
            self._lib.sse_parser_free(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


def make_parser(max_buffer_bytes: int = 0, max_event_bytes: int = 0):
    """The serving path's parser factory: native when available, else the
    pure-Python implementation (identical semantics either way).  Caps of
    0 disable the corresponding byte budget."""
    lib = load_native_library()
    if lib is not None:
        return NativeSSEParser(
            lib,
            max_buffer_bytes=max_buffer_bytes,
            max_event_bytes=max_event_bytes,
        )
    return SSEParser(
        max_buffer_bytes=max_buffer_bytes,
        max_event_bytes=max_event_bytes,
    )
