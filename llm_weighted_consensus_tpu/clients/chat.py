"""Resilient asyncio SSE client for OpenAI-compatible chat APIs.

Parity target: reference src/chat/completions/client.rs — ``DefaultClient``:

* per-request ``CtxHandler`` hook that can rewrite the endpoint list
  (client.rs:26-54);
* archived-completion prefetch + message rehydration (client.rs:211-222,
  437-645 — implemented in ``archive``);
* forced streaming with ``include_usage`` when the caller wanted unary
  (client.rs:230-236);
* attempt matrix: primary model x every api_base, then each fallback model x
  every api_base (client.rs:238-258);
* retry under exponential backoff with first-chunk peek: a stream only
  commits once its first chunk arrives (client.rs:263-304);
* SSE decode with two-tier timeouts (first vs other chunk), ``[DONE]``
  handling, OpenRouter error-shape fallback, JSON-path deserialization
  errors, bad-status body capture (client.rs:334-434).

Streams yield a union: ``ChatCompletionChunk`` items interleaved with
``ChatError`` items (the reference's ``Result`` stream).  A yielded error
does not necessarily end the stream — a malformed chunk yields an error and
decoding continues — matching the reference exactly; consumers decide.

Transport is a seam (``Transport``) so tests script byte streams without
sockets; ``AiohttpTransport`` is the real implementation.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import AsyncIterator, Optional

from .. import archive as archive_mod
from .. import obs
from ..errors import (
    BadStatusError,
    BreakerOpenError,
    ChatError,
    CtxHandlerError,
    DeadlineExceededError,
    DeserializationError,
    EmptyStreamError,
    IngestCapError,
    ProviderError,
    ResponseError,
    StreamTimeoutError,
    TransportError,
)
from ..resilience import current_deadline, current_retry_budget
from ..types.base import SchemaError, fold_chunks
from ..types.chat_request import ChatCompletionCreateParams, StreamOptions
from ..types.chat_response import ChatCompletion, ChatCompletionChunk
from ..utils import jsonutil
from .sse import make_parser

DONE_FRAME = "[DONE]"


@dataclass
class ApiBase:
    """One upstream endpoint (client.rs:13-17)."""

    api_base: str
    api_key: str

    @classmethod
    def from_json_obj(cls, obj: dict) -> "ApiBase":
        return cls(api_base=obj["api_base"], api_key=obj["api_key"])


@dataclass
class BackoffPolicy:
    """Exponential backoff (reference uses the ``backoff`` crate; defaults
    from main.rs:5-16)."""

    initial_interval_ms: float = 100.0
    randomization_factor: float = 0.5
    multiplier: float = 1.5
    max_interval_ms: float = 1000.0
    max_elapsed_ms: Optional[float] = 40000.0

    def sleeps(self, rng: Optional[random.Random] = None):
        """Yield sleep durations (seconds); stops when max_elapsed exceeded.

        ``max_elapsed`` caps *wall-clock since the first attempt* (attempt
        time included), matching the backoff crate's max_elapsed_time.
        """
        rng = rng or random
        interval = self.initial_interval_ms
        start = time.monotonic()
        while True:
            jittered = interval * (
                1 + self.randomization_factor * (2 * rng.random() - 1)
            )
            if self.max_elapsed_ms is not None:
                elapsed_ms = (time.monotonic() - start) * 1000.0
                if elapsed_ms + jittered > self.max_elapsed_ms:
                    return
            yield jittered / 1000.0
            interval = min(interval * self.multiplier, self.max_interval_ms)


class CtxHandler:
    """Per-request auth/routing hook (client.rs:26-54).

    ``handle`` may rewrite the endpoint list per request context; raising
    :class:`ResponseError` aborts the request as a ctx error.
    """

    async def handle(self, ctx, api_bases: list) -> list:
        return api_bases


# ---------------------------------------------------------------------------
# Transport seam
# ---------------------------------------------------------------------------


class TransportResponse:
    status: int = 0

    async def read_body(self) -> bytes:
        raise NotImplementedError

    def byte_stream(self) -> AsyncIterator[bytes]:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class Transport:
    async def post_sse(
        self, url: str, headers: dict, body: bytes
    ) -> TransportResponse:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class AiohttpTransport(Transport):
    """Real HTTP transport; lazily creates one shared aiohttp session."""

    def __init__(self, connect_timeout_ms: float = 30000.0) -> None:
        self._session = None
        self.connect_timeout_ms = connect_timeout_ms

    def _get_session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            # no total timeout: streams are bounded by the client's own
            # per-chunk timeouts
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(
                    total=None, sock_connect=self.connect_timeout_ms / 1000.0
                )
            )
        return self._session

    async def close(self) -> None:
        """Release the shared session (service shutdown hook)."""
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def post_sse(self, url, headers, body) -> TransportResponse:
        session = self._get_session()
        try:
            resp = await session.post(
                url,
                data=body,
                headers={**headers, "content-type": "application/json"},
            )
        except Exception as e:  # connection-level failure
            raise TransportError(str(e)) from e

        class _Resp(TransportResponse):
            status = resp.status

            async def read_body(self) -> bytes:
                try:
                    return await resp.read()
                finally:
                    resp.release()

            async def byte_stream(self):
                async for chunk in resp.content.iter_any():
                    yield chunk

            async def close(self) -> None:
                resp.close()

        return _Resp()


# ---------------------------------------------------------------------------
# The client
# ---------------------------------------------------------------------------


@dataclass
class _Attempt:
    api_base: ApiBase
    model: str


class ChatClient:
    """Abstract client interface (client.rs:56-79): the consensus engine and
    the gateway depend on this, not on the HTTP implementation."""

    async def create_streaming(self, ctx, params: ChatCompletionCreateParams):
        raise NotImplementedError

    async def create_unary(self, ctx, params) -> ChatCompletion:
        stream = await self.create_streaming(ctx, params)
        chunks = []
        try:
            async for item in stream:
                if isinstance(item, ChatError):
                    raise item
                chunks.append(item)
        finally:
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                await aclose()
        aggregate = fold_chunks(chunks)
        if aggregate is None:
            raise EmptyStreamError()
        return ChatCompletion.from_streaming(aggregate)


class DefaultChatClient(ChatClient):
    def __init__(
        self,
        transport: Transport,
        api_bases: list,
        *,
        backoff: Optional[BackoffPolicy] = None,
        user_agent: Optional[str] = None,
        x_title: Optional[str] = None,
        referer: Optional[str] = None,
        first_chunk_timeout_ms: float = 10000.0,
        other_chunk_timeout_ms: float = 60000.0,
        ctx_handler: Optional[CtxHandler] = None,
        archive_fetcher: Optional[archive_mod.Fetcher] = None,
        resilience=None,
        judge_stream_max_bytes: int = 0,
        sse_max_event_bytes: int = 0,
    ) -> None:
        self.transport = transport
        self.api_bases = list(api_bases)
        self.backoff = backoff or BackoffPolicy()
        self.user_agent = user_agent
        self.x_title = x_title
        self.referer = referer
        self.first_chunk_timeout_ms = first_chunk_timeout_ms
        self.other_chunk_timeout_ms = other_chunk_timeout_ms
        self.ctx_handler = ctx_handler or CtxHandler()
        self.archive_fetcher = archive_fetcher or archive_mod.UnimplementedFetcher()
        # optional resilience.ResiliencePolicy: breakers, hedging, counters.
        # None (the default) preserves pre-resilience behavior exactly; the
        # ambient retry budget / deadline contextvars are still honored
        # because activating them is itself opt-in upstream.
        self.resilience = resilience
        # ingest byte budgets (ISSUE 19; 0 = uncapped, the library-level
        # default — the serving config turns them on): the cumulative
        # per-leg stream budget doubles as the bad-status/unary body cap,
        # and the SSE event cap also bounds the parser's newline-less
        # residue (a line cannot be longer than the event it would form)
        self.judge_stream_max_bytes = int(judge_stream_max_bytes)
        self.sse_max_event_bytes = int(sse_max_event_bytes)
        # compile/load the native SSE parser NOW (sync startup context) so
        # make_parser() inside the async decode loop never blocks the loop
        # on a g++ run
        from .sse import load_native_library

        load_native_library()

    # -- public API ---------------------------------------------------------

    async def create_streaming(self, ctx, params):
        stream, _api_base = await self.create_streaming_return_api_base(ctx, params)
        return stream

    async def create_streaming_return_api_base(self, ctx, params):
        """Returns (stream, api_base_used); raises ChatError when every
        attempt fails for the whole backoff budget (client.rs:193-306)."""
        # concurrently: ctx hook + archive prefetch
        async def _handle_ctx():
            try:
                return await self.ctx_handler.handle(ctx, list(self.api_bases))
            except ResponseError as e:
                raise CtxHandlerError(e) from e

        # join with cancellation (tokio::try_join! semantics): first failure
        # cancels the sibling so an aborted request does no stray archive IO
        api_bases, completions = await _try_join(
            _handle_ctx(),
            archive_mod.fetch_archived_for_messages(
                self.archive_fetcher, ctx, params.messages
            ),
        )

        request = params.clone()
        request.messages = archive_mod.replace_archive_messages(
            completions, request.messages
        )

        # force streaming (+usage when the caller wanted unary)
        if not request.stream:
            request.stream_options = StreamOptions(include_usage=True)
        request.stream = True

        # attempt matrix: primary model x ctx api_bases, then each fallback
        # model x the configured api_bases (client.rs:238-258)
        attempts = [_Attempt(ab, request.model) for ab in api_bases]
        if request.models:
            for model in request.models:
                attempts.extend(_Attempt(ab, model) for ab in self.api_bases)
            request.models = None
        if not attempts:
            raise TransportError("no api endpoints to attempt", 500)

        last_error: Optional[ChatError] = None
        sleeps = self.backoff.sleeps()
        while True:
            for i, attempt in enumerate(attempts):
                result = await self._attempt_maybe_hedged(attempts, i, request)
                if isinstance(result, ChatError):
                    last_error = result
                    continue
                return result
            sleep = next(sleeps, None)
            if sleep is None:
                raise last_error if last_error is not None else EmptyStreamError()
            deadline = current_deadline()
            if deadline is not None:
                if deadline.expired():
                    self._inc("deadline_expired")
                    obs.annotate(deadline_expired="retry loop")
                    raise DeadlineExceededError("retry loop")
                # never sleep past the deadline: wake with whatever budget
                # is left and let the next attempt's clamped timeouts decide
                sleep = min(sleep, deadline.remaining())
            budget = current_retry_budget()
            if budget is not None and not budget.try_acquire():
                # the fan-out's shared retry budget is dry: fail this judge
                # over to its error path instead of joining a retry storm
                self._inc("retry_denied")
                obs.annotate(retry_denied=True)
                raise last_error if last_error is not None else EmptyStreamError()
            await asyncio.sleep(sleep)

    # -- resilience-aware attempt machinery ----------------------------------

    def _inc(self, name: str) -> None:
        if self.resilience is not None:
            self.resilience.inc(name)

    async def _attempt_maybe_hedged(self, attempts, i, request):
        """One slot of the attempt matrix; with hedging enabled, a backup
        against the next endpoint races the primary after the hedge delay
        (Dean & Barroso: the loser is cancelled, extra load is bounded by
        how rarely the delay fires)."""
        policy = self.resilience
        hedge = policy.hedge if policy is not None else None
        if hedge is None or not hedge.enabled or len(attempts) < 2:
            return await self._open_committed(attempts[i], request)
        delay_ms = hedge.delay_ms_effective()
        if delay_ms is None:
            # quantile-only config, reservoir still cold: no hedge yet
            return await self._open_committed(attempts[i], request)

        primary = asyncio.create_task(self._open_committed(attempts[i], request))
        backup = None
        try:
            delay = delay_ms / 1000.0
            deadline = current_deadline()
            if deadline is not None:
                delay = min(delay, deadline.remaining())
            done, _ = await asyncio.wait({primary}, timeout=delay)
            if primary in done:
                return primary.result()

            # a hedge is an extra attempt, so it spends the shared retry
            # budget: under a brown-out (exactly when hedge delays fire) a
            # dry budget disables hedging before it can double the load
            budget = current_retry_budget()
            if budget is not None and not budget.try_acquire():
                self._inc("hedge_denied")
                obs.annotate(hedge_denied=True)
                return await primary

            self._inc("hedge_launched")
            obs.annotate(
                hedge_launched=True,
                hedge_delay_ms=delay_ms,
                hedge=policy.hedge.explain(),
            )
            backup = asyncio.create_task(
                self._open_committed(attempts[(i + 1) % len(attempts)], request)
            )
            tasks = {primary, backup}
            last: Optional[ChatError] = None
            while tasks:
                done, tasks = await asyncio.wait(
                    tasks, return_when=asyncio.FIRST_COMPLETED
                )
                winner = None
                for task in done:
                    result = task.result()
                    if isinstance(result, ChatError):
                        last = result
                    elif winner is None:
                        winner = (task, result)
                    else:
                        # both committed in one wake-up: keep the first, close
                        # the duplicate stream
                        await _close_committed(result)
                if winner is not None:
                    if winner[0] is backup:
                        self._inc("hedge_won")
                        obs.annotate(hedge_won=True)
                    await _discard_attempts(tasks)
                    return winner[1]
            return last
        except BaseException:
            # cancellation (quorum early-exit, client disconnect) or an
            # unexpected task exception must not orphan the sibling attempt
            # or any committed upstream stream it holds
            await _discard_attempts({t for t in (primary, backup) if t is not None})
            raise

    async def _open_committed(self, attempt, request):
        """One attempt end to end: breaker gate, open, first-chunk peek.

        Returns ``(stream, api_base)`` on commit or the ``ChatError`` that
        felled it; the outcome lands on the attempt's breaker and a commit's
        first-chunk latency feeds the hedge tracker.

        The attempt span (child of the ambient judge span — hedged
        attempts run as tasks that inherit it, so primary and backup
        become sibling children) covers gate -> open -> first-chunk
        commit; its activation makes the outgoing ``traceparent`` name
        THIS attempt as the upstream's parent."""
        policy = self.resilience
        aspan = obs.child_span(
            "judge:attempt",
            api_base=attempt.api_base.api_base,
            model=attempt.model,
        )
        atoken = aspan.activate() if aspan is not None else None
        try:
            breaker = None
            if policy is not None and policy.breakers is not None:
                breaker = policy.breakers.get(
                    attempt.api_base.api_base, attempt.model
                )
                if aspan is not None:
                    aspan.annotate(breaker_state=breaker.describe())
                if not breaker.allow():
                    self._inc("breaker_rejected")
                    if aspan is not None:
                        aspan.annotate(breaker_rejected=True)
                        aspan.finish("error")
                    return BreakerOpenError(
                        attempt.api_base.api_base, attempt.model
                    )
            # allow() may have claimed a half-open probe slot; from here on
            # every exit must settle it — record an outcome, or release it
            # when the attempt is cancelled / ends without a verdict
            resolved = breaker is None
            try:
                # per-attempt clone: hedged attempts run concurrently and
                # must not race on the shared request's model field
                req = request.clone()
                req.model = attempt.model
                start = time.monotonic()
                stream = self._open_event_stream(attempt.api_base, req)
                # first-chunk peek: commit only on a good first chunk
                try:
                    first = await stream.__anext__()
                except StopAsyncIteration:
                    first = EmptyStreamError()
                if isinstance(first, ChatError):
                    if breaker is not None:
                        if _breaker_failure(first):
                            breaker.record_failure()
                        elif isinstance(first, DeadlineExceededError):
                            # our budget ran out before the upstream
                            # answered: neither success nor failure — the
                            # upstream's health was never actually probed
                            breaker.release_probe()
                        else:
                            breaker.record_success()
                        resolved = True
                    await stream.aclose()
                    if aspan is not None:
                        # attempt-level failures are routine (the retry
                        # loop may still commit): mark the span errored
                        # without forcing trace retention — that verdict
                        # belongs to the judge/request outcome
                        aspan.annotate(error=str(first))
                        aspan.finish("error")
                    return first
                if breaker is not None:
                    breaker.record_success()
                    resolved = True
                first_chunk_ms = (time.monotonic() - start) * 1000.0
                if policy is not None and policy.hedge is not None:
                    policy.hedge.observe(first_chunk_ms)
                if aspan is not None:
                    aspan.annotate(first_chunk_ms=round(first_chunk_ms, 3))
                return _prepend(first, stream), attempt.api_base
            finally:
                if not resolved:
                    breaker.release_probe()
        finally:
            if aspan is not None:
                obs.Span.deactivate(atoken)
                aspan.finish()

    # -- stream machinery ---------------------------------------------------

    def _headers(self, api_base: ApiBase) -> dict:
        headers = {
            "authorization": f"Bearer {api_base.api_key}",
            "accept": "text/event-stream",
        }
        if self.user_agent:
            headers["user-agent"] = self.user_agent
        if self.x_title:
            headers["x-title"] = self.x_title
        if self.referer:
            headers["referer"] = self.referer
            headers["http-referer"] = self.referer
        # W3C traceparent: the ambient span (the attempt span, when one
        # is active) becomes the upstream's parent — no-op untraced
        obs.inject(headers)
        return headers

    async def _open_event_stream(self, api_base: ApiBase, request):
        """Async generator yielding ChatCompletionChunk | ChatError items.

        Mirrors create_streaming_stream (client.rs:334-434).  Decode errors
        for individual frames yield an error item and keep going; transport
        errors, bad statuses and timeouts yield an error item and stop.
        """
        url = f"{api_base.api_base.rstrip('/')}/chat/completions"
        body = jsonutil.dumps(request.to_json_obj()).encode("utf-8")
        # propagated per-request deadline (None unless the gateway set one):
        # every wait below is clamped to its remaining budget
        deadline = current_deadline()
        try:
            resp = await self.transport.post_sse(url, self._headers(api_base), body)
        except ChatError as e:
            yield e
            return
        except Exception as e:
            yield TransportError(str(e))
            return

        byte_iter = None
        try:
            if not (200 <= resp.status < 300):
                started = time.monotonic()
                try:
                    raw = await asyncio.wait_for(
                        resp.read_body(),
                        _clamp(self.first_chunk_timeout_ms, deadline),
                    )
                except asyncio.TimeoutError:
                    yield _timeout_error("first_chunk", started, deadline)
                    return
                if (
                    self.judge_stream_max_bytes
                    and len(raw) > self.judge_stream_max_bytes
                ):
                    # a hostile upstream can pad an error body too: the
                    # leg's byte budget caps the unary read, and the
                    # oversized body is dropped, not parsed
                    self._inc("ingest_cap_tripped")
                    yield IngestCapError(
                        "unary_body", self.judge_stream_max_bytes, len(raw)
                    )
                    return
                try:
                    parsed = jsonutil.loads(raw.decode("utf-8", errors="replace"))
                except Exception:
                    parsed = raw.decode("utf-8", errors="replace")
                yield BadStatusError(resp.status, parsed)
                return

            # native C++ parser when built (hot loop #1), Python fallback;
            # the event cap bounds both one event's data payload and the
            # newline-less residue (giant_line / newline_less_flood)
            parser = make_parser(
                max_buffer_bytes=self.sse_max_event_bytes,
                max_event_bytes=self.sse_max_event_bytes,
            )
            byte_iter = resp.byte_stream().__aiter__()
            first = True
            stream_bytes = 0
            pending: list = []
            while True:
                # per-chunk timeout tiers (client.rs:334-354; defaults
                # main.rs:17-20)
                if not pending:
                    tier = "first_chunk" if first else "other_chunk"
                    timeout = _clamp(
                        self.first_chunk_timeout_ms
                        if first
                        else self.other_chunk_timeout_ms,
                        deadline,
                    )
                    started = time.monotonic()
                    try:
                        data = await asyncio.wait_for(
                            byte_iter.__anext__(), timeout
                        )
                    except StopAsyncIteration:
                        try:
                            tail = parser.flush()
                        except IngestCapError as e:
                            self._inc("ingest_cap_tripped")
                            yield e
                            return
                        if tail is not None and tail != DONE_FRAME:
                            pending.append(tail)
                        if not pending:
                            return
                        data = None
                    except asyncio.TimeoutError:
                        yield _timeout_error(tier, started, deadline)
                        return
                    except Exception as e:
                        yield TransportError(str(e))
                        return
                    if data is not None:
                        # cumulative leg budget (JUDGE_STREAM_MAX_BYTES):
                        # checked before the parser sees the chunk so a
                        # flood is dropped, not buffered
                        stream_bytes += len(data)
                        if (
                            self.judge_stream_max_bytes
                            and stream_bytes > self.judge_stream_max_bytes
                        ):
                            self._inc("ingest_cap_tripped")
                            yield IngestCapError(
                                "judge_stream",
                                self.judge_stream_max_bytes,
                                stream_bytes,
                            )
                            return
                        try:
                            pending.extend(parser.feed(data))
                        except IngestCapError as e:
                            self._inc("ingest_cap_tripped")
                            yield e
                            return
                        continue
                event = pending.pop(0)
                first = False
                if event == DONE_FRAME:
                    # annotates the ambient judge span (this generator
                    # body runs in the judge's pump task)
                    obs.annotate(sse_events=parser.events_parsed)
                    return
                if not event or event.startswith(":"):
                    continue
                item = self._decode_chunk(event)
                yield item
        finally:
            # a [DONE] frame exits before the byte stream is exhausted:
            # close it rather than leave a suspended generator to the GC
            aclose = getattr(byte_iter, "aclose", None)
            if aclose is not None:
                await aclose()
            await resp.close()

    @staticmethod
    def _decode_chunk(data: str):
        try:
            obj = jsonutil.loads(data)
        except Exception as e:
            return DeserializationError(f"invalid JSON: {e}")
        try:
            chunk = ChatCompletionChunk.from_json_obj(obj)
            chunk.with_total_cost()
            return chunk
        except SchemaError as e:
            # OpenRouter provider-error passthrough (error.rs:99-141)
            if isinstance(obj, dict) and isinstance(obj.get("error"), dict):
                inner = obj["error"]
                return ProviderError(
                    code=inner.get("code"),
                    message=inner.get("message"),
                    metadata=inner.get("metadata"),
                    user_id=obj.get("user_id"),
                )
            return DeserializationError(str(e))


def _clamp(timeout_ms: float, deadline) -> float:
    """A tier timeout clamped to the remaining request deadline."""
    timeout = timeout_ms / 1000.0
    if deadline is not None:
        timeout = min(timeout, deadline.remaining())
    return timeout


def _timeout_error(tier: str, started: float, deadline) -> ChatError:
    """TimeoutError -> taxonomy: the deadline expiring is reported as such
    (it is this request's budget, not the upstream's slowness)."""
    if deadline is not None and deadline.expired():
        return DeadlineExceededError(f"{tier} wait")
    return StreamTimeoutError(tier, (time.monotonic() - started) * 1000.0)


def _breaker_failure(err: ChatError) -> bool:
    """Upstream-health classification: transport failures, timeouts and
    5xx/429 count against the breaker; any other 4xx means the upstream is
    alive and answering (a bad request is our fault, not its health), and a
    deadline expiry is our budget running out, not the upstream's fault."""
    if isinstance(err, DeadlineExceededError):
        return False
    if isinstance(
        err,
        (TransportError, StreamTimeoutError, EmptyStreamError, IngestCapError),
    ):
        # an ingest-cap trip is the upstream misbehaving (giant lines,
        # newline-less floods, oversized bodies): it counts against the
        # upstream's health exactly like a transport failure
        return True
    if isinstance(err, BadStatusError):
        return err.code >= 500 or err.code == 429
    return False


async def _close_committed(result) -> None:
    """Close a committed (stream, api_base) that lost the hedge race."""
    stream = result[0]
    aclose = getattr(stream, "aclose", None)
    if aclose is not None:
        await aclose()


async def _discard_attempts(tasks) -> None:
    """Cancel in-flight hedge losers; close any that committed anyway."""
    for task in tasks:
        task.cancel()
    results = await asyncio.gather(*tasks, return_exceptions=True)
    for result in results:
        if isinstance(result, tuple):
            await _close_committed(result)


async def _try_join(*coros):
    """asyncio.gather with sibling cancellation on first failure."""
    tasks = [asyncio.ensure_future(c) for c in coros]
    try:
        return await asyncio.gather(*tasks)
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise


async def _prepend(first, rest):
    """StreamOnce(first).chain(rest) (util.rs:33-53, client.rs:281-302)."""
    yield first
    async for item in rest:
        yield item
