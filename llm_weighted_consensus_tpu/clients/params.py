"""Shared judge/generator upstream-request assembly.

Both the consensus engine (score) and the multichat fan-out build a chat
request from a judge's ``LlmBase`` sampling surface plus the caller's
request-level passthrough fields (client.rs:488-743).  The field mapping
lives here once; score layers ballot forcing on top, multichat offsets the
seed per slot.
"""

from __future__ import annotations

from typing import Optional

from ..types import chat_request


def wrap_messages(base, messages: list) -> list:
    """Splice the judge's prefix/suffix messages around the conversation
    (client.rs:488-495)."""
    messages = list(messages)
    if base.prefix_messages:
        messages = list(base.prefix_messages) + messages
    if base.suffix_messages:
        messages = messages + list(base.suffix_messages)
    return messages


def base_chat_params(
    base,
    request,
    messages: list,
    *,
    seed: Optional[int],
    logprobs: Optional[bool] = None,
    top_logprobs: Optional[int] = None,
    response_format=None,
    tools=None,
    tool_choice=None,
) -> chat_request.ChatCompletionCreateParams:
    """The judge's upstream chat request (client.rs:661-743 field map)."""
    return chat_request.ChatCompletionCreateParams(
        messages=messages,
        model=base.model,
        frequency_penalty=base.frequency_penalty,
        logit_bias=base.logit_bias,
        logprobs=logprobs,
        max_completion_tokens=base.max_completion_tokens,
        presence_penalty=base.presence_penalty,
        response_format=response_format,
        seed=seed,
        service_tier=request.service_tier,
        stop=base.stop,
        stream=request.stream,
        stream_options=request.stream_options,
        temperature=base.temperature,
        tool_choice=tool_choice,
        tools=tools,
        top_logprobs=top_logprobs,
        top_p=base.top_p,
        max_tokens=base.max_tokens,
        min_p=base.min_p,
        provider=base.provider,
        reasoning=base.reasoning,
        repetition_penalty=base.repetition_penalty,
        top_a=base.top_a,
        top_k=base.top_k,
        usage=request.usage,
        verbosity=base.verbosity,
        models=base.models,
    )
