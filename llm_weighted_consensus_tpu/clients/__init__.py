"""IO shells: upstream chat client, consensus engine, multichat fan-out.

These modules are the host-side orchestration layer (asyncio); they import
the pure core but no JAX.  Device math is reached through the ``weights`` /
``ops`` seams so the IO path stays importable everywhere.
"""

from . import chat, multichat, score  # noqa: F401
