"""The consensus engine: weighted consensus scoring over LLM completions.

Parity target: reference src/score/completions/client.rs (1,800 LoC — the
core of the product).  Pipeline (client.rs:93-465):

1. stamp ``created``, generate ``scrcpl-{uuid}-{created}`` id;
2. reject <2 candidate choices;
3. concurrently resolve the panel model and prefetch archived completions
   referenced by choices *and* messages;
4. resolve choices to internal form, render every candidate to plain text
   for the ballot;
5. fetch per-judge weights (static config or the TPU training-table path);
6. emit an initial chunk carrying all N candidates as finished choices;
7. fan out all judges concurrently — unordered interleaved streaming,
   re-indexed into the global choice space by ``ChoiceIndexer``;
8. accumulate chunks; strip per-judge usage into a running total;
9. tally ``choice_weight[i] += vote[i] * judge_weight``, detect all-failed;
10. final chunk: weight_data, total usage(+cost), per-candidate weight +
    confidence, per-judge-choice confidence; deltas cleared;
11. if every judge errored: trailing AllVotesFailed error item.

Streaming protocol invariants (the product contract, SURVEY §2.6): candidate
choices arrive first and finished; judge streams interleave arbitrarily but
per-choice chunks are ordered; each judge's last frame carries its ``vote``;
exactly one final aggregate frame carries weights/confidences/usage; errors
are per-choice and never abort other judges.

The host-side tally here is exact Decimal math.  The batched device twin
(``ops.consensus``: votes[M,N] x weights[M] einsum + normalize on TPU) is
used by archive re-scoring; both are tested against each other.
"""

from __future__ import annotations

import asyncio
import random
import re
import time
from typing import AsyncIterator, Optional

from .. import archive as archive_mod
from .. import obs
from ..ballot import (
    PrefixTree,
    ballot_instruction,
    branch_limit,
    extract_vote,
    serialize_ballot,
)
from ..ballot.prompting import response_key_schema
from ..errors import (
    AllVotesFailed,
    ChatError,
    ExpectedTwoOrMoreChoices,
    FetchModelError,
    FetchModelWeightsError,
    InvalidContentError,
    InvalidModelError,
    ResponseError,
    ScoreArchiveError,
    ScoreChatError,
    ScoreError,
    ScoreInvalidCompletionChoiceIndex,
    to_response_error,
)
from ..identity.model import Model, ModelBase
from ..resilience import QuorumTracker, RetryBudget, current_deadline
from ..types import chat_request, score_request
from ..types.base import SchemaError, fold_chunks
from ..types.chat_response import Usage
from ..types.score_response import (
    ChatCompletion,
    ChatCompletionChunk,
    CompletionMetadata,
    Delta,
    StreamingChoice,
    TrainingTableData,
)
from ..utils import ChoiceIndexer, jsonutil, response_id
from ..weights import WeightFetchers
from .chat import ChatClient
from .tally import fixed_point_fold

RESPONSE_ID_PREFIX = "scrcpl"


# ---------------------------------------------------------------------------
# Model resolution (client.rs:911-950)
# ---------------------------------------------------------------------------


async def fetch_or_validate_score_model(model_fetcher, ctx, model_param) -> Model:
    """Resolve the ``model`` request field: 22-char id -> fetch;
    author-prefixed slug ending in a 22-char id -> fetch; inline JSON string
    -> parse+validate; structured body -> validate."""
    if isinstance(model_param, ModelBase):
        try:
            return model_param.into_model_validate()
        except ValueError as e:
            raise InvalidModelError(str(e)) from e
    model_id = model_param
    if len(model_id) == 22:
        return await _fetch_model(model_fetcher, ctx, model_id)
    slug = model_id.split("/")[-1]
    if len(slug) == 22:
        return await _fetch_model(model_fetcher, ctx, slug)
    try:
        obj = jsonutil.loads(model_id)
        base = ModelBase.from_json_obj(obj)
    except (ValueError, SchemaError):
        raise InvalidModelError(model_id) from None
    try:
        return base.into_model_validate()
    except ValueError as e:
        raise InvalidModelError(str(e)) from e


async def _fetch_model(model_fetcher, ctx, model_id: str) -> Model:
    try:
        return await model_fetcher.fetch(ctx, model_id)
    except ResponseError as e:
        raise FetchModelError(e) from e


# ---------------------------------------------------------------------------
# Choice resolution (client.rs:952-1163)
# ---------------------------------------------------------------------------

# InternalChoice variants (request.rs:93-110), as (kind, payload) pairs
_TEXT = "text"
_RAW_MESSAGE = "raw_message"
_CHAT = "chat"
_SCORE = "score"
_MULTICHAT = "multichat"


class InternalChoice:
    __slots__ = ("kind", "message", "logprobs", "error", "model", "metadata")

    def __init__(self, kind, message, logprobs=None, error=None, model=None, metadata=None):
        self.kind = kind
        self.message = message  # text str | chat_response.Message-like
        self.logprobs = logprobs
        self.error = error
        self.model = model
        self.metadata = metadata  # CompletionMetadata (usage already dropped)


_CHOICE_REF_KIND = {
    score_request.ChatCompletionChoiceRef: archive_mod.KIND_CHAT,
    score_request.ScoreCompletionChoiceRef: archive_mod.KIND_SCORE,
    score_request.MultichatCompletionChoiceRef: archive_mod.KIND_MULTICHAT,
}


async def fetch_archived_for_choices_and_messages(
    fetcher, ctx, choices: list, messages: list
) -> dict:
    """Prefetch unique archived completions referenced by score choices and
    by archive-role messages (client.rs:952-1076); failures carry the
    score-level error envelope (score Error::CompletionsArchiveError)."""
    seen: set = set()
    refs: list = []
    for choice in choices:
        kind = _CHOICE_REF_KIND.get(type(choice))
        if kind is None or choice.id in seen:
            continue
        seen.add(choice.id)
        refs.append((choice.id, kind))
    refs.extend(archive_mod.message_refs(messages, seen))
    return await archive_mod.fetch_archived(
        fetcher, ctx, refs, error_cls=ScoreArchiveError
    )


def convert_choices_to_internal(completions: dict, choices: list) -> list:
    """Score request choices -> InternalChoice list (client.rs:1078-1163)."""
    out = []
    for choice in choices:
        if isinstance(choice, str):
            out.append(InternalChoice(_TEXT, choice))
            continue
        ref_kind = _CHOICE_REF_KIND.get(type(choice))
        if ref_kind is None:
            # raw chat response message provided inline
            out.append(InternalChoice(_RAW_MESSAGE, choice))
            continue
        _, completion = completions[choice.id]
        found = None
        for arch_choice in completion.choices:
            if arch_choice.index == choice.choice_index:
                found = arch_choice
                break
        if found is None:
            raise ScoreInvalidCompletionChoiceIndex(choice.id, choice.choice_index)
        if ref_kind == archive_mod.KIND_CHAT:
            out.append(
                InternalChoice(
                    _CHAT,
                    found.message,
                    logprobs=found.logprobs,
                    metadata=CompletionMetadata(
                        id=completion.id,
                        created=completion.created,
                        model=completion.model,
                        service_tier=completion.service_tier,
                        system_fingerprint=completion.system_fingerprint,
                        usage=None,
                        provider=completion.provider,
                    ),
                )
            )
        elif ref_kind == archive_mod.KIND_SCORE:
            metadata = found.completion_metadata
            if metadata is not None:
                metadata = metadata.clone()
                metadata.usage = None
            out.append(
                InternalChoice(
                    _SCORE,
                    found.message.inner(),
                    logprobs=found.logprobs,
                    error=found.error,
                    model=found.model,
                    metadata=metadata,
                )
            )
        else:
            metadata = found.completion_metadata
            if metadata is not None:
                metadata = metadata.clone()
                metadata.usage = None
            out.append(
                InternalChoice(
                    _MULTICHAT,
                    found.message,
                    logprobs=found.logprobs,
                    error=found.error,
                    model=found.model,
                    metadata=metadata,
                )
            )
    return out


def render_message_text(message) -> str:
    """Flatten a response message to ballot text (client.rs:1222-1289):
    reasoning + content + refusal + pretty-printed tool calls, joined by
    blank lines."""
    parts = []
    if getattr(message, "reasoning", None):
        parts.append(message.reasoning)
    if getattr(message, "content", None):
        parts.append(message.content)
    if getattr(message, "refusal", None):
        parts.append(message.refusal)
    tool_calls = getattr(message, "tool_calls", None)
    if tool_calls:
        rendered = []
        for tc in tool_calls:
            try:
                args = jsonutil.loads(tc.function.arguments)
            except ValueError:
                args = tc.function.arguments
            rendered.append(
                {"type": "tool_call", "name": tc.function.name, "arguments": args}
            )
        parts.append(jsonutil.dumps(rendered, pretty=True))
    return "\n\n".join(parts)


def internal_choice_text(choice: InternalChoice) -> str:
    if choice.kind == _TEXT:
        return choice.message
    return render_message_text(choice.message)


def _message_to_delta(message) -> Delta:
    """Unary response message -> streaming score delta (client.rs:1196-1220)."""
    tool_calls = None
    if getattr(message, "tool_calls", None) is not None:
        from ..types.chat_response import (
            StreamingToolCall,
            StreamingToolCallFunction,
        )

        tool_calls = [
            StreamingToolCall(
                index=i,
                id=tc.id,
                function=StreamingToolCallFunction(
                    name=tc.function.name, arguments=tc.function.arguments
                ),
                type="function",
            )
            for i, tc in enumerate(message.tool_calls)
        ]
    return Delta(
        content=message.content,
        refusal=message.refusal,
        role=getattr(message, "role", None) or "assistant",
        tool_calls=tool_calls,
        reasoning=getattr(message, "reasoning", None),
        images=getattr(message, "images", None),
        vote=None,
    )


def _is_ingest_cap_error(error) -> bool:
    """True when a per-judge ResponseError carries an ingest-cap trip
    (IngestCapError, ISSUE 19 byte budgets).  The wire nesting is
    score -> chat -> {"kind": "ingest_cap", ...}; walked generically so
    both the stream-opening and mid-stream error paths match."""
    msg = getattr(error, "message", None)
    while isinstance(msg, dict):
        inner = msg.get("error")
        if isinstance(inner, dict) and inner.get("kind") == "ingest_cap":
            return True
        msg = inner
    return False


# ---------------------------------------------------------------------------
# Stream merge (select_all analog)
# ---------------------------------------------------------------------------


# terminal queue markers for merge_streams: module-private, so a judge
# stream can never yield one as a payload (identity-checked)
_PUMP_DONE = object()


class _PumpCrash:
    """A pump task's exception, surfaced through the queue in FIFO order
    so items the crashed judge already delivered still drain first."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


async def merge_streams(streams: list) -> AsyncIterator:
    """Unordered interleaved merge of async iterators (futures select_all,
    client.rs:342-356).  Items surface in arrival order across all judges."""
    # Bounded queue preserves select_all's pull-based backpressure: a slow
    # downstream consumer throttles upstream judge reads instead of
    # buffering every provider token in memory.  Exactly one pump task per
    # stream for the whole merge — completion and pump crashes travel
    # through the queue as terminal markers, so the consumer is a plain
    # ``await queue.get()`` with zero per-chunk task creation (the old
    # select loop burned a fresh ``queue.get()`` task plus a rebuilt
    # ``asyncio.wait`` pending-set per item; tests/test_host_fastpath.py
    # pins the no-churn contract).
    queue: asyncio.Queue = asyncio.Queue(maxsize=16)

    async def pump(stream):
        try:
            async for item in stream:
                await queue.put(item)
        except Exception as exc:
            # judge streams themselves never raise; this catches
            # programming errors instead of hanging the merge.
            # CancelledError is not an Exception and propagates.
            await queue.put(_PumpCrash(exc))
            return
        await queue.put(_PUMP_DONE)

    tasks = [asyncio.create_task(pump(s)) for s in streams]
    remaining = len(tasks)
    try:
        while remaining:
            item = await queue.get()
            if item is _PUMP_DONE:
                remaining -= 1
            elif type(item) is _PumpCrash:
                raise item.exc
            else:
                yield item
    finally:
        # an abandoned consumer can always cancel pumps blocked on a full
        # queue — the markers above never wedge shutdown
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


# ---------------------------------------------------------------------------
# The client
# ---------------------------------------------------------------------------


class ScoreClient:
    def __init__(
        self,
        chat_client: ChatClient,
        model_fetcher,
        weight_fetchers: Optional[WeightFetchers] = None,
        archive_fetcher: Optional[archive_mod.Fetcher] = None,
        rng_factory=random.Random,
        ballot_sink=None,
        cache=None,
        flights=None,
        resilience=None,
        bias_plan=None,
        ledger=None,
        fleet=None,
        host_fastpath: bool = False,
        live_weights=None,
    ) -> None:
        self.chat_client = chat_client
        self.model_fetcher = model_fetcher
        self.weight_fetchers = weight_fetchers or WeightFetchers()
        self.archive_fetcher = archive_fetcher or archive_mod.UnimplementedFetcher()
        self.rng_factory = rng_factory
        # optional callback(response_id, judge_index, key_indices): archives
        # the per-judge ballot assignment so stored logprobs can be
        # re-extracted into soft votes later (archive/rescore.py revote)
        self.ballot_sink = ballot_sink
        # optional content-addressed result cache (cache/ScoreCache) with
        # single-flight dedup: identical concurrent requests collapse onto
        # one judge fan-out, repeats replay recorded chunk frames
        self.cache = cache
        if flights is None and cache is not None:
            from ..cache import SingleFlight

            flights = SingleFlight()
        self.flights = flights
        # optional resilience.ResiliencePolicy: a per-request retry budget
        # shared across the judge fan-out and weight-quorum graceful
        # degradation.  None (the default) = pre-resilience behavior.
        self.resilience = resilience
        # optional resilience.JudgeBiasPlan: deterministic per-judge vote
        # perturbation (JUDGE_BIAS_PLAN) for consensus-quality drills
        self.bias_plan = bias_plan
        # optional obs.OutcomeLedger: one record per scored request
        # (LEDGER_RING/LEDGER_DIR), the weight-learning training substrate
        self.ledger = ledger
        # optional fleet.FleetCoordinator (FLEET_*): after winning the
        # in-process single-flight slot, the leader additionally consults
        # the fleet — peer cache fetch or a cross-replica lease — so a
        # fleet-wide hot fingerprint hits upstream exactly once
        self.fleet = fleet
        # optional weights.live.LiveWeightStore (WEIGHTS_*): versioned
        # per-judge weight overrides behind atomic hot-swap, with the
        # applied version stamped on every tally span + ledger record
        # and shadow-table counters feeding the quality scorecards
        self.live_weights = live_weights
        # HOST_FASTPATH: run the tally fold on scaled-int64 numpy vectors
        # (clients/tally.py) and hoist the per-candidate share divisions;
        # off = the Decimal loops below, byte-identical either way — any
        # ballot the fast lane cannot prove exact falls back per request
        self.host_fastpath = host_fastpath

    # -- unary (client.rs:71-91) --------------------------------------------

    async def create_unary(self, ctx, params) -> ChatCompletion:
        stream = await self.create_streaming(ctx, params)
        chunks = []
        try:
            async for item in stream:
                if isinstance(item, ScoreError):
                    raise item
                chunks.append(item)
        finally:
            await stream.aclose()
        aggregate = fold_chunks(chunks)
        return ChatCompletion.from_streaming(aggregate)

    # -- cache front door (cache/: fingerprint -> hit replay / miss record) --

    def _cache_key(self, ctx, params) -> Optional[str]:
        if self.cache is None or not self.cache.enabled:
            return None
        if getattr(params, "cache_bypass", None):
            return None
        from ..cache import score_fingerprint

        return score_fingerprint(params, ctx)

    async def create_streaming(self, ctx, params):
        """Cache front door.  Uncacheable requests (no cache configured,
        ``cache_bypass``, unfingerprintable model form) go straight to the
        live pipeline; otherwise a hit replays the recorded chunk frames
        byte-identically and a miss claims the single-flight slot — the
        leader streams live while recording, concurrent identical
        requests await the leader's recording and replay it."""
        fp = self._cache_key(ctx, params)
        # front-door span: one per request, closed at the routing decision
        # (hit / leader / follower / bypass) — the streaming itself is
        # covered by the judge/tally spans downstream
        cspan = obs.child_span("cache:lookup")

        def _decide(outcome: str) -> None:
            if cspan is not None:
                cspan.annotate(outcome=outcome)
                cspan.finish()

        if fp is None:
            _decide("bypass")
            return await self._create_streaming_live(ctx, params)
        from ..cache import replay_stream

        waits = 0
        while True:
            record = self.cache.get(fp)
            if record is not None:
                _decide("hit" if waits == 0 else "follower")
                return replay_stream(record)
            future = self.flights.claim(fp)
            if future is None:  # leader
                # only the in-process leader talks to the fleet: one
                # replica contributes at most one fleet participant per
                # fingerprint, and every fleet failure mode resolves to
                # plan "local" — exactly the pre-fleet behavior
                plan, chunks = "local", None
                if self.fleet is not None:
                    plan, chunks = await self.fleet.begin(fp)
                if plan == "hit":
                    _decide("fleet_hit")
                    self.cache.put_chunks(fp, chunks)
                    self.flights.complete(fp, chunks)
                    return replay_stream(chunks)
                _decide("leader")
                try:
                    live = await self._create_streaming_live(ctx, params)
                except BaseException as e:
                    self.flights.fail(fp, e)
                    if plan == "lease":
                        self.fleet.abandon(fp)
                    raise
                return self._record_and_stream(
                    fp, live, lease=(plan == "lease")
                )
            waits += 1
            if cspan is not None:
                cspan.annotate(singleflight_waits=waits)
            ok, record = await self.flights.wait(future)
            if ok:
                _decide("follower")
                return replay_stream(record)
            # leader abandoned (disconnect) or produced an uncacheable
            # stream: retry — this caller likely becomes the new leader

    async def _record_and_stream(self, fp, live, lease: bool = False):
        """Leader path: stream live to this client while recording; on
        clean error-free completion the recording lands in the cache and
        resolves every follower.  Any other outcome (abandoned stream,
        error items) releases the flight so followers retry as leaders.
        With ``lease`` (the fleet granted this replica the cross-replica
        slot) a clean completion also publishes to the owning replica,
        and anything else releases the lease so fleet waiters fall back
        instead of riding out the TTL."""
        import asyncio

        from ..cache import record_stream

        done = False

        def on_complete(chunk_objs):
            nonlocal done
            done = True
            self.cache.put_chunks(fp, chunk_objs)
            self.flights.complete(fp, chunk_objs)
            if lease:
                self.fleet.publish(fp, chunk_objs)

        rec = record_stream(live, on_complete)
        try:
            async for item in rec:
                yield item
        finally:
            await rec.aclose()
            if not done:
                self.flights.fail(fp, asyncio.CancelledError())
                if lease:
                    self.fleet.abandon(fp)

    # -- streaming (client.rs:93-465) ---------------------------------------

    async def _create_streaming_live(self, ctx, params):
        created = int(time.time())
        resp_id = response_id(RESPONSE_ID_PREFIX, created)

        n_choices = len(params.choices)
        if n_choices < 2:
            raise ExpectedTwoOrMoreChoices(n_choices)

        from .chat import _try_join

        model, completions = await _try_join(
            fetch_or_validate_score_model(self.model_fetcher, ctx, params.model),
            fetch_archived_for_choices_and_messages(
                self.archive_fetcher, ctx, params.choices, params.messages
            ),
        )

        request = params.clone()
        request.model = model.id
        request.messages = archive_mod.replace_archive_messages(
            completions, request.messages
        )
        internal_choices = convert_choices_to_internal(completions, request.choices)
        choice_texts = [internal_choice_text(c) for c in internal_choices]
        request.choices = choice_texts

        try:
            weights, weight_data = await self.weight_fetchers.fetch(
                ctx, request, model
            )
        except ResponseError as e:
            raise FetchModelWeightsError(e) from e

        # live weight overrides (weights/live.py): the (weights, version)
        # pair is captured HERE, in one store read, and threaded through
        # the whole stream — a hot swap mid-request can never mix two
        # versions inside one tally, and the stamped version is always
        # the one that actually scored the request
        weights_version = None
        if self.live_weights is not None:
            weights, weights_version = self.live_weights.apply(
                model, weights
            )

        initial_chunk = self._initial_chunk(
            resp_id, created, model, internal_choices
        )
        return self._stream(
            ctx,
            resp_id,
            created,
            model,
            request,
            weights,
            weight_data,
            initial_chunk,
            n_choices,
            weights_version=weights_version,
        )

    def _initial_chunk(
        self, resp_id: str, created: int, model: Model, internal_choices: list
    ) -> ChatCompletionChunk:
        """All N candidates as already-finished choices (client.rs:182-327)."""
        choices = []
        for i, ic in enumerate(internal_choices):
            if ic.kind == _TEXT:
                delta = Delta(content=ic.message, role="assistant")
            else:
                delta = _message_to_delta(ic.message)
            choices.append(
                StreamingChoice(
                    delta=delta,
                    finish_reason="stop",
                    index=i,
                    logprobs=ic.logprobs,
                    weight=None,
                    confidence=None,
                    error=ic.error,
                    model=ic.model,
                    model_index=None,
                    completion_metadata=ic.metadata,
                )
            )
        return ChatCompletionChunk(
            id=resp_id,
            choices=choices,
            created=created,
            model=model.id,
            usage=None,
            weight_data=None,
        )

    async def _stream(
        self,
        ctx,
        resp_id,
        created,
        model,
        request,
        weights,
        weight_data,
        initial_chunk,
        n_choices,
        weights_version=None,
    ):
        # usage seeded by embeddings evidence for trained weights
        # (client.rs:330-337)
        if isinstance(weight_data, TrainingTableData) and (
            weight_data.embeddings_response.usage is not None
        ):
            usage = weight_data.embeddings_response.usage.clone()
        else:
            usage = Usage()

        aggregate = initial_chunk.clone()
        pending_initial = initial_chunk
        indexer = ChoiceIndexer(n_choices)

        policy = self.resilience
        budget_token = None
        if policy is not None and policy.retry_budget_tokens > 0:
            # one bucket for the whole fan-out: the pump tasks the stream
            # merge spawns inherit it via contextvar, so every judge's
            # backoff loop draws from the same allotment
            budget_token = RetryBudget(policy.retry_budget_tokens).activate()

        quorum = None
        if policy is not None and policy.quorum_fraction > 0:
            # judge-level tracking (each judge settles on its first final
            # frame); mirrors the Decimal tally below exactly
            quorum = QuorumTracker(
                {llm.index: weights[llm.index] for llm in model.llms},
                n_choices,
                policy.quorum_fraction,
            )

        judge_streams = [
            self._judge_stream(
                ctx, resp_id, created, indexer, llm, weights[llm.index], request
            )
            for llm in model.llms
        ]

        degraded = False
        quorum_degraded = False
        merged = merge_streams(judge_streams)
        try:
            async for chunk in merged:
                if pending_initial is not None:
                    yield pending_initial
                    pending_initial = None
                aggregate.push(chunk)
                # strip per-judge usage into the running total; interim chunks go
                # out without it, the final frame carries the sum
                for choice in chunk.choices:
                    metadata = choice.completion_metadata
                    if metadata is not None and metadata.usage is not None:
                        usage.push(metadata.usage)
                        metadata.usage = None
                yield chunk
                if quorum is not None:
                    for choice in chunk.choices:
                        if choice.model_index is None:
                            continue
                        if choice.delta.vote is not None:
                            quorum.record_vote(
                                choice.model_index, choice.delta.vote
                            )
                        elif choice.error is not None:
                            quorum.record_error(choice.model_index)
                    if quorum.decided():
                        # stragglers cannot flip the argmax: cancel them
                        # (closing the merge cancels pumps and judge
                        # streams, which close their upstreams) and ship
                        degraded = True
                        quorum_degraded = True
                        policy.inc("quorum_degraded")
                        obs.annotate(quorum=quorum.explain())
                        break
        finally:
            await merged.aclose()
            if budget_token is not None:
                RetryBudget.deactivate(budget_token)

        if pending_initial is not None:
            # no judges / no judge produced output: still emit candidates
            yield pending_initial

        if degraded and quorum is not None:
            # synthesize per-judge failure detail for the cancelled
            # stragglers; pushed into the aggregate so the tally and the
            # final frame see them like any other errored judge
            straggler_chunk = self._straggler_chunk(
                resp_id, created, indexer, model, weights, request, quorum
            )
            if straggler_chunk is not None:
                aggregate.push(straggler_chunk)
                yield straggler_chunk

        if not degraded and policy is not None:
            deadline = current_deadline()
            if deadline is not None and deadline.expired():
                # time ran out with a partial panel: judges that missed the
                # deadline carry errors, at least one vote landed -> the
                # consensus ships, marked degraded (all-failed keeps its
                # AllVotesFailed error path below)
                tail = aggregate.choices[n_choices:]
                if any(c.delta.vote is not None for c in tail) and any(
                    c.error is not None for c in tail
                ):
                    degraded = True
                    policy.inc("deadline_degraded")
                    obs.annotate(deadline_degraded=True)

        if not degraded:
            # a judge leg tripped an ingest byte budget (IngestCapError,
            # clients/chat.py) while other judges voted: the consensus
            # ships degraded so the final frame keeps the per-judge
            # cap-trip error entries (the `if not degraded: choice.error
            # = None` strip below) — same contract as quorum/deadline
            # degradation, and record_stream refuses to cache it
            # (all-failed keeps its AllVotesFailed error path below)
            tail = aggregate.choices[n_choices:]
            if any(
                c.error is not None and _is_ingest_cap_error(c.error)
                for c in tail
            ) and any(c.delta.vote is not None for c in tail):
                degraded = True
                if policy is not None:
                    policy.inc("ingest_cap_degraded")
                obs.annotate(ingest_cap_degraded=True)

        # tally + all-error detection (client.rs:384-416)
        from decimal import Decimal

        # the tally span's attributes are the consensus "explain" record:
        # per-judge vote/weight/contribution plus per-candidate results —
        # built only when a trace is live (None otherwise, zero cost)
        t_tally = time.perf_counter()
        tspan = obs.child_span("consensus:tally", n_judges=len(model.llms))

        tail = aggregate.choices[n_choices:]
        choice_weight = None
        if self.host_fastpath:
            # HOST_FASTPATH: the weighted-vote fold on scaled-int64 numpy
            # vectors — byte-identical by construction, None when the
            # ballots cannot be proven exact (the Decimal loop below is
            # the authority and re-runs in full)
            choice_weight = fixed_point_fold(tail, n_choices)
        fold_in_loop = choice_weight is None
        if fold_in_loop:
            choice_weight = [Decimal(0)] * n_choices
        all_error = True
        all_error_code: Optional[int] = None
        for choice in tail:
            if all_error:
                if choice.error is None:
                    all_error = False
                elif all_error_code is None:
                    all_error_code = choice.error.code
                elif choice.error.code != all_error_code:
                    if (
                        400 <= choice.error.code < 500
                        and 400 <= all_error_code < 500
                    ):
                        all_error_code = 400
                    else:
                        all_error_code = 500
            if fold_in_loop and choice.delta.vote is not None:
                w = choice.weight if choice.weight is not None else Decimal(0)
                for i, v in enumerate(choice.delta.vote):
                    choice_weight[i] += v * w

        # final frame (client.rs:418-456)
        weight_sum = sum(choice_weight)
        aggregate.weight_data = weight_data
        usage.with_total_cost()
        aggregate.usage = usage
        if degraded:
            aggregate.degraded = True
        all_failed = all_error and len(model.llms) > 0
        # winner + confidence margin (top1 - top2) are consensus-health
        # facts, computed Decimal-exact whether or not a trace is live —
        # the quality aggregates must not depend on sampling
        winner = None
        margin = None
        if weight_sum > 0:
            winner = max(range(n_choices), key=lambda i: choice_weight[i])
            ranked = sorted(choice_weight, reverse=True)
            top2 = ranked[1] if len(ranked) > 1 else Decimal(0)
            margin = float((ranked[0] - top2) / weight_sum)
        explain_candidates: list = []
        explain_judges: list = []
        quality_ballots: list = []
        want_ledger = self.ledger is not None
        conf_vec = [0.0] * n_choices
        ledger_judges: list = []
        # HOST_FASTPATH: the share choice_weight[i]/weight_sum is divided
        # out once per candidate instead of once per judge per candidate
        # (the division is deterministic, so hoisting it is byte-identical
        # to the slow lane's in-loop recompute below)
        shares = None
        if self.host_fastpath:
            if weight_sum > 0:
                # identical weight OBJECTS (the fixed-point fold memoizes
                # repeated sums onto one Decimal) share one division and
                # one result object — deterministic division makes this
                # byte-identical, and downstream the splice encoder
                # formats each shared confidence object once
                div_memo: dict = {}
                shares = []
                for w in choice_weight:
                    hit = div_memo.get(id(w))
                    if hit is None:
                        div_memo[id(w)] = hit = (w, w / weight_sum)
                    shares.append(hit[1])
            else:
                shares = [Decimal(0)] * n_choices
        for choice in aggregate.choices:
            if choice.index < n_choices:
                w = choice_weight[choice.index]
                choice.weight = w
                if shares is not None:
                    choice.confidence = shares[choice.index]
                else:
                    choice.confidence = (
                        w / weight_sum if weight_sum > 0 else Decimal(0)
                    )
                if want_ledger:
                    conf_vec[choice.index] = float(choice.confidence)
                if tspan is not None:
                    explain_candidates.append(
                        {
                            "index": choice.index,
                            "weight": float(w),
                            "confidence": float(choice.confidence),
                        }
                    )
            elif choice.delta.vote is not None:
                vote = choice.delta.vote
                confidence = Decimal(0)
                if shares is not None:
                    for i, v in enumerate(vote):
                        confidence += shares[i] * v
                else:
                    for i, v in enumerate(vote):
                        share = (
                            choice_weight[i] / weight_sum
                            if weight_sum > 0
                            else Decimal(0)
                        )
                        confidence += share * v
                choice.confidence = confidence
                judge_weight = (
                    choice.weight if choice.weight is not None else Decimal(0)
                )
                # one Decimal->float pass per ballot, shared by the
                # quality ballot and the ledger record (the weight
                # itself stays Decimal for the exact weight share)
                fvote = [float(v) for v in vote]
                quality_ballots.append(
                    obs.JudgeBallot(
                        choice.model or "",
                        choice.model_index,
                        judge_weight,
                        fvote,
                    )
                )
                if want_ledger:
                    ledger_judges.append(
                        {
                            "model": choice.model,
                            "model_index": choice.model_index,
                            "weight": float(judge_weight),
                            "vote": fvote,
                            "error": None,
                            # the judge's vote-mass-weighted share of the
                            # final confidence vector: the Decimal-exact
                            # alignment score weights/learning.py trains on
                            "alignment": float(confidence),
                        }
                    )
                if tspan is not None:
                    explain_judges.append(
                        {
                            "model": choice.model,
                            "model_index": choice.model_index,
                            "weight": float(choice.weight)
                            if choice.weight is not None
                            else None,
                            "vote": fvote,
                            "confidence_contribution": float(confidence),
                            "error": choice.error.code
                            if choice.error is not None
                            else None,
                        }
                    )
            else:
                # voteless judge choice: errored or cancelled
                error_code = (
                    choice.error.code if choice.error is not None else None
                )
                quality_ballots.append(
                    obs.JudgeBallot(
                        choice.model or "",
                        choice.model_index,
                        choice.weight
                        if choice.weight is not None
                        else Decimal(0),
                        None,
                        error_code,
                    )
                )
                if want_ledger:
                    ledger_judges.append(
                        {
                            "model": choice.model,
                            "model_index": choice.model_index,
                            "weight": float(choice.weight)
                            if choice.weight is not None
                            else None,
                            "vote": None,
                            "error": error_code,
                            "alignment": None,
                        }
                    )
                if tspan is not None:
                    explain_judges.append(
                        {
                            "model": choice.model,
                            "model_index": choice.model_index,
                            "weight": float(choice.weight)
                            if choice.weight is not None
                            else None,
                            "vote": None,
                            "confidence_contribution": 0.0,
                            "error": error_code,
                        }
                    )
            choice.delta = Delta()
            choice.finish_reason = None
            choice.logprobs = None
            if not degraded:
                choice.error = None
            # degraded: keep per-judge failure detail on the final frame so
            # unary consumers see WHY the panel is partial
        if tspan is not None:
            tspan.annotate(
                judges=explain_judges,
                candidates=explain_candidates,
                weight_sum=float(weight_sum),
                winner=winner,
                degraded=degraded,
                **(
                    {"weights_version": weights_version}
                    if weights_version is not None
                    else {}
                ),
            )
            tspan.finish()
        if self.live_weights is not None:
            # shadow-mode comparison (weights/live.py): re-tally the
            # same ballots under the staged table; pure observation,
            # the served result above is already final
            self.live_weights.observe_shadow(quality_ballots, n_choices)
        trace_id = obs.current_trace_id()
        # consensus-quality aggregates: scorecards, pairwise agreement,
        # drift windows, margin histogram (obs/quality.py) — always on,
        # like the phase aggregate below
        obs.observe_outcome(
            obs.Outcome(
                winner=winner,
                margin=margin,
                weight_sum=weight_sum if weight_sum > 0 else Decimal(0),
                n_choices=n_choices,
                degraded=degraded,
                quorum_degraded=quorum_degraded,
                all_failed=all_failed,
                trace_id=trace_id,
                judges=quality_ballots,
            )
        )
        if self.ledger is not None:
            # one ledger record per scored request: the persistent
            # training substrate for weight learning / archive re-scoring
            self.ledger.offer(
                {
                    "id": resp_id,
                    "created": created,
                    "panel": model.id,
                    "n_choices": n_choices,
                    "winner": winner,
                    "confidence": conf_vec,
                    "margin": margin,
                    "weight_sum": float(weight_sum),
                    "degraded": degraded,
                    "quorum_degraded": quorum_degraded,
                    "all_failed": all_failed,
                    "trace_id": trace_id,
                    # which weight-table version scored this request —
                    # "base" when no live table was active, so the
                    # learner can partition its substrate by version
                    "weights_version": weights_version,
                    "judges": ledger_judges,
                }
            )
        # host_tally phase: the weighted-vote fold + final-frame build
        # (runs with or without a live trace — the aggregate must not
        # depend on sampling)
        obs.observe_phase(
            "host_tally", (time.perf_counter() - t_tally) * 1e3
        )
        if degraded:
            # degraded consensus is always retained, whatever the sample
            # rate said at the door
            obs.force_keep("degraded")
        if all_failed:
            # an all-judges-failed tally is exactly as diagnosis-worthy
            # as a degraded one; the unary path can surface it as a
            # merged 4xx, which the trace middleware's >=500 forcing
            # would otherwise drop
            obs.force_keep("all_failed")
        # the final frame carries the trace id so SSE consumers can fetch
        # the explain trace from /v1/traces/{trace_id}
        aggregate.trace_id = trace_id
        yield aggregate

        if all_failed:
            yield AllVotesFailed(all_error_code)

    @staticmethod
    def _straggler_chunk(
        resp_id, created, indexer, model, weights, request, quorum
    ):
        """Error choices for judges cancelled by the quorum early exit."""
        pending = sorted(quorum.pending())
        if not pending:
            return None
        llms_by_index = {llm.index: llm for llm in model.llms}
        choices = []
        for judge_index in pending:
            quorum.record_error(judge_index)
            llm = llms_by_index.get(judge_index)
            choices.append(
                StreamingChoice(
                    delta=Delta(),
                    finish_reason="error",
                    index=indexer.get(judge_index, 0),
                    logprobs=None,
                    weight=weights[judge_index],
                    confidence=None,
                    error=ResponseError(
                        code=499,
                        message="straggler cancelled: weight quorum reached",
                    ),
                    model=llm.id if llm is not None else None,
                    model_index=judge_index,
                    completion_metadata=None,
                )
            )
        return ChatCompletionChunk(
            id=resp_id,
            choices=choices,
            created=created,
            model=request.model,
            usage=None,
            weight_data=None,
        )

    # -- per-judge ballot stream (client.rs:467-908) ------------------------

    async def _judge_stream(
        self, ctx, resp_id, created, indexer, llm, weight, request
    ):
        """Span wrapper around the ballot stream proper.  This generator is
        driven by exactly one dedicated pump task (merge_streams), so the
        judge span can live in the pump's contextvar context: the chat
        client's attempt spans and retry/hedge annotations land under it,
        isolated from sibling judges."""
        inner = self._judge_stream_inner(
            ctx, resp_id, created, indexer, llm, weight, request
        )
        jspan = obs.child_span(
            "judge:stream",
            model=llm.id,
            judge_index=llm.index,
            weight=float(weight),
        )
        token = jspan.activate() if jspan is not None else None
        t_judge = time.perf_counter()
        try:
            async for item in inner:
                yield item
        finally:
            await inner.aclose()
            # upstream_judge phase: this judge's whole ballot-stream
            # lifetime (the per-request breakdown interval-unions the
            # judge spans instead, so R concurrent judges count once)
            obs.observe_phase(
                "upstream_judge", (time.perf_counter() - t_judge) * 1e3
            )
            if jspan is not None:
                obs.Span.deactivate(token)
                jspan.finish()

    async def _judge_stream_inner(
        self, ctx, resp_id, created, indexer, llm, weight, request
    ):
        rng = self.rng_factory()
        n_choices = len(request.choices)

        # ballot construction (client.rs:497-517)
        tree = PrefixTree.build(
            rng, n_choices, branch_limit(llm.base.top_logprobs)
        )
        key_indices = tree.key_indices(rng)
        keys = [k for k, _ in key_indices]
        ballot_json = serialize_ballot(request.choices, key_indices)
        with_ticks, without_ticks = PrefixTree.regex_patterns(keys)
        if self.host_fastpath:
            # compile the per-judge ballot patterns once: every ballot
            # alphabet is freshly randomized, so the module-level re
            # cache (512 entries, cleared wholesale when full) churns
            # under concurrent panels, and the final frame re-scans both
            # patterns once per choice.  ``re.finditer`` accepts Pattern
            # objects, so find_key/extract_vote thread them unchanged —
            # matches (and therefore bytes) are identical either way.
            with_ticks = re.compile(with_ticks)
            without_ticks = re.compile(without_ticks)
        if self.ballot_sink is not None:
            self.ballot_sink(resp_id, llm.index, list(key_indices))

        chat_params = self._judge_chat_params(
            llm, request, ballot_json, keys
        )

        def error_chunk(err) -> ChatCompletionChunk:
            # lands on the ambient judge span (we run in the pump task)
            obs.annotate(judge_error=str(err))
            return ChatCompletionChunk(
                id=resp_id,
                choices=[
                    StreamingChoice(
                        delta=Delta(),
                        finish_reason="error",
                        index=indexer.get(llm.index, 0),
                        logprobs=None,
                        weight=weight,
                        confidence=None,
                        error=to_response_error(ScoreChatError(err))
                        if isinstance(err, ChatError)
                        else to_response_error(err),
                        model=llm.id,
                        model_index=llm.index,
                        completion_metadata=None,
                    )
                ],
                created=created,
                model=request.model,
                usage=None,
                weight_data=None,
            )

        # open the judge's chat stream; failure -> error choice, not stream
        # failure (client.rs:712-783)
        try:
            stream = await self.chat_client.create_streaming(ctx, chat_params)
        except ChatError as e:
            yield error_chunk(e)
            return
        except Exception as e:
            # per-judge error isolation covers unexpected failures too: a
            # judge must never take the whole consensus down
            yield error_chunk(ResponseError(code=500, message=str(e)))
            return

        aggregate_chunk = None
        final_chunk = None
        # Deviation from the reference: it attaches chat-chunk usage only to
        # per-choice metadata, so an OpenAI-style trailing usage chunk with
        # empty `choices` is silently dropped from cost accounting.  We
        # collect such trailing usage and graft it onto the judge's final
        # frame metadata.
        trailing_usage = None
        try:
            # look-ahead loop: an error on the *next* item marks the current
            # chunk's choices as errored (client.rs:795-882)
            try:
                next_chat_chunk = await stream.__anext__()
            except StopAsyncIteration:
                next_chat_chunk = None
            if isinstance(next_chat_chunk, ChatError):
                yield error_chunk(next_chat_chunk)
                return

            while next_chat_chunk is not None:
                chat_chunk = next_chat_chunk
                error = None
                try:
                    upcoming = await stream.__anext__()
                except StopAsyncIteration:
                    upcoming = None
                if isinstance(upcoming, ChatError):
                    error = to_response_error(ScoreChatError(upcoming))
                    next_chat_chunk = None
                else:
                    next_chat_chunk = upcoming

                if not chat_chunk.choices and chat_chunk.usage is not None:
                    if trailing_usage is None:
                        trailing_usage = chat_chunk.usage.clone()
                    else:
                        trailing_usage.push(chat_chunk.usage)
                chunk = self._convert_chat_chunk(
                    chat_chunk, resp_id, created, indexer, llm, weight,
                    request, error,
                )
                if llm.base.output_mode == "tool_call":
                    chunk.tool_as_content()

                if aggregate_chunk is None:
                    aggregate_chunk = chunk.clone()
                else:
                    aggregate_chunk.push(chunk)

                finished = self._split_off_finished(chunk)
                if finished is not None:
                    if final_chunk is None:
                        final_chunk = finished
                    else:
                        final_chunk.push(finished)
                if chunk.choices:
                    yield chunk
        finally:
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                await aclose()

        if final_chunk is None:
            if aggregate_chunk is None:
                yield error_chunk(ResponseError(code=500, message="empty judge stream"))
                return
            # no finish_reason ever arrived (provider ended the stream
            # abruptly): synthesize a final frame with cleared deltas so the
            # vote can still attach without re-streaming content
            final_chunk = aggregate_chunk.clone_without_choices()
            for c in aggregate_chunk.choices:
                cc = c.clone()
                cc.delta = Delta()
                final_chunk.choices.append(cc)

        if trailing_usage is not None and final_chunk.choices:
            first = final_chunk.choices[0]
            if first.completion_metadata is None:
                first.completion_metadata = CompletionMetadata(
                    id="", created=0, model="", usage=trailing_usage
                )
            elif first.completion_metadata.usage is None:
                first.completion_metadata.usage = trailing_usage
            else:
                first.completion_metadata.usage.push(trailing_usage)

        # attach votes to the withheld final frame (client.rs:884-907)
        for choice in final_chunk.choices:
            agg_choice = next(
                (c for c in aggregate_chunk.choices if c.index == choice.index),
                None,
            )
            try:
                if agg_choice is None:
                    raise InvalidContentError("choice missing from aggregate")
                logprob_tokens = None
                if (
                    agg_choice.logprobs is not None
                    and agg_choice.logprobs.content is not None
                ):
                    logprob_tokens = agg_choice.logprobs.content
                vote = extract_vote(
                    tree,
                    with_ticks,
                    without_ticks,
                    n_choices,
                    agg_choice.delta.content,
                    logprob_tokens,
                )
                if self.bias_plan is not None:
                    # JUDGE_BIAS_PLAN drill seam: deterministically
                    # miscalibrate the targeted judge's extracted vote
                    # (Decimal-in, Decimal-out) before it enters the tally
                    vote = self.bias_plan.perturb(llm.index, vote)
                choice.delta.vote = vote
                obs.annotate(vote=[float(v) for v in vote])
            except InvalidContentError as e:
                obs.annotate(vote_error=str(e))
                if choice.error is None:
                    choice.error = to_response_error(e)
                    choice.finish_reason = "error"
        yield final_chunk

    def _judge_chat_params(self, llm, request, ballot_json, keys):
        """Assemble the judge's upstream chat request (client.rs:488-743)."""
        from .params import base_chat_params, wrap_messages

        base = llm.base
        messages = wrap_messages(base, request.messages)

        # ballot goes into (or creates) the trailing system message
        # (client.rs:533-572)
        content = ballot_instruction(ballot_json, keys, base.output_mode)
        if messages and isinstance(messages[-1], chat_request.SystemMessage):
            last = messages[-1].clone()
            if isinstance(last.content, str):
                last.content = f"{last.content}\n\n{content}"
            else:
                last.content = list(last.content) + [
                    chat_request.SimpleContentPart(text=f"\n\n{content}")
                ]
            messages = messages[:-1] + [last]
        else:
            messages = messages + [chat_request.SystemMessage(content=content)]

        # output forcing by mode (client.rs:574-659)
        schema = response_key_schema(keys, bool(base.synthetic_reasoning))
        readonly_tools = request.tools
        response_format = None
        tools = None
        tool_choice = None
        if base.output_mode == "instruction":
            if readonly_tools:
                tools = list(readonly_tools)
                tool_choice = "none"
        elif base.output_mode == "json_schema":
            response_format = chat_request.ResponseFormat(
                type="json_schema",
                json_schema=chat_request.JsonSchema(
                    name="response_key", strict=True, schema=schema
                ),
            )
            if readonly_tools:
                tools = list(readonly_tools)
                tool_choice = "none"
        else:  # tool_call
            tools = list(readonly_tools or [])
            tools.append(
                chat_request.Tool(
                    function=chat_request.FunctionDefinition(
                        name="response_key", parameters=schema, strict=True
                    )
                )
            )
            tool_choice = chat_request.ToolChoiceFunction(
                function=chat_request.ToolChoiceFunctionFunction(
                    name="response_key"
                )
            )

        return base_chat_params(
            base,
            request,
            messages,
            seed=request.seed,
            logprobs=True if base.top_logprobs is not None else None,
            top_logprobs=base.top_logprobs,
            response_format=response_format,
            tools=tools,
            tool_choice=tool_choice,
        )

    @staticmethod
    def _convert_chat_chunk(
        chat_chunk, resp_id, created, indexer, llm, weight, request, error
    ) -> ChatCompletionChunk:
        """Chat chunk -> score chunk with global indices + judge identity
        (client.rs:813-868)."""
        choices = []
        for choice in chat_chunk.choices:
            choices.append(
                StreamingChoice(
                    delta=Delta.from_chat(choice.delta),
                    finish_reason="error" if error is not None else choice.finish_reason,
                    index=indexer.get(llm.index, choice.index),
                    logprobs=choice.logprobs,
                    weight=weight,
                    confidence=None,
                    error=error,
                    model=llm.id,
                    model_index=llm.index,
                    completion_metadata=CompletionMetadata(
                        id=chat_chunk.id,
                        created=chat_chunk.created,
                        model=chat_chunk.model,
                        service_tier=chat_chunk.service_tier,
                        system_fingerprint=chat_chunk.system_fingerprint,
                        usage=chat_chunk.usage,
                        provider=chat_chunk.provider,
                    ),
                )
            )
        return ChatCompletionChunk(
            id=resp_id,
            choices=choices,
            created=created,
            model=request.model,
            usage=None,
            weight_data=None,
        )

    @staticmethod
    def _split_off_finished(chunk: ChatCompletionChunk):
        """Withhold finished choices so the judge's final frame can attach
        the vote (client.rs:1633-1659)."""
        if not any(c.has_finish_reason_or_usage() for c in chunk.choices):
            return None
        finished_chunk = chunk.clone_without_choices()
        unfinished = []
        for choice in chunk.choices:
            if choice.has_finish_reason_or_usage():
                finished_chunk.choices.append(choice)
            else:
                unfinished.append(choice)
        chunk.choices = unfinished
        return finished_chunk
