"""Fixed-point vectorized tally — the HOST_FASTPATH consensus fold.

The streaming tally folds every judge ballot into per-candidate weights:
``choice_weight[i] = Σ_k vote[k][i] * weight[k]`` over Decimal values
(clients/score.py, mirroring reference client.rs:384-416).  At panel
sizes that loop is J×N Decimal multiply-adds on the host critical path.
This module runs the same fold on scaled-int64 numpy vectors over
(judges × candidates) and reconstructs exact Decimals — the Decimal
fold stays the authority: any input the fixed-point lane cannot PROVE
it reproduces byte-for-byte makes :func:`fixed_point_fold` return None
and the caller re-runs the Decimal loop.  Exactness is proven, never
assumed; overflow falls back loudly, never drifts silently.

Why the reconstruction is exact (and byte-identical through
``format(d, "f")`` on the final frame):

* every ballot value ``d`` is gated to a finite int/Decimal and read as
  ``(coeff, exp)`` with ``d = coeff * 10**exp``;
* with ``Pv/Pw`` the largest vote/weight downscales, each value maps to
  the integer ``coeff * 10**(P + exp)`` (``P = Pv`` or ``Pw``), so the
  integer matrix product computes ``Σ v*w`` scaled by ``10**(Pv+Pw)``
  with no rounding anywhere;
* the Decimal fold is exact too **iff** no intermediate coefficient
  outgrows the context precision.  ``Σ_k max_i |v'| * |w'|`` bounds
  every intermediate sum *and* every product coefficient, so one gate —
  that bound must fit both the Decimal context precision and int64 —
  covers the whole fold.  A fold the gate rejects is one the Decimal
  loop may round, exactly when the fast lane must stand down;
* exact Decimal arithmetic lands on ideal exponents (``e1+e2`` for
  multiply, ``min(e1, e2)`` for add), so the fold's result exponent is
  ``E_i = min(0, min_k(exp_v[k][i] + exp_w[k]))`` — the 0 is the
  ``Decimal(0)`` accumulator the loop starts from.  The result is
  rebuilt at that exponent from the integer sum via the context-free
  ``Decimal((sign, digits, E_i))`` constructor (``scaleb`` would apply
  context rounding), preserving trailing zeros: ``0.5 + 0.5`` renders
  ``1.0``, not ``1``.

Parity with the Decimal fold across pathological weights (tiny, huge,
repeating-decimal, mixed exponents) is property-tested in
tests/test_host_fastpath.py.
"""

from __future__ import annotations

from decimal import Decimal, getcontext
from itertools import chain

import numpy as np

_D0 = Decimal(0)
# the scaled-product sum must stay provably below int64 wraparound
# (numpy overflows silently); 2**62 leaves headroom over the gate's
# own bound arithmetic
_I64_GUARD = 1 << 62


class _Unfit(Exception):
    """A ballot value the fixed-point lane cannot prove exact."""


def _scan(value, memo: dict):
    """``(coefficient, exponent)`` with ``value == coeff * 10**exp``,
    exact — or :class:`_Unfit`.  Memoized on the Decimal's *string
    representation* (not its value: ``Decimal("1")`` and
    ``Decimal("1.0")`` are equal but carry different exponents, and the
    exponent decides the rendered bytes)."""
    if type(value) is Decimal:
        key = str(value)
        hit = memo.get(key)
        if hit is not None:
            return hit
        sign, digits, exp = value.as_tuple()
        if not isinstance(exp, int):
            raise _Unfit(key)  # NaN / Infinity
        coeff = 0
        for d in digits:
            coeff = coeff * 10 + d
        if sign:
            coeff = -coeff
        memo[key] = hit = (coeff, exp)
        return hit
    if type(value) is int:
        # int * Decimal is exact in the Decimal fold with exponent 0
        return (value, 0)
    # float (TypeError in the Decimal fold), bool, anything else: the
    # slow path is the authority on how to fail
    raise _Unfit(type(value).__name__)


def fixed_point_fold(tail, n_choices: int):
    """``choice_weight`` of the Decimal tally fold, computed on
    scaled-int64 numpy vectors — or None when byte-identity cannot be
    proven (the caller MUST then run the Decimal fold; the fast lane
    never ships an unproven number).

    ``tail`` is the aggregate's judge choices; ballots are the choices
    with a non-None ``delta.vote`` folded with their ``weight``
    (missing weight = 0), exactly like the slow loop.
    """
    if n_choices <= 0:
        return None
    votes = []
    weights = []
    for choice in tail:
        vote = choice.delta.vote
        if vote is None:
            continue
        if type(vote) is not list or len(vote) != n_choices:
            # short ballots fold partially and long ones IndexError in
            # the slow loop; both shapes belong to the authority
            return None
        votes.append(vote)
        w = choice.weight
        weights.append(w if w is not None else _D0)
    if not votes:
        # the fold never ran: the accumulator list itself is the result
        return [Decimal(0)] * n_choices
    # Votes repeat a handful of distinct objects — hard ballots share
    # ONE zero Decimal via ``[Decimal(0)] * n`` (ballot/vote.py) — so
    # the whole matrix dedups at C speed over object ids (objects stay
    # alive in ``tail`` for the whole call, ids are stable), only the
    # distinct objects are scanned, and the scaled-int64 matrix is a
    # numpy gather over that tiny table.
    J = len(votes)
    ids = np.fromiter(
        map(id, chain.from_iterable(votes)),
        dtype=np.int64,
        count=J * n_choices,
    )
    _, first, inv = np.unique(ids, return_index=True, return_inverse=True)
    memo: dict = {}
    try:
        table = [
            _scan(votes[i // n_choices][i % n_choices], memo)
            for i in first.tolist()
        ]
        sw = [_scan(w, memo) for w in weights]
    except _Unfit:
        return None
    pv = max(0, max(-e for (_, e) in table))
    pw = max(0, max(-e for (_, e) in sw))
    # scaled integers as Python ints first: the exactness/overflow gates
    # must run before anything narrows to int64
    v_distinct = [c * 10 ** (pv + e) for (c, e) in table]
    wscaled = [c * 10 ** (pw + e) for (c, e) in sw]
    max_v = max(abs(v) for v in v_distinct)
    max_w = max(abs(w) for w in wscaled)
    # max_v * Σ|w| bounds every product and every intermediate sum of
    # the Decimal fold (scaled): within it, the fold is exact under the
    # context precision and the int64 matrix cannot wrap.  The raw
    # elements are gated on their own too — a huge scaled value beside
    # a zero vote/weight vanishes from the product bound.
    s_bound = max_v * sum(abs(w) for w in wscaled)
    if (
        s_bound >= _I64_GUARD
        or max_v >= _I64_GUARD
        or max_w >= _I64_GUARD
        or len(str(s_bound)) > getcontext().prec
    ):
        # int64 could wrap / the Decimal fold itself may round — loud
        # fallback to the authority, never silent drift
        return None
    idx_mat = inv.reshape(J, n_choices)
    vmat = np.take(np.array(v_distinct, dtype=np.int64), idx_mat)
    wvec = np.array(wscaled, dtype=np.int64)
    sums = (vmat * wvec[:, None]).sum(axis=0).tolist()
    vote_exps = {e for (_, e) in table}
    weight_exps = {e for (_, e) in sw}
    if len(vote_exps) == 1 and len(weight_exps) == 1:
        # one quantum each (hard votes + a uniform weight table): the
        # result exponent is the same scalar for every candidate
        e0 = min(0, next(iter(vote_exps)) + next(iter(weight_exps)))
        exps = [e0] * n_choices
    else:
        evote = np.take(
            np.array([e for (_, e) in table], dtype=np.int64), idx_mat
        )
        evec = np.array([e for (_, e) in sw], dtype=np.int64)
        exps = np.minimum((evote + evec[:, None]).min(axis=0), 0).tolist()
    p = pv + pw
    out = []
    # candidate sums repeat heavily (hard ballots leave most candidates
    # at zero), so reconstructed Decimals are shared through a memo
    rebuilt: dict = {}
    for s, e in zip(sums, exps):
        d = rebuilt.get((s, e))
        if d is None:
            # every term carries 10**(P + ev + ew) with ev+ew >= E_i, so
            # the division is exact by construction and the E-notation
            # literal reconstructs the exact coefficient+exponent pair
            # ("1000E-3" parses to 1.000, trailing zeros preserved)
            # without the context rounding scaleb would apply
            shift = p + e
            coeff = s if shift == 0 else s // 10 ** shift
            rebuilt[(s, e)] = d = Decimal("%dE%d" % (coeff, e))
        out.append(d)
    return out
