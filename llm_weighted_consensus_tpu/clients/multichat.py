"""Multichat client: one request fans out to many generator models.

The reference defines multichat only as response types + identity
(SURVEY §2.10: "one request, many models, choices = each model's answer");
this implements the client for real.  A score panel's judges define the
generator slots: judges are deduplicated by ``multichat_id`` (weight /
output_mode / synthetic_reasoning / top_logprobs reset — llm/mod.rs:538-548)
and duplicates of the same generator occupy consecutive slots
(model/mod.rs:153-178) — i.e. extra samples from that generator.

Streaming protocol mirrors the score engine's: slots stream interleaved,
per-slot errors are error choices (never request failures), unary is the
fold of the stream.  ``StreamingSelfConsistency`` adds the incremental
on-device consensus update (BASELINE config 5): each finished candidate is
embedded and the cosine consensus recomputed, so consumers watch confidence
converge while slower generators are still streaming.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..errors import ChatError, ScoreChatError, to_response_error
from ..identity.model import Model
from ..types.base import fold_chunks
from ..types.chat_response import Delta as ChatDelta
from ..types.multichat_response import (
    ChatCompletion,
    ChatCompletionChunk,
    StreamingChoice,
)
from ..types.score_response import CompletionMetadata
from ..utils import response_id
from .chat import ChatClient, _try_join
from .score import (
    fetch_archived_for_choices_and_messages,
    fetch_or_validate_score_model,
    merge_streams,
)

RESPONSE_ID_PREFIX = "mchcpl"


def generator_slots(model: Model) -> list:
    """(slot_index, llm) per generator slot, ordered by multichat_index.

    Every judge occupies exactly one slot; judges sharing a multichat_id
    are the same generator sampled multiple times.
    """
    return [
        (llm.multichat_index, llm)
        for llm in sorted(model.llms, key=lambda l: l.multichat_index)
    ]


class MultichatClient:
    def __init__(
        self,
        chat_client: ChatClient,
        model_fetcher,
        archive_fetcher=None,
    ) -> None:
        from .. import archive as archive_mod

        self.chat_client = chat_client
        self.model_fetcher = model_fetcher
        self.archive_fetcher = archive_fetcher or archive_mod.UnimplementedFetcher()

    async def create_unary(self, ctx, params) -> ChatCompletion:
        stream = await self.create_streaming(ctx, params)
        chunks = []
        try:
            # slot streams convert every failure into error choices, so the
            # stream yields only chunks (unlike score's AllVotesFailed item)
            async for item in stream:
                chunks.append(item)
        finally:
            await stream.aclose()
        return ChatCompletion.from_streaming(fold_chunks(chunks))

    async def create_streaming(self, ctx, params):
        from .. import archive as archive_mod

        created = int(time.time())
        resp_id = response_id(RESPONSE_ID_PREFIX, created)

        model, completions = await _try_join(
            fetch_or_validate_score_model(self.model_fetcher, ctx, params.model),
            fetch_archived_for_choices_and_messages(
                self.archive_fetcher, ctx, [], params.messages
            ),
        )
        request = params.clone()
        request.model = model.id
        request.messages = archive_mod.replace_archive_messages(
            completions, request.messages
        )
        return self._stream(ctx, resp_id, created, model, request)

    async def _stream(self, ctx, resp_id, created, model, request):
        streams = [
            self._slot_stream(ctx, resp_id, created, slot, llm, request)
            for slot, llm in generator_slots(model)
        ]
        async for chunk in merge_streams(streams):
            yield chunk

    def _slot_params(self, llm, request, slot: int):
        """The upstream chat request for one generator slot: the judge's
        sampling surface minus ballot forcing (the multichat-reset fields)."""
        from .params import base_chat_params, wrap_messages

        base = llm.base
        # identical generators must not produce identical samples: offset a
        # caller-provided seed per slot
        seed = request.seed + slot if request.seed is not None else None
        return base_chat_params(
            base, request, wrap_messages(base, request.messages), seed=seed
        )

    async def _slot_stream(self, ctx, resp_id, created, slot, llm, request):
        def error_chunk(err) -> ChatCompletionChunk:
            return ChatCompletionChunk(
                id=resp_id,
                choices=[
                    StreamingChoice(
                        delta=ChatDelta(),
                        finish_reason="error",
                        index=slot,
                        logprobs=None,
                        error=to_response_error(ScoreChatError(err))
                        if isinstance(err, ChatError)
                        else to_response_error(err),
                        model=llm.multichat_id,
                        model_index=llm.multichat_index,
                        completion_metadata=None,
                    )
                ],
                created=created,
                model=request.model,
                usage=None,
            )

        try:
            stream = await self.chat_client.create_streaming(
                ctx, self._slot_params(llm, request, slot)
            )
        except ChatError as e:
            yield error_chunk(e)
            return
        except Exception as e:
            # per-slot isolation covers unexpected failures too
            yield error_chunk(to_response_error(e))
            return

        try:
            async for item in stream:
                if isinstance(item, ChatError):
                    yield error_chunk(item)
                    return
                yield ChatCompletionChunk(
                    id=resp_id,
                    choices=[
                        StreamingChoice(
                            delta=choice.delta,
                            finish_reason=choice.finish_reason,
                            index=slot,
                            logprobs=choice.logprobs,
                            error=None,
                            model=llm.multichat_id,
                            model_index=llm.multichat_index,
                            completion_metadata=CompletionMetadata(
                                id=item.id,
                                created=item.created,
                                model=item.model,
                                service_tier=item.service_tier,
                                system_fingerprint=item.system_fingerprint,
                                usage=item.usage,
                                provider=item.provider,
                            ),
                        )
                        for choice in item.choices
                        if choice.index == 0
                    ],
                    created=created,
                    model=request.model,
                    usage=None,
                )
        finally:
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                await aclose()


# ---------------------------------------------------------------------------
# Streaming incremental consensus (BASELINE config 5)
# ---------------------------------------------------------------------------


class StreamingSelfConsistency:
    """Fold a multichat stream into a live consensus distribution.

    As each candidate finishes, it is embedded on device and the cosine
    consensus vote recomputed over the completed set — consumers see
    ``confidence`` tighten while slow generators are still streaming.
    """

    INITIAL_CAPACITY = 16

    def __init__(self, embedder, temperature: float = 0.05, batcher=None):
        self.embedder = embedder
        self.temperature = temperature
        # when set (serve/batcher.py), async updates go through the serving
        # micro-batcher so concurrent streams share device dispatches
        self.batcher = batcher
        self.texts: dict = {}
        self.failed: set = set()
        self.confidence: dict = {}
        # device-resident consensus state: embedded candidates live in a
        # fixed-capacity buffer (grown by bucket) so every update is ONE
        # fused embed+revote dispatch and the only fetch is the confidence
        # vector (VERDICT r1 item 8 + link-RTT discipline)
        self._order: list = []  # position -> slot
        self._buf = None
        self._valid = None

    @property
    def count(self) -> int:
        return len(self._order)

    def _absorb(self, chunk: ChatCompletionChunk) -> list:
        """Fold a chunk into the text accumulators; returns the slots that
        just finished and now need embedding (pure host work)."""
        pending = []
        for choice in chunk.choices:
            slot = choice.index
            if choice.delta.content:
                self.texts[slot] = self.texts.get(slot, "") + choice.delta.content
            if choice.error is not None or choice.finish_reason == "error":
                # errored generators contribute nothing to the consensus
                self.failed.add(slot)
                continue
            if (
                choice.finish_reason is not None
                and slot not in self._order
                and slot not in pending
                and slot not in self.failed
            ):
                pending.append(slot)
        return pending

    def _ensure_capacity(self) -> None:
        import jax.numpy as jnp

        hidden = self.embedder.config.hidden_size
        if self._buf is None:
            cap = self.INITIAL_CAPACITY
            self._buf = jnp.zeros((cap, hidden), jnp.float32)
            self._valid = jnp.zeros((cap,), jnp.float32)
        elif self.count == self._buf.shape[0]:
            grow = self._buf.shape[0]  # double (next power-of-two bucket)
            self._buf = jnp.pad(self._buf, ((0, grow), (0, 0)))
            self._valid = jnp.pad(self._valid, (0, grow))

    def _next_position(self) -> int:
        self._ensure_capacity()
        return len(self._order)

    def _commit(self, slot: int, buf, valid) -> None:
        # updates are functional (new buffers returned), so nothing commits
        # until the dispatch succeeds: a raising embedder leaves no phantom
        # slot behind and the candidate can retry later.  (Host-side
        # failures before dispatch keep the old buffers valid; the update
        # jit donates them, so only an in-flight device failure — already
        # fatal for the stream — can consume them without a replacement.)
        self._buf, self._valid = buf, valid
        self._order.append(slot)

    def _publish(self, conf) -> None:
        import numpy as np

        if conf is not None and self.count >= 2:
            host_conf = np.asarray(conf)  # the ONE fetch
            self.confidence = {
                slot: float(host_conf[i])
                for i, slot in enumerate(self._order)
            }

    def _embed_slots(self, slots: list) -> None:
        """Fold finished candidates into the device buffer; one fused
        embed+revote dispatch per candidate, one confidence fetch total."""
        conf = None
        for slot in slots:
            position = self._next_position()
            buf, valid, conf = self.embedder.stream_vote_update(
                self.texts.get(slot, ""),
                self._buf,
                self._valid,
                position,
                self.temperature,
            )
            self._commit(slot, buf, valid)
        self._publish(conf)

    def push_chunk(self, chunk: ChatCompletionChunk) -> Optional[dict]:
        """Returns {slot: confidence} when the distribution updates.

        Blocking variant (embeds + revotes inline); async consumers must
        use ``push_chunk_async`` so the device work never stalls the event
        loop."""
        pending = self._absorb(chunk)
        if pending:
            self._embed_slots(pending)
        if not pending or self.count < 2:
            return None
        return dict(self.confidence)

    async def _embed_slots_batched(self, slots: list) -> None:
        """``_embed_slots`` through the serving micro-batcher: each update
        awaits its turn in a shared device dispatch, so R concurrent
        streams' finished candidates ride one vmapped embed+revote.  Only
        the LAST slot's confidence is published, so intermediate updates
        skip the host fetch (want_conf=False — no wasted link RTTs)."""
        conf = None
        for i, slot in enumerate(slots):
            position = self._next_position()
            buf, valid, conf = await self.batcher.stream_update(
                self.texts.get(slot, ""),
                self._buf,
                self._valid,
                position,
                self.temperature,
                want_conf=i == len(slots) - 1,
            )
            self._commit(slot, buf, valid)
        self._publish(conf)

    async def push_chunk_async(
        self, chunk: ChatCompletionChunk
    ) -> Optional[dict]:
        """``push_chunk`` with the fused embed+revote dispatch moved off
        the event loop (VERDICT r1 item 8: the blocking embed stalled the
        event loop on every finished candidate) — through the micro-batcher
        when one is attached, else a plain executor hop."""
        pending = self._absorb(chunk)
        if not pending:
            return None
        if self.batcher is not None:
            await self._embed_slots_batched(pending)
        else:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, self._embed_slots, pending)
        if self.count < 2:
            return None
        return dict(self.confidence)


class ConsensusUpdate:
    """In-stream consensus frame (a wire extension — the reference has no
    multichat client at all, SURVEY §2.10): emitted by the gateway between
    multichat chunks as the live confidence distribution tightens."""

    def __init__(self, confidence: dict):
        self.confidence = confidence

    def to_json_obj(self) -> dict:
        return {
            "object": "multichat.consensus",
            "confidence": {
                str(slot): conf
                for slot, conf in sorted(self.confidence.items())
            },
        }
