"""The lock-model registry: every threading primitive in the package.

``CONCURRENCY_MODEL`` is the declarative table the concurrency rules
(LWC014–016, ``analysis/concurrency.py``) and the runtime
``LockWitness`` (``analysis/witness.py``) both consume.  It is enforced
BOTH ways, like the LWC010/011 registries:

* a ``threading.Lock``/``RLock``/``Condition`` assignment anywhere in
  the package that has no entry here fails LWC014 (unregistered lock);
* an entry whose creation site no longer exists fails LWC014 (stale
  registry row) — the table only ever shrinks honestly.

Per-lock entry fields:

``module``
    Repo-relative path suffix of the file that creates the lock (the
    both-ways match key; fixtures under ``tests/fixtures/analysis/``
    declare their own table with their own file name here).
``kind``
    ``"lock"`` | ``"rlock"`` | ``"condition"``.  LWC015 flags lexical
    re-acquisition of a ``"lock"`` (self-deadlock); the witness allows
    re-entrant acquire only for ``"rlock"``/``"condition"``.
``guards``
    The instance fields this lock protects.  LWC014 flags any
    read/write of one of these outside a ``with <lock>`` scope once the
    field is reachable from >= 2 thread entry points.  Fields NOT
    listed are intentionally unguarded (construction-time config,
    single-thread state, or benign monotonic flags) — the table is the
    place that intent is recorded.
``acquire_via``
    Method names whose call inside a ``with`` acquires this lock
    indirectly — the shape gate's ``shared()``/``exclusive()``
    contextmanagers and the batcher-facing ``dispatch_guard()`` alias.
``long_held``
    True for the reader/writer shape gate: its shared side is DESIGNED
    to be held across an entire device staging (including tokenizer
    waits and the PJRT enqueue), so LWC016's held-across-blocking check
    exempts it.  The underlying ``Condition`` is only ever held for the
    bookkeeping instants inside the gate.

``order`` declares the acquisition-order DAG edges the static analysis
(LWC015) must observe — a declared edge the lock-acquisition graph no
longer contains is stale and fails, an observed edge missing here
fails, and any cycle over declared + observed edges fails.
``order_runtime`` declares edges only the runtime witness can see
(paths the static call graph cannot resolve, e.g. through callbacks
installed at serve-build time); each carries its reason.  The witness
validates real interleavings against the union of both.

Unguarded-by-design notes (fields deliberately absent from ``guards``):

* ``MeshFaultManager._rungs`` — built once (idempotent ``build_ladder``
  at construction/first-downsize) and append-free afterwards; readers
  index an immutable list.
* ``MeshFaultManager.rescale_hooks`` / ``probe_fn`` / ``fault_plan`` —
  wired at serve-build time before any dispatch thread exists.
* ``DeviceBatcher._use_fallback`` — a benign monotonic bool flag read
  by the dispatch hot path; a torn read costs one routed-then-retried
  dispatch, never corruption.
* ``DeviceWatchdog._thread`` / ``_stop`` — monitor-thread lifecycle,
  mutated only from the owning (loop) side in ``start``/``stop``.
* ``StagingPool.per_bucket`` — construction-time capacity config; the
  batcher sizes it before the first dispatch thread starts.
"""

CONCURRENCY_MODEL = {
    "locks": {
        "PhaseAggregator._lock": {
            "module": "llm_weighted_consensus_tpu/obs/phases.py",
            "kind": "lock",
            "guards": ("_phases", "_device", "_intervals"),
        },
        "QualityAggregator._lock": {
            "module": "llm_weighted_consensus_tpu/obs/quality.py",
            "kind": "lock",
            "guards": (
                "_judges",
                "_pairs",
                "_margin",
                "_outcomes",
                "_requests",
                "_exemplar",
                "window",
                "drift_threshold",
            ),
        },
        "StagingPool._lock": {
            "module": "llm_weighted_consensus_tpu/models/dispatch_seam.py",
            "kind": "lock",
            "guards": ("_free", "hits", "misses"),
        },
        "DeviceWatchdog._lock": {
            "module": "llm_weighted_consensus_tpu/resilience/watchdog.py",
            "kind": "lock",
            "guards": (
                "_active",
                "_seq",
                "_healthy",
                "trips",
                "recoveries",
                "dispatches",
                "_last_overdue_ms",
                "_last_label",
            ),
        },
        "MemGuard._lock": {
            "module": "llm_weighted_consensus_tpu/resilience/memguard.py",
            "kind": "lock",
            "guards": (
                "_level",
                "last_rss",
                "peak_rss",
                "soft_trips",
                "hard_trips",
                "recoveries",
            ),
        },
        "_ShapeGate._cond": {
            "module": "llm_weighted_consensus_tpu/resilience/meshfault.py",
            "kind": "condition",
            "guards": ("_readers", "_writer", "_writers_waiting"),
            "acquire_via": ("shared", "exclusive", "dispatch_guard"),
            "long_held": True,
        },
        "MeshFaultManager._lock": {
            "module": "llm_weighted_consensus_tpu/resilience/meshfault.py",
            "kind": "rlock",
            "guards": (
                "_rung_index",
                "_epoch",
                "_downsizes",
                "_upsizes",
                "_re_dispatches",
                "_probe_failures",
                "_consecutive_probe_failures",
                "_transient_streak",
                "_watchdog_overdue",
                "_faulted_devices",
                "_warned_blind_upsize",
            ),
        },
        "ChoiceIndexer._lock": {
            "module": "llm_weighted_consensus_tpu/utils/__init__.py",
            "kind": "lock",
            "guards": ("_counter", "_indices"),
        },
        "LockWitness._mu": {
            "module": "llm_weighted_consensus_tpu/analysis/witness.py",
            "kind": "lock",
            "guards": ("_edges", "_violations", "_acquisitions"),
        },
        "DeviceBatcher._stats_lock": {
            "module": "llm_weighted_consensus_tpu/serve/batcher.py",
            "kind": "lock",
            "guards": (
                "_pack_real_tokens",
                "_pack_slot_tokens",
                "_pad_real_tokens",
                "_pad_slot_tokens",
                "prefix_dedup_hits",
                "prefix_dedup_tokens_saved",
                "packed_fallback_items",
                "_packed_occupancy",
                "fallback_dispatches",
            ),
        },
    },
    # static acquisition-order DAG: "u before v" — LWC015 enforces these
    # both ways against the with/acquire graph and fails on any cycle
    "order": (
        # downsize/try_recover/warm_ladder take the gate's exclusive
        # side, then the manager lock for the rung/epoch bookkeeping;
        # maybe_inject draws the fault plan under the manager lock while
        # the dispatch thread holds the gate's shared side
        ("_ShapeGate._cond", "MeshFaultManager._lock"),
        # the dispatch path stages padded rows into the staging pool
        # while holding the gate's shared side
        ("_ShapeGate._cond", "StagingPool._lock"),
        # pack-plan/device phase observations land in the phase
        # aggregator from inside the guarded dispatch
        ("_ShapeGate._cond", "PhaseAggregator._lock"),
        # occupancy/padding counters update under the batcher's stats
        # lock from inside the guarded dispatch
        ("_ShapeGate._cond", "DeviceBatcher._stats_lock"),
        # the guarded dispatch brackets device work with watchdog
        # begin/end, which take the watchdog lock
        ("_ShapeGate._cond", "DeviceWatchdog._lock"),
    ),
    # edges only real interleavings exercise (the static call graph
    # cannot resolve these paths); validated by the LockWitness
    "order_runtime": (),
}
