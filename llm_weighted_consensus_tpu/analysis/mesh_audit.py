"""Mesh-aware sharding & resource audit of the serving path (JXA006–011).

GSPMD sharding is propagated at trace time, which makes it *auditable*
at trace time: this module builds the first-class mesh embedder exactly
as ``serve/__main__.py`` does (``shard_embedder_mesh`` + ``aot_warmup``)
under a simulated v5e-8 mesh (8 virtual CPU devices via
``parallel/dist.py``'s ``--xla_force_host_platform_device_count``
plumbing, dp=4 × tp=2 by default) and audits the ACTUAL serving
executables in the embedder's AOT table — the same
``jit``-with-shardings callables the batcher dispatches, not a parallel
re-lowering that could drift from what serves traffic.  Only
``deberta.reward_packed`` keeps a fresh lowering (the reranker has no
AOT table; see ``_measure_reward_packed``).  Checked: the partition
plan, the collective plan, and the resource envelope, before a single
TPU chip is rented:

* **JXA006 rule coverage** — against the first-class partition-rule
  tables in ``parallel/sharding.py``, every param leaf of every audited
  tree (bert + deberta, full-precision + int8) matches EXACTLY one rule
  and every rule matches at least one leaf: no silently-replicated new
  param, no dead rule rotting in the table.
* **JXA007 oversized replication** — shape-only (``jax.eval_shape``)
  trees of the big real presets: any leaf above
  ``replicated_threshold_bytes`` whose spec replicates it across the
  mesh must have an explicit ``replicated_allowlist`` entry (with a
  written reason) in ``analysis/budgets.json``.
* **JXA008 collective plan** — the compiled HLO of every bucket
  contains the expected cross-device reduction (all-reduce /
  reduce-scatter / all-gather: the Megatron TP layout's two
  reductions per layer) and NONE of the forbidden ops: no all-to-all,
  no host transfer inside the hot path.
* **JXA009/JXA010 resource budgets** — per-bucket static HBM footprint
  (argument+output+temp bytes, XLA ``memory_analysis``) and
  flops / bytes-accessed (``cost_analysis``) compared against the
  committed ``analysis/budgets.json`` within a tolerance band; missing
  and stale entries fail too (``budgets.py``).
* **JXA011 numerical equivalence** — each warmed bucket is driven
  through the embedder's PUBLIC dispatch method against a same-seed
  single-device reference embedder on identical inputs; results must
  agree to float32 reduction-reordering tolerance, and a ``jit_stats``
  bracket asserts the dispatches really rode the audited executables
  (zero specialization growth).
* **JXA012 fault-ladder coverage** — the mesh fault-domain downsize
  ladder (``resilience/meshfault.py``): every fallback rung must hold a
  full AOT bucket set after ``warm_ladder`` (a missing rung bucket means
  a mid-incident downsize compiles under fire), and driving the public
  dispatch on each downsized rung must pass the JXA011 parity gate
  against the single-device reference with zero specialization growth.
  The ladder audit runs twice: once on the dense dp×tp mesh and once on
  the sp-bearing ring mesh (dp halves, tp AND sp preserved per rung,
  ring buckets pre-warmed under each rung's ``("mesh", dp, tp, sp)``
  namespace).
* **JXA013 roofline coverage** — every audited bucket must have a
  live row in ``analysis/roofline.json`` (flops / bytes-accessed plus
  per-chip backend peaks) so the serving gauge can report speed-of-light
  attainment; missing rows, stale rows, drifted figures, and bad peaks
  all fail (``roofline.py``).

Device plumbing: the checks need ``dp*tp`` devices.  Under tier-1
pytest the conftest already forces 8 virtual CPU devices, so everything
runs in-process; the bare CLI process has one device, so
``run_mesh_audit`` respawns itself as a subprocess with
``force_cpu_env`` — the same recipe the DCN smoke uses.

The long-context ring (sequence-parallel) serving path is audited on
an sp-bearing sibling mesh: the sp axis folds out of dp
(``dp//sp × tp × sp``, default 2×2×2) so the device budget stays
``dp*tp``, and the warmed ``("ring", B, S)`` / ``("ring_vote", N, S)``
executables get the same JXA008–011 treatment — their figures land in
``budgets.json`` / ``roofline.json`` next to the dense buckets, and
JXA011 parity runs ring-vs-dense against the single-device reference.

Env knobs (all optional): ``ANALYSIS_MESH_MODEL`` (embedder preset,
default ``test-tiny``), ``ANALYSIS_MESH_DP`` / ``ANALYSIS_MESH_TP``
(mesh shape, default 4×2), ``ANALYSIS_MESH_SP`` (ring mesh sp axis,
default 2; 1 disables the ring audit), ``ANALYSIS_MESH_SPECS``
(``NxS`` list, default ``8x16``), ``ANALYSIS_MESH_R_BUCKETS`` (default
``2``), ``ANALYSIS_MESH_PACKED_BUCKETS`` (``BxLxK`` list, default
``8x64x8``), ``ANALYSIS_MESH_RING_BUCKETS`` (``NxS`` list, default
``2x64``; empty disables the ring audit), ``ANALYSIS_BUDGETS``
(budgets file override), ``ANALYSIS_ROOFLINE``
(roofline file override), ``ANALYSIS_SKIP_MESH=1``
to skip (honored by the CLI and scripts/t1.sh; tier-1 does not set it).

Re-baselining: ``python -m llm_weighted_consensus_tpu.analysis.mesh_audit
--write-budgets`` re-measures and rewrites ``budgets.json`` (tolerance,
threshold, and allowlist preserved); ``--write-roofline`` does the same
for ``roofline.json`` (peaks and tolerance preserved); review the diff.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .budgets import (
    allowlisted,
    check_allowlist_stale,
    compare_budgets,
    default_budgets_path,
    load_budgets,
    replicated_allowlist,
    replicated_threshold,
)
from .engine import Finding
from .roofline import (
    compare_roofline,
    default_roofline_path,
    load_roofline,
    write_roofline,
)

_DEFAULT_MODEL = "test-tiny"
_DEFAULT_RM_MODEL = "deberta-test-tiny"
_DEFAULT_DP, _DEFAULT_TP = 4, 2
_DEFAULT_SPECS = ((8, 16),)
_DEFAULT_R_BUCKETS = (2,)
_DEFAULT_PACKED_BUCKETS = ((8, 64, 8),)
# the long-context ring audit folds the sp axis out of dp (dp//sp x tp
# x sp) so the device budget stays dp*tp; sp=2 over the default 4x2
# mesh gives the 2x2x2 sp-bearing shape serve/__main__.py would build
# from MESH_SHAPE=2x2x2
_DEFAULT_SP = 2
_DEFAULT_RING_BUCKETS = ((2, 64),)

# shape-only presets for the coverage/replication checks: the BIG trees,
# because that is where an accidentally replicated table costs real HBM
_COVERAGE_PRESETS = ("bge-large-en",)
_COVERAGE_RM_PRESETS = ("deberta-v3-base",)

# the reduction the Megatron TP layout must insert, and the ops the
# serving path must never contain (an all-to-all means a layout went
# resharding-crazy; a host transfer stalls the whole dispatch)
EXPECTED_COLLECTIVES = (r"all-reduce|reduce-scatter|all-gather",)
FORBIDDEN_COLLECTIVES = (r"all-to-all", r"is_host_transfer=true")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    return int(raw) if raw.strip() else default


def _env_mesh() -> Tuple[int, int]:
    return (
        _env_int("ANALYSIS_MESH_DP", _DEFAULT_DP),
        _env_int("ANALYSIS_MESH_TP", _DEFAULT_TP),
    )


def _env_model() -> str:
    return os.environ.get("ANALYSIS_MESH_MODEL", "") or _DEFAULT_MODEL


def _env_specs() -> Tuple[Tuple[int, int], ...]:
    raw = os.environ.get("ANALYSIS_MESH_SPECS", "")
    if not raw.strip():
        return _DEFAULT_SPECS
    return tuple(
        tuple(int(x) for x in part.strip().lower().split("x"))
        for part in raw.split(",")
        if part.strip()
    )


def _env_r_buckets() -> Tuple[int, ...]:
    raw = os.environ.get("ANALYSIS_MESH_R_BUCKETS", "")
    if not raw.strip():
        return _DEFAULT_R_BUCKETS
    return tuple(int(p) for p in raw.split(",") if p.strip())


def _env_packed_buckets() -> Tuple[Tuple[int, int, int], ...]:
    raw = os.environ.get("ANALYSIS_MESH_PACKED_BUCKETS")
    if raw is None or not raw.strip():
        return _DEFAULT_PACKED_BUCKETS
    return tuple(
        tuple(int(x) for x in part.strip().lower().split("x"))
        for part in raw.split(",")
        if part.strip()
    )


def _env_sp() -> int:
    return _env_int("ANALYSIS_MESH_SP", _DEFAULT_SP)


def _env_ring_buckets() -> Tuple[Tuple[int, int], ...]:
    """``NxS`` long-context ring buckets; an explicitly empty
    ``ANALYSIS_MESH_RING_BUCKETS`` disables the ring audit."""
    raw = os.environ.get("ANALYSIS_MESH_RING_BUCKETS")
    if raw is None:
        return _DEFAULT_RING_BUCKETS
    return tuple(
        tuple(int(x) for x in part.strip().lower().split("x"))
        for part in raw.split(",")
        if part.strip()
    )


def _ring_enabled() -> bool:
    dp, tp = _env_mesh()
    sp = _env_sp()
    return bool(_env_ring_buckets()) and sp > 1 and dp % sp == 0


def _budgets_path() -> Path:
    raw = os.environ.get("ANALYSIS_BUDGETS", "")
    return Path(raw) if raw.strip() else default_budgets_path()


def _roofline_path() -> Path:
    raw = os.environ.get("ANALYSIS_ROOFLINE", "")
    return Path(raw) if raw.strip() else default_roofline_path()


def _scope() -> dict:
    dp, tp = _env_mesh()
    return {
        "model": _env_model(),
        "rm_model": _DEFAULT_RM_MODEL,
        "dp": dp,
        "tp": tp,
        "specs": ["x".join(map(str, s)) for s in _env_specs()],
        "r_buckets": list(_env_r_buckets()),
        "packed_buckets": [
            "x".join(map(str, b)) for b in _env_packed_buckets()
        ],
        "sp": _env_sp(),
        "ring_buckets": [
            "x".join(map(str, b)) for b in _env_ring_buckets()
        ],
    }


# ---------------------------------------------------------------------------
# JXA006/JXA007 — partition-rule coverage and replication policy
# ---------------------------------------------------------------------------


def audit_rule_coverage(rules, tree, label: str) -> List[Finding]:
    """JXA006: every leaf exactly one rule; every rule at least one leaf."""
    from ..parallel.sharding import match_report

    findings: List[Finding] = []
    leaf_matches, rule_counts = match_report(rules, tree)
    for path, hits in sorted(leaf_matches.items()):
        if len(hits) == 0:
            findings.append(
                Finding(
                    rule="JXA006",
                    path=f"mesh:{label}",
                    line=0,
                    symbol=path,
                    message=(
                        f"param leaf `{path}` matches NO partition rule: "
                        "it would silently fall back to whatever XLA "
                        "propagates — add a rule (or fix the pattern)"
                    ),
                )
            )
        elif len(hits) > 1:
            findings.append(
                Finding(
                    rule="JXA006",
                    path=f"mesh:{label}",
                    line=0,
                    symbol=path,
                    message=(
                        f"param leaf `{path}` matches {len(hits)} rules "
                        f"({', '.join(hits)}): ambiguous — first-match-"
                        "wins hides whichever layout lost"
                    ),
                )
            )
    for name, count in rule_counts.items():
        if count == 0:
            findings.append(
                Finding(
                    rule="JXA006",
                    path=f"mesh:{label}",
                    line=0,
                    symbol=name,
                    message=(
                        f"partition rule `{name}` matches no param leaf: "
                        "a dead rule is a layout decision nobody audits "
                        "— delete it or fix its pattern"
                    ),
                )
            )
    return findings


def audit_replication(
    rules,
    tree,
    label: str,
    threshold_bytes: int,
    allowlist: Sequence[dict],
) -> Tuple[List[Finding], Set[str]]:
    """JXA007: no leaf above the size threshold replicated across the
    mesh without an explicit allowlist entry.  Returns the findings and
    the set of allowlist patterns that earned their keep."""
    from ..parallel.sharding import match_partition_rules, tree_path_leaves

    findings: List[Finding] = []
    matched_patterns: Set[str] = set()
    try:
        spec_tree = match_partition_rules(rules, tree)
    except ValueError:
        # JXA006 owns uncovered leaves; nothing to size-check here
        return findings, matched_patterns
    specs = dict(tree_path_leaves(spec_tree))
    for path, leaf in tree_path_leaves(tree):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            continue
        size = int(dtype.itemsize)
        for dim in shape:
            size *= int(dim)
        if size <= threshold_bytes:
            continue
        spec = specs[path]
        if any(axis is not None for axis in spec):
            continue  # sharded somewhere: not replicated
        pattern = allowlisted(path, allowlist)
        if pattern is not None:
            matched_patterns.add(pattern)
            continue
        findings.append(
            Finding(
                rule="JXA007",
                path=f"mesh:{label}",
                line=0,
                symbol=path,
                message=(
                    f"`{path}` ({size} bytes, {'x'.join(map(str, shape))} "
                    f"{dtype}) is fully replicated and above the "
                    f"{threshold_bytes}-byte threshold: shard it or add "
                    "a replicated_allowlist entry with a reason to "
                    "analysis/budgets.json"
                ),
            )
        )
    return findings, matched_patterns


def _shape_trees():
    """(label, rules, shape-only tree) for every audited param layout —
    big real presets, full-precision and int8, bert and deberta."""
    import jax

    from ..models import bert, deberta, quant
    from ..models.configs import PRESETS
    from ..models.reranker import RM_PRESETS
    from ..parallel.sharding import (
        bert_partition_rules,
        deberta_partition_rules,
    )

    rng = jax.random.PRNGKey(0)
    out = []
    for preset in _COVERAGE_PRESETS:
        config = PRESETS[preset]
        tree = jax.eval_shape(lambda c=config: bert.init_params(rng, c))
        out.append((f"bert:{preset}", bert_partition_rules(), tree))
        qtree = jax.eval_shape(
            lambda c=config: quant.quantize_bert_params(
                bert.init_params(rng, c)
            )
        )
        out.append(
            (
                f"bert:{preset}:int8",
                bert_partition_rules(quantized=True),
                qtree,
            )
        )
    for preset in _COVERAGE_RM_PRESETS:
        config = RM_PRESETS[preset]
        tree = jax.eval_shape(
            lambda c=config: deberta.init_params(rng, c)
        )
        out.append((f"deberta:{preset}", deberta_partition_rules(), tree))
        qtree = jax.eval_shape(
            lambda c=config: quant.quantize_deberta_params(
                deberta.init_params(rng, c)
            )
        )
        out.append(
            (
                f"deberta:{preset}:int8",
                deberta_partition_rules(quantized=True),
                qtree,
            )
        )
    return out


# ---------------------------------------------------------------------------
# JXA008 — the collective plan, as a pure function over HLO text
# ---------------------------------------------------------------------------


def audit_hlo_collectives(
    hlo_text: str,
    label: str,
    expect: Sequence[str] = EXPECTED_COLLECTIVES,
    forbid: Sequence[str] = FORBIDDEN_COLLECTIVES,
) -> List[Finding]:
    """Each ``expect`` regex must match the compiled HLO at least once
    (the sharded layout really inserted its reduction); each ``forbid``
    regex must match zero times."""
    import re

    findings: List[Finding] = []
    for pattern in expect:
        if re.search(pattern, hlo_text) is None:
            findings.append(
                Finding(
                    rule="JXA008",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        f"expected collective `{pattern}` absent from the "
                        "lowered HLO: the TP layout degenerated (params "
                        "replicated instead of split?) — the mesh buys "
                        "nothing"
                    ),
                )
            )
    for pattern in forbid:
        match = re.search(pattern, hlo_text)
        if match is not None:
            findings.append(
                Finding(
                    rule="JXA008",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        f"forbidden op `{match.group(0)}` in the lowered "
                        "HLO: an all-to-all / host transfer inside the "
                        "serving hot path wedges scarce interconnect "
                        "(the BENCH_r04/r05 failure class)"
                    ),
                )
            )
    return findings


# ---------------------------------------------------------------------------
# JXA008–011 — lower/compile every bucket on the simulated mesh
# ---------------------------------------------------------------------------


def _exe_figures(exe) -> Dict[str, float]:
    """The budget/roofline figures of one compiled executable: static
    HBM footprint (``memory_analysis``) plus flops / bytes-accessed
    (``cost_analysis``) — the shared measurement for every audited
    bucket (padded, packed, ring, reward)."""
    mem = exe.memory_analysis()
    figures = {
        "hbm_bytes": float(
            mem.argument_size_in_bytes
            + mem.output_size_in_bytes
            + mem.temp_size_in_bytes
        ),
    }
    cost = exe.cost_analysis()
    cost0 = cost[0] if isinstance(cost, (list, tuple)) else cost
    figures["flops"] = float(cost0.get("flops", 0.0))
    figures["bytes_accessed"] = float(cost0.get("bytes accessed", 0.0))
    return figures


def _packed_inputs(rng, vocab: int, b: int, l: int, k: int):
    import numpy as np

    pids = np.zeros((b, l), np.int32)
    pseg = np.zeros((b, l), np.int32)
    ppos = np.zeros((b, l), np.int32)
    pstarts = np.zeros((b, k), np.int32)
    for row in range(b):
        n0, n1 = 5 + row % 3, 3
        pids[row, : n0 + n1] = rng.integers(3, vocab, n0 + n1)
        pseg[row, :n0] = 1
        pseg[row, n0 : n0 + n1] = 2
        ppos[row, :n0] = np.arange(n0)
        ppos[row, n0 : n0 + n1] = np.arange(n1)
        pstarts[row, 1] = n0
    return pids, pseg, ppos, pstarts


def audit_serving_executables(
    embedder, ref, specs, r_buckets, packed_buckets
) -> Tuple[List[Finding], Dict[str, Dict[str, float]]]:
    """JXA008–011 against a warmed mesh embedder's AOT table — the very
    ``jit``-with-shardings executables the batcher dispatches, not a
    parallel re-lowering that could drift from what serves traffic.

    Per bucket: JXA008/009/010 read the committed executable straight
    out of ``embedder._aot`` (a missing bucket is itself a finding —
    lazy jit at serve time breaks the zero-specialization contract).
    JXA011 then drives the PUBLIC dispatch method end-to-end against a
    same-seed single-device reference embedder, and the whole dispatch
    block is bracketed by ``jit_stats`` snapshots: any specialization
    growth means the dispatches bypassed the audited executables, which
    would make the audit above vacuous — also a finding.

    ``ref`` must be the same preset/seed left single-device; its
    dispatches run BEFORE the snapshot bracket because the module-level
    jit caches are shared across embedder instances.
    """
    import numpy as np

    from ..models.embedder import _bucket, _seq_bucket

    findings: List[Finding] = []
    measured: Dict[str, Dict[str, float]] = {}
    bm = embedder.batch_multiple
    rng = np.random.default_rng(0)
    vocab = embedder.config.vocab_size
    temp = 1.0
    atol = 1e-4

    def account(label, key):
        exe = embedder._aot.get(embedder._aot_key(key))
        if exe is None:
            findings.append(
                Finding(
                    rule="JXA008",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        f"no AOT executable at serving bucket {key}: "
                        "aot_warmup did not cover it, so mesh traffic at "
                        "this bucket would lazily jit mid-request (the "
                        "zero-specialization contract breaks)"
                    ),
                )
            )
            return
        findings.extend(audit_hlo_collectives(exe.as_text(), label))
        measured[label] = _exe_figures(exe)

    def check(label, got, want):
        got, want = np.asarray(got), np.asarray(want)
        if not np.allclose(got, want, atol=atol, rtol=1e-4):
            worst = float(np.max(np.abs(got - want)))
            findings.append(
                Finding(
                    rule="JXA011",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        "mesh dispatch diverges from the single-device "
                        f"reference (max abs diff {worst:.2e} > {atol}): "
                        "the partition plan changed the math, not just "
                        "the layout"
                    ),
                )
            )

    # Build every input and its single-device reference output FIRST:
    # the reference dispatches specialize the SHARED module-level jit
    # caches, and the zero-growth bracket below must see mesh traffic
    # only.
    cases = []  # (kind, label, aot bucket key, np inputs, ref output)
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        ids = rng.integers(3, vocab, (n, s)).astype(np.int32)
        mask = np.ones((n, s), np.int32)
        ref_out = np.asarray(
            ref.consensus_confidence_tokens(ids, mask, temperature=temp)
        )
        cases.append(
            ("vote1", f"vote1(n={n},s={s})", ("vote1", n, s),
             (ids, mask), ref_out)
        )

        pad_b = _bucket(n, embedder.MAX_DEVICE_BATCH)
        pad_b += (-pad_b) % bm
        bids = rng.integers(3, vocab, (pad_b, s)).astype(np.int32)
        bmask = np.ones((pad_b, s), np.int32)
        ref_out = np.asarray(ref.embed_tokens(bids, bmask))
        cases.append(
            ("embed", f"embed(b={pad_b},s={s})", ("embed", pad_b, s),
             (bids, bmask), ref_out)
        )

        for r in r_buckets:
            if r < 2:
                continue
            gids = rng.integers(3, vocab, (r, n, s)).astype(np.int32)
            gmask = np.ones((r, n, s), np.int32)
            ref_out = np.asarray(
                ref.consensus_confidence_tokens_many(
                    gids, gmask, temperature=temp
                )
            )
            cases.append(
                ("many", f"many(r={r},n={n},s={s})", ("many", r, n, s),
                 (gids, gmask), ref_out)
            )

    for b, l, k in packed_buckets:
        pids, pseg, ppos, pstarts = _packed_inputs(rng, vocab, b, l, k)
        ref_out = np.asarray(ref.embed_packed(pids, pseg, ppos, pstarts))
        pb = b + (-b) % bm  # the dispatch pads rows to the dp multiple
        cases.append(
            ("packed", f"packed(b={pb},l={l},k={k})",
             ("packed", pb, l, k), (pids, pseg, ppos, pstarts), ref_out)
        )

    before = embedder.jit_stats()["specializations"]
    for kind, label, key, args, ref_out in cases:
        account(label, key)
        if kind == "vote1":
            got = embedder.consensus_confidence_tokens(
                args[0], args[1], temperature=temp
            )
        elif kind == "embed":
            got = embedder.embed_tokens(*args)
        elif kind == "many":
            got = embedder.consensus_confidence_tokens_many(
                args[0], args[1], temperature=temp
            )
        else:
            got = embedder.embed_packed(*args)
        check(label, got, ref_out)
    after = embedder.jit_stats()["specializations"]
    grew = {
        name: f"{before.get(name, 0)}->{count}"
        for name, count in after.items()
        if count > before.get(name, 0)
    }
    if grew:
        findings.append(
            Finding(
                rule="JXA008",
                path="mesh:dispatch",
                line=0,
                message=(
                    "mesh dispatches bypassed the audited AOT executables "
                    f"and lazily jitted instead ({grew}): the bucket "
                    "figures above describe executables that served no "
                    "traffic"
                ),
            )
        )
    return findings, measured


def _measure_buckets(
    model: str, dp: int, tp: int, specs, r_buckets, packed_buckets
) -> Tuple[List[Finding], Dict[str, Dict[str, float]]]:
    """Build the first-class mesh embedder exactly as serve/__main__.py
    does — ``shard_embedder_mesh`` + ``aot_warmup`` — then audit its AOT
    table (``audit_serving_executables``) and the reward model's packed
    lowering."""
    import numpy as np

    from ..models.embedder import TpuEmbedder
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import shard_embedder_mesh

    mesh = make_mesh(dp=dp, tp=tp)
    # the JXA011 oracle: same preset + seed, left single-device
    ref = TpuEmbedder(model, max_tokens=64, seed=0, quantize="none")
    embedder = TpuEmbedder(model, max_tokens=64, seed=0, quantize="none")
    shard_embedder_mesh(embedder, mesh)
    embedder.aot_warmup(
        list(specs),
        r_buckets=[r for r in r_buckets if r >= 2],
        packed_buckets=list(packed_buckets),
    )
    findings, measured = audit_serving_executables(
        embedder, ref, specs, r_buckets, packed_buckets
    )
    rm_findings, rm_measured = _measure_reward_packed(mesh, packed_buckets)
    findings += rm_findings
    measured.update(rm_measured)
    return findings, measured


def _audit_fault_ladder(
    model: str, dp: int, tp: int, specs, r_buckets, packed_buckets
) -> List[Finding]:
    """JXA012: walk the MeshFaultManager downsize ladder as an incident
    would — warm it, then downsize rung by rung — and on every fallback
    rung assert (a) each serving bucket has a committed AOT executable
    under that rung's ``("mesh", dp, tp)`` namespace and (b) the public
    dispatch agrees with the single-device reference with zero jit
    growth (the JXA011 gate, re-applied to the degraded shapes).  A
    rung that fails either check means the fault path itself is the
    outage: a downsize mid-incident would compile — or worse, compute
    wrong numbers — exactly when the service can least afford it."""
    import numpy as np

    from ..models.embedder import TpuEmbedder, _bucket, _seq_bucket
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import shard_embedder_mesh
    from ..resilience import MeshFaultManager

    findings: List[Finding] = []
    ref = TpuEmbedder(model, max_tokens=64, seed=0, quantize="none")
    embedder = TpuEmbedder(model, max_tokens=64, seed=0, quantize="none")
    shard_embedder_mesh(embedder, make_mesh(dp=dp, tp=tp))
    manager = MeshFaultManager(embedder, shape=(dp, tp))
    r2 = [r for r in r_buckets if r >= 2]
    manager.warm_ladder(list(specs), r2, list(packed_buckets))

    rng = np.random.default_rng(0)
    vocab = embedder.config.vocab_size
    atol = 1e-4
    # reference outputs FIRST: the module-level jit caches are shared, so
    # the zero-growth brackets below must see rung traffic only
    cases = []
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        ids = rng.integers(3, vocab, (n, s)).astype(np.int32)
        mask = np.ones((n, s), np.int32)
        ref_out = np.asarray(ref.consensus_confidence_tokens(ids, mask))
        cases.append((n, s, ids, mask, ref_out))

    for rung_dp, rung_tp in manager.build_ladder()[1:]:
        label = f"ladder:{rung_dp}x{rung_tp}"
        if not manager.downsize():
            findings.append(
                Finding(
                    rule="JXA012",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        "downsize() refused a declared ladder rung: the "
                        "ladder the manager walks is not the ladder it "
                        "declared"
                    ),
                )
            )
            break
        # (a) full AOT bucket coverage under this rung's key namespace
        bm = embedder.batch_multiple
        keys = []
        for n, s in specs:
            s = _seq_bucket(s, embedder.max_tokens)
            keys.append(("vote1", n, s))
            pad_b = _bucket(n, embedder.MAX_DEVICE_BATCH)
            pad_b += (-pad_b) % bm
            keys.append(("embed", pad_b, s))
            keys.extend(("many", r, n, s) for r in r2)
        for b, l, k in packed_buckets:
            pb = b + (-b) % bm
            keys.append(("packed", pb, l, k))
        for key in keys:
            if embedder._aot.get(embedder._aot_key(key)) is None:
                findings.append(
                    Finding(
                        rule="JXA012",
                        path=f"mesh:{label}",
                        line=0,
                        message=(
                            f"no AOT executable at fallback-rung bucket "
                            f"{key}: warm_ladder did not cover it, so a "
                            f"downsize to {rung_dp}x{rung_tp} would "
                            "compile mid-incident"
                        ),
                    )
                )
        # (b) parity + zero growth through the public dispatch ON the rung
        before = embedder.jit_stats()["specializations"]
        for n, s, ids, mask, ref_out in cases:
            got = np.asarray(embedder.consensus_confidence_tokens(ids, mask))
            if not np.allclose(got, ref_out, atol=atol, rtol=1e-4):
                worst = float(np.max(np.abs(got - ref_out)))
                findings.append(
                    Finding(
                        rule="JXA012",
                        path=f"mesh:{label}",
                        line=0,
                        message=(
                            "degraded-rung dispatch diverges from the "
                            "single-device reference (max abs diff "
                            f"{worst:.2e} > {atol}): the re-dispatched "
                            "answers after a real downsize would be wrong"
                        ),
                    )
                )
        after = embedder.jit_stats()["specializations"]
        grew = {
            name: f"{before.get(name, 0)}->{count}"
            for name, count in after.items()
            if count > before.get(name, 0)
        }
        if grew:
            findings.append(
                Finding(
                    rule="JXA012",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        "rung dispatches bypassed the warmed executables "
                        f"and lazily jitted instead ({grew})"
                    ),
                )
            )
    return findings


def _ring_bucket_keys(embedder, ring_buckets):
    """The (label, AOT sub-key) pairs ``aot_warmup(...,
    ring_buckets=...)`` lands for a warmed sp-mesh embedder — snapped
    through the same sequence-bucket + sp-multiple rounding the warmup
    and the dispatch both apply, so the audit checks the keys that
    actually serve."""
    from ..models.embedder import _bucket, _seq_bucket

    sp = embedder.mesh_sp
    bm = embedder.batch_multiple
    out = []
    for n, s in ring_buckets:
        s = _seq_bucket(s, embedder.ring_max_tokens)
        s = min(s + (-s) % sp, embedder.ring_max_tokens)
        out.append((f"ring_vote(n={n},s={s})", ("ring_vote", n, s)))
        pad_b = _bucket(n, embedder.MAX_DEVICE_BATCH)
        pad_b += (-pad_b) % bm
        out.append((f"ring(b={pad_b},s={s})", ("ring", pad_b, s)))
    return out


def _measure_ring_buckets(
    model: str, dp: int, tp: int, sp: int, ring_buckets
) -> Tuple[List[Finding], Dict[str, Dict[str, float]]]:
    """JXA008–011 over the long-context ring (sequence-parallel)
    buckets: build the sp-bearing mesh embedder exactly as
    serve/__main__.py does from ``MESH_SHAPE=dpxTPxSP`` +
    ``LONG_CONTEXT_WARMUP`` (the sp axis folds out of dp so the device
    budget stays ``dp*tp``), then audit the warmed ring executables —
    collective plan and resource figures straight off the AOT table,
    and ring-vs-dense parity through the PUBLIC ring dispatch against a
    same-seed single-device reference (the ring rotation must be a
    layout change, not a math change), bracketed by the usual
    zero-specialization guard."""
    import numpy as np

    from ..models.embedder import TpuEmbedder
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import shard_embedder_mesh

    findings: List[Finding] = []
    measured: Dict[str, Dict[str, float]] = {}
    mesh = make_mesh(dp=dp // sp, tp=tp, sp=sp)
    ref = TpuEmbedder(model, max_tokens=64, seed=0, quantize="none")
    embedder = TpuEmbedder(model, max_tokens=64, seed=0, quantize="none")
    shard_embedder_mesh(embedder, mesh)
    embedder.aot_warmup([], ring_buckets=list(ring_buckets))

    rng = np.random.default_rng(3)
    vocab = embedder.config.vocab_size
    temp = 1.0
    atol = 1e-4

    def account(label, key):
        exe = embedder._aot.get(embedder._ring_aot_key(key))
        if exe is None:
            findings.append(
                Finding(
                    rule="JXA008",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        f"no AOT executable at ring bucket {key}: "
                        "aot_warmup(ring_buckets=...) did not cover it, "
                        "so long-context traffic at this bucket would "
                        "lazily jit mid-request"
                    ),
                )
            )
            return
        findings.extend(audit_hlo_collectives(exe.as_text(), label))
        measured[label] = _exe_figures(exe)

    # inputs + single-device DENSE reference outputs first (shared jit
    # caches; the zero-growth bracket below must see ring traffic only)
    cases = []
    for label, key in _ring_bucket_keys(embedder, ring_buckets):
        kind, s = key[0], key[-1]
        if kind == "ring_vote":
            n = key[1]
            ids = rng.integers(3, vocab, (n, s)).astype(np.int32)
            mask = np.ones((n, s), np.int32)
            ref_out = np.asarray(
                ref.consensus_confidence_tokens(ids, mask, temperature=temp)
            )
        else:
            pad_b = key[1]
            ids = rng.integers(3, vocab, (pad_b, s)).astype(np.int32)
            mask = np.ones((pad_b, s), np.int32)
            ref_out = np.asarray(ref.embed_tokens(ids, mask))
        cases.append((kind, label, key, (ids, mask), ref_out))

    before = embedder.jit_stats()["specializations"]
    for kind, label, key, args, ref_out in cases:
        account(label, key)
        if kind == "ring_vote":
            got = embedder.consensus_confidence_tokens_ring(
                args[0], args[1], temperature=temp
            )
        else:
            got = embedder.embed_tokens_ring(*args)
        got = np.asarray(got)
        if not np.allclose(got, ref_out, atol=atol, rtol=1e-4):
            worst = float(np.max(np.abs(got - ref_out)))
            findings.append(
                Finding(
                    rule="JXA011",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        "ring dispatch diverges from the single-device "
                        f"dense reference (max abs diff {worst:.2e} > "
                        f"{atol}): the sequence rotation changed the "
                        "math, not just the layout"
                    ),
                )
            )
    after = embedder.jit_stats()["specializations"]
    grew = {
        name: f"{before.get(name, 0)}->{count}"
        for name, count in after.items()
        if count > before.get(name, 0)
    }
    if grew:
        findings.append(
            Finding(
                rule="JXA008",
                path="mesh:ring-dispatch",
                line=0,
                message=(
                    "ring dispatches bypassed the audited AOT "
                    f"executables and lazily jitted instead ({grew}): "
                    "the ring bucket figures above describe executables "
                    "that served no traffic"
                ),
            )
        )
    return findings, measured


def _audit_ring_fault_ladder(
    model: str, dp: int, tp: int, sp: int, ring_buckets
) -> List[Finding]:
    """JXA012 on the sp-bearing mesh: walk the downsize ladder of a
    ring-serving embedder (dp halves, tp AND sp preserved per rung) and
    on every fallback rung assert the ring buckets were pre-warmed
    under that rung's ``("mesh", dp, tp, sp)`` namespace and that the
    public ring dispatch still matches the single-device dense
    reference with zero jit growth — a downsize mid-incident must not
    compile a ring executable or corrupt a long-context answer."""
    import numpy as np

    from ..models.embedder import TpuEmbedder
    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import shard_embedder_mesh
    from ..resilience import MeshFaultManager

    findings: List[Finding] = []
    rdp = dp // sp
    if rdp < 2:
        return findings  # no rung below the full shape to walk
    ref = TpuEmbedder(model, max_tokens=64, seed=0, quantize="none")
    embedder = TpuEmbedder(model, max_tokens=64, seed=0, quantize="none")
    shard_embedder_mesh(embedder, make_mesh(dp=rdp, tp=tp, sp=sp))
    manager = MeshFaultManager(embedder, shape=(rdp, tp))
    manager.warm_ladder([], ring_buckets=list(ring_buckets))

    rng = np.random.default_rng(5)
    vocab = embedder.config.vocab_size
    atol = 1e-4
    cases = []
    for label, key in _ring_bucket_keys(embedder, ring_buckets):
        if key[0] != "ring_vote":
            continue
        n, s = key[1], key[2]
        ids = rng.integers(3, vocab, (n, s)).astype(np.int32)
        mask = np.ones((n, s), np.int32)
        ref_out = np.asarray(ref.consensus_confidence_tokens(ids, mask))
        cases.append((n, s, ids, mask, ref_out))

    for rung_dp, rung_tp in manager.build_ladder()[1:]:
        label = f"ring-ladder:{rung_dp}x{rung_tp}x{sp}"
        if not manager.downsize():
            findings.append(
                Finding(
                    rule="JXA012",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        "downsize() refused a declared ladder rung on "
                        "the sp-bearing mesh: the ladder the manager "
                        "walks is not the ladder it declared"
                    ),
                )
            )
            break
        for _blabel, key in _ring_bucket_keys(embedder, ring_buckets):
            if embedder._aot.get(embedder._ring_aot_key(key)) is None:
                findings.append(
                    Finding(
                        rule="JXA012",
                        path=f"mesh:{label}",
                        line=0,
                        message=(
                            f"no AOT executable at fallback-rung ring "
                            f"bucket {key}: warm_ladder did not cover "
                            f"it, so a downsize to {rung_dp}x{rung_tp}"
                            f"x{sp} would compile a long-context "
                            "executable mid-incident"
                        ),
                    )
                )
        before = embedder.jit_stats()["specializations"]
        for n, s, ids, mask, ref_out in cases:
            got = np.asarray(
                embedder.consensus_confidence_tokens_ring(ids, mask)
            )
            if not np.allclose(got, ref_out, atol=atol, rtol=1e-4):
                worst = float(np.max(np.abs(got - ref_out)))
                findings.append(
                    Finding(
                        rule="JXA012",
                        path=f"mesh:{label}",
                        line=0,
                        message=(
                            "degraded-rung ring dispatch diverges from "
                            "the single-device dense reference (max abs "
                            f"diff {worst:.2e} > {atol}): re-dispatched "
                            "long-context answers after a real downsize "
                            "would be wrong"
                        ),
                    )
                )
        after = embedder.jit_stats()["specializations"]
        grew = {
            name: f"{before.get(name, 0)}->{count}"
            for name, count in after.items()
            if count > before.get(name, 0)
        }
        if grew:
            findings.append(
                Finding(
                    rule="JXA012",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        "rung ring dispatches bypassed the warmed "
                        f"executables and lazily jitted instead ({grew})"
                    ),
                )
            )
    return findings


def _measure_reward_packed(
    mesh, packed_buckets
) -> Tuple[List[Finding], Dict[str, Dict[str, float]]]:
    """The reward-model packed path, under the deberta rule table.

    Unlike the embedder buckets this IS a fresh lowering: the reranker
    has no AOT table (serving jits ``deberta.reward_packed`` lazily),
    so there is no committed executable to audit — the audit lowers the
    same entry point the reranker's jit would, under the same rule-table
    sharding."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..models import deberta
    from ..models.reranker import RM_PRESETS
    from ..parallel.sharding import deberta_partition_rules, shard_by_rules

    findings: List[Finding] = []
    measured: Dict[str, Dict[str, float]] = {}
    batch_s = NamedSharding(mesh, P("dp", None))
    rng = np.random.default_rng(0)
    atol = 1e-4

    def put(arr, sharding):
        return jax.device_put(arr, sharding)

    rm_config = RM_PRESETS[_DEFAULT_RM_MODEL]
    rm_params = deberta.init_params(jax.random.PRNGKey(1), rm_config)
    rm_params_s = shard_by_rules(
        rm_params, mesh, deberta_partition_rules()
    )
    rm_vocab = rm_config.vocab_size
    for b, l, k in packed_buckets:
        pids, pseg, _ppos, pstarts = _packed_inputs(rng, rm_vocab, b, l, k)

        def reward_fn(p, i, g, st):
            return deberta.reward_packed(p, i, g, st, rm_config)

        label = f"reward_packed(b={b},l={l},k={k})"
        jitted = jax.jit(reward_fn)
        args = [
            put(pids, batch_s), put(pseg, batch_s), put(pstarts, batch_s)
        ]
        compiled = jitted.lower(rm_params_s, *args).compile()
        findings.extend(audit_hlo_collectives(compiled.as_text(), label))
        measured[label] = _exe_figures(compiled)
        # JXA011: only the used slots are defined output (unused slots
        # carry garbage rewards by contract) — compare slots 0..1
        sharded_out = np.asarray(compiled(rm_params_s, *args))
        ref = np.asarray(reward_fn(rm_params, pids, pseg, pstarts))
        if not np.allclose(
            sharded_out[:, :2], ref[:, :2], atol=atol, rtol=1e-4
        ):
            worst = float(
                np.max(np.abs(sharded_out[:, :2] - ref[:, :2]))
            )
            findings.append(
                Finding(
                    rule="JXA011",
                    path=f"mesh:{label}",
                    line=0,
                    message=(
                        "sharded reward output diverges from the single-"
                        f"device reference (max abs diff {worst:.2e} > "
                        f"{atol}): the partition plan changed the math"
                    ),
                )
            )
    return findings, measured


# ---------------------------------------------------------------------------
# Orchestration: in-process when devices suffice, else self-respawn
# ---------------------------------------------------------------------------


def _devices_ok(need: int) -> bool:
    import jax

    return jax.device_count() >= need


def _respawn(
    need: int, write_budgets: bool, write_roofline: bool = False
) -> List[Finding]:
    """Re-run this module in a child with ``need`` virtual CPU devices
    (the parent's jax backend, if initialized, is stuck at its device
    count — XLA_FLAGS are read once at first backend init)."""
    from ..parallel.dist import force_cpu_env

    cmd = [
        sys.executable,
        "-m",
        "llm_weighted_consensus_tpu.analysis.mesh_audit",
        "--json",
    ]
    if write_budgets:
        cmd.append("--write-budgets")
    if write_roofline:
        cmd.append("--write-roofline")
    env = force_cpu_env(dict(os.environ), n_devices=need)
    proc = subprocess.run(
        cmd, capture_output=True, text=True, env=env, timeout=600
    )
    try:
        payload = json.loads(proc.stdout)
        return [Finding(**entry) for entry in payload["findings"]]
    except (json.JSONDecodeError, KeyError, TypeError):
        tail = (proc.stderr or proc.stdout or "")[-800:]
        return [
            Finding(
                rule="JXA008",
                path="mesh:subprocess",
                line=0,
                message=(
                    "mesh audit subprocess failed (exit "
                    f"{proc.returncode}); tail: {tail!r}"
                ),
            )
        ]


def _audit_in_process(
    write_budgets: bool = False,
    write_roofline_file: bool = False,
) -> Tuple[List[Finding], Dict[str, Dict[str, float]]]:
    findings: List[Finding] = []
    budgets_path = _budgets_path()
    budgets = load_budgets(budgets_path)
    allowlist = replicated_allowlist(budgets)
    threshold = replicated_threshold(budgets)
    matched: Set[str] = set()
    for label, rules, tree in _shape_trees():
        findings += audit_rule_coverage(rules, tree, label)
        repl_findings, repl_matched = audit_replication(
            rules, tree, label, threshold, allowlist
        )
        findings += repl_findings
        matched |= repl_matched
    findings += check_allowlist_stale(allowlist, matched)

    dp, tp = _env_mesh()
    bucket_findings, measured = _measure_buckets(
        _env_model(), dp, tp,
        _env_specs(), _env_r_buckets(), _env_packed_buckets(),
    )
    findings += bucket_findings
    # JXA012 rung figures carry no committed budget baseline; the ladder
    # audit contributes findings only, never entries in ``measured``.
    findings += _audit_fault_ladder(
        _env_model(), dp, tp,
        _env_specs(), _env_r_buckets(), _env_packed_buckets(),
    )
    # long-context ring buckets on the sp-bearing mesh: same JXA008–011
    # treatment (figures land in budgets/roofline next to the dense
    # buckets), plus the sp-preserving downsize ladder (JXA012)
    if _ring_enabled():
        sp = _env_sp()
        ring_findings, ring_measured = _measure_ring_buckets(
            _env_model(), dp, tp, sp, _env_ring_buckets()
        )
        findings += ring_findings
        measured.update(ring_measured)
        findings += _audit_ring_fault_ladder(
            _env_model(), dp, tp, sp, _env_ring_buckets()
        )
    if write_budgets:
        _write_budgets_file(budgets_path, measured, budgets)
    else:
        findings += compare_budgets(measured, budgets, scope=_scope())
    # JXA013: the same measured cost figures must back a committed
    # roofline row per bucket, or serving would run without its
    # speed-of-light attainment gauge.
    roofline_path = _roofline_path()
    roofline = load_roofline(roofline_path)
    if write_roofline_file:
        write_roofline(roofline_path, measured, _scope(), roofline)
    else:
        findings += compare_roofline(measured, roofline, scope=_scope())
    return findings, measured


def _write_budgets_file(
    path: Path, measured: Dict[str, Dict[str, float]], previous: dict
) -> None:
    """Fresh measurements under the committed policy knobs (tolerance,
    threshold, allowlist survive a re-baseline; figures do not)."""
    payload = {
        "_doc": (
            "Committed per-bucket resource budgets for the mesh audit "
            "(JXA009/JXA010). Re-baseline: python -m "
            "llm_weighted_consensus_tpu.analysis.mesh_audit "
            "--write-budgets, then review the diff. Policy: DESIGN.md "
            "'Static analysis v2'."
        ),
        "scope": _scope(),
        "tolerance": previous.get(
            "tolerance",
            {"hbm_bytes": 0.25, "flops": 0.25, "bytes_accessed": 0.25},
        ),
        "replicated_threshold_bytes": previous.get(
            "replicated_threshold_bytes", 1 << 20
        ),
        "replicated_allowlist": previous.get("replicated_allowlist", []),
        "buckets": {
            label: {k: round(v, 1) for k, v in figures.items()}
            for label, figures in sorted(measured.items())
        },
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


def run_mesh_audit(
    write_budgets: bool = False, write_roofline: bool = False
) -> List[Finding]:
    """Entry point for the analysis CLI and tier-1: in-process when the
    backend already has dp*tp devices (pytest's virtual-CPU env),
    subprocess respawn otherwise."""
    dp, tp = _env_mesh()
    if not _devices_ok(dp * tp):
        return _respawn(dp * tp, write_budgets, write_roofline)
    findings, _ = _audit_in_process(write_budgets, write_roofline)
    return findings


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m llm_weighted_consensus_tpu.analysis.mesh_audit",
        description="simulated-mesh sharding & resource audit (JXA006-011)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--write-budgets",
        action="store_true",
        help="re-measure and rewrite analysis/budgets.json "
        "(policy knobs preserved); review the diff",
    )
    parser.add_argument(
        "--write-roofline",
        action="store_true",
        help="re-measure and rewrite analysis/roofline.json "
        "(peaks and tolerance preserved); review the diff",
    )
    args = parser.parse_args(argv)

    dp, tp = _env_mesh()
    if not _devices_ok(dp * tp):
        findings = _respawn(dp * tp, args.write_budgets, args.write_roofline)
        measured = {}
    else:
        findings, measured = _audit_in_process(
            args.write_budgets, args.write_roofline
        )
    if args.json:
        print(
            json.dumps(
                {
                    "findings": [vars(f) for f in findings],
                    "measured": measured,
                    "scope": _scope(),
                }
            )
        )
    else:
        for finding in findings:
            print(finding.render())
        print(
            f"mesh audit: {len(findings)} finding(s), "
            f"{len(measured)} bucket(s) measured",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
