"""LWC014 — lock registry consistency + cross-thread guarded fields.

The lock-model registry (``analysis/concurrency_model.py``) declares
every threading primitive in the package and the instance fields each
one guards.  This rule enforces it both ways, LWC010-style, then runs
the RacerX-shaped lockset check over the owning classes:

* an **unregistered lock** — a ``threading.Lock``/``RLock``/
  ``Condition`` creation site with no registry entry — fails: a lock
  nobody declared guards nothing anybody can audit;
* a **stale registry row** — an entry whose creation site is gone —
  fails: the registry only ever shrinks honestly;
* a **guarded-field access outside its lock** fails once the field is
  cross-thread: the union of thread entry points (Thread targets,
  executor submits — each worth 2, every pool has >= 2 workers — and
  the asyncio loop) reaching the class's accessing methods weighs >= 2.
  ``__init__`` is exempt (construction precedes publication);
* the escape hatch is an explicit ``# caller-holds-lock: <Lock.key>
  (reason)`` comment on the method — which itself requires every
  resolved caller to hold that lock at the call site (or be exempted
  for it in turn), and requires the written reason.

Project-scoped; a parsed set that declares no ``CONCURRENCY_MODEL``
checks nothing (single-file lint invocations stay self-contained).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..concurrency import (
    FKey,
    lock_sites,
    method_exemptions,
    project_index,
)
from ..engine import Finding, ParsedModule, enclosing_symbol
from . import Rule


def _registry_findings(idx, modules) -> List[Finding]:
    model = idx.model
    findings: List[Finding] = []
    matched: Set[str] = set()
    for site in lock_sites(modules):
        entry = model.locks.get(site.key)
        if entry is not None and site.module.rel.endswith(
            entry.get("module", "")
        ):
            matched.add(site.key)
            continue
        findings.append(
            Finding(
                rule=RULE.name,
                path=site.module.rel,
                line=site.node.lineno,
                symbol=enclosing_symbol(site.module, site.node),
                message=(
                    f"threading primitive `{site.key}` is not in the "
                    f"lock-model registry ({model.module.rel}): declare "
                    "it with the fields it guards and any acquisition-"
                    "order edges, or nothing audits its discipline"
                ),
            )
        )
    for key in model.locks:
        if key in matched or not model.in_scope(key, modules):
            continue
        findings.append(
            Finding(
                rule=RULE.name,
                path=model.module.rel,
                line=model.line,
                symbol=key,
                message=(
                    f"lock-model registry entry `{key}` has no creation "
                    "site: the lock it described is gone — delete the "
                    "stale row (guards and order edges die with it)"
                ),
            )
        )
    return findings


def _guard_findings(idx, modules) -> List[Finding]:
    model = idx.model
    findings: List[Finding] = []
    # owning class per registered lock (via its declared module)
    for key, entry in model.locks.items():
        guards = tuple(entry.get("guards", ()))
        if not guards or "." not in key:
            continue
        class_name, _ = key.rsplit(".", 1)
        owner = None
        for module in modules:
            if not module.rel.endswith(entry.get("module", "")):
                continue
            for cls in module.classes():
                if cls.name == class_name:
                    owner = (module, cls)
        if owner is None:
            continue  # stale row already reported
        module, cls = owner
        guard_set = set(guards)
        # accesses[field] -> [(method fkey, node, locked, exempted)]
        accesses: Dict[str, List[Tuple[FKey, ast.AST, bool, bool]]] = {}
        exempt_by_method: Dict[FKey, List] = {}
        for method in cls.methods:
            fkey = (module.rel, method.qualname)
            fentry = idx.funcs.get(fkey)
            if fentry is None:
                continue
            exemptions = [
                e
                for e in method_exemptions(module, method.node)
                if e.lock == key
            ]
            if exemptions:
                exempt_by_method[fkey] = exemptions
                for e in exemptions:
                    if not e.reason:
                        findings.append(
                            Finding(
                                rule=RULE.name,
                                path=module.rel,
                                line=e.line,
                                symbol=method.qualname,
                                message=(
                                    "caller-holds-lock exemption for "
                                    f"`{key}` has no written reason: "
                                    "say WHY the caller chain holds it, "
                                    "e.g. `# caller-holds-lock: X._lock "
                                    "(only called from locked Y)`"
                                ),
                            )
                        )
            if method.node.name == "__init__":
                continue  # construction precedes publication
            for node, held in fentry.facts.nodes:
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in guard_set
                ):
                    accesses.setdefault(node.attr, []).append(
                        (fkey, node, key in held, bool(exemptions))
                    )
        for fld, sites in accesses.items():
            entry_ids: Set[str] = set()
            for fkey, _, _, _ in sites:
                entry_ids |= idx.entry_sets.get(fkey, set())
            weight = sum(
                2 if e.startswith("executor:") else 1 for e in entry_ids
            )
            if weight < 2:
                continue  # statically single-threaded state
            for fkey, node, locked, exempted in sites:
                if locked or exempted:
                    continue
                findings.append(
                    Finding(
                        rule=RULE.name,
                        path=fkey[0],
                        line=node.lineno,
                        symbol=fkey[1],
                        message=(
                            f"`self.{fld}` is guarded by `{key}` and "
                            "cross-thread (reached from "
                            f"{sorted(entry_ids)}), but this access "
                            f"holds no `with` on it: wrap it, or exempt "
                            "the method with `# caller-holds-lock: "
                            f"{key} (reason)` if every caller locks"
                        ),
                    )
                )
        # exemption honesty: every resolved caller must hold the lock
        for fkey, exemptions in exempt_by_method.items():
            for caller, call in idx.call_sites.get(fkey, ()):
                centry = idx.funcs[caller]
                held = centry.held_by_node().get(id(call), ())
                if key in held:
                    continue
                if caller in exempt_by_method:
                    continue  # the chain's own exemption covers it
                if centry.qualname.split(".")[-1] == "__init__":
                    continue
                findings.append(
                    Finding(
                        rule=RULE.name,
                        path=caller[0],
                        line=call.lineno,
                        symbol=centry.qualname,
                        message=(
                            f"call into `{fkey[1]}` (exempted via "
                            f"caller-holds-lock: {key}) without holding "
                            f"`{key}`: the exemption's contract is that "
                            "EVERY caller locks — take the lock here or "
                            "extend the exemption up the chain"
                        ),
                    )
                )
    return findings


def project(modules: List[ParsedModule]) -> List[Finding]:
    idx = project_index(modules)
    if idx is None:
        return []
    return _registry_findings(idx, modules) + _guard_findings(
        idx, modules
    )


RULE = Rule(
    name="LWC014",
    summary="lock registry drift / guarded field accessed outside its lock",
    check=None,
    project=project,
)
