"""Rule registry for the AST lint engine.

Each rule module exposes a single ``RULE`` object: a name (``LWCnnn``),
a one-line summary, and a ``check(ParsedModule) -> list[Finding]``
callable.  ``ALL_RULES`` is the ordered registry the engine and CLI
iterate; adding a rule means adding a module here and one line below
(see DESIGN.md "Static analysis" for the checklist).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ..engine import Finding, ParsedModule


@dataclass(frozen=True)
class Rule:
    name: str
    summary: str
    # per-module check; None for purely project-scoped rules
    check: Optional[Callable[[ParsedModule], List[Finding]]]
    # whole-parsed-set check (declared-vs-used registries and other
    # cross-module invariants); runs once after every module is parsed
    project: Optional[
        Callable[[List[ParsedModule]], List[Finding]]
    ] = None


from . import (  # noqa: E402
    lwc001_swallowed_cancellation,
    lwc002_orphaned_task,
    lwc003_release_in_finally,
    lwc004_contextvar_token,
    lwc005_decimal_purity,
    lwc006_blocking_in_async,
    lwc007_envelope_kind,
    lwc008_env_read_outside_config,
    lwc009_jax_in_async,
    lwc010_registry_consistency,
    lwc011_config_readme_drift,
    lwc012_prom_family_registry,
    lwc013_blocking_readiness,
    lwc014_guarded_field,
    lwc015_lock_order,
    lwc016_blocking_under_lock,
    lwc017_frame_rebuild_in_merge_loop,
    lwc018_unbounded_ingest_growth,
)

ALL_RULES: Tuple[Rule, ...] = (
    lwc001_swallowed_cancellation.RULE,
    lwc002_orphaned_task.RULE,
    lwc003_release_in_finally.RULE,
    lwc004_contextvar_token.RULE,
    lwc005_decimal_purity.RULE,
    lwc006_blocking_in_async.RULE,
    lwc007_envelope_kind.RULE,
    lwc008_env_read_outside_config.RULE,
    lwc009_jax_in_async.RULE,
    lwc010_registry_consistency.RULE,
    lwc011_config_readme_drift.RULE,
    lwc012_prom_family_registry.RULE,
    lwc013_blocking_readiness.RULE,
    lwc014_guarded_field.RULE,
    lwc015_lock_order.RULE,
    lwc016_blocking_under_lock.RULE,
    lwc017_frame_rebuild_in_merge_loop.RULE,
    lwc018_unbounded_ingest_growth.RULE,
)

RULES_BY_NAME = {rule.name: rule for rule in ALL_RULES}

__all__ = ["Rule", "ALL_RULES", "RULES_BY_NAME"]
