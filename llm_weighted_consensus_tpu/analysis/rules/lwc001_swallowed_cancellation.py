"""LWC001 — handlers that can swallow ``asyncio.CancelledError``.

Since Python 3.8 ``CancelledError`` derives from ``BaseException``, so
``except Exception`` is safe; what swallows cancellation in an
``async def`` is a bare ``except:``, an ``except BaseException:``, or
an explicit ``except asyncio.CancelledError:`` — unless the handler
re-raises.

One structural exemption: a function that calls ``.cancel()`` on a
task is a *canceller* reaping its own cancellation (the
``_discard_attempts`` / stream-merge-cleanup shape), and absorbing the
resulting ``CancelledError`` there is the whole point.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ParsedModule, body_nodes
from . import Rule


def _names_base_exception(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "BaseException"
    if isinstance(node, ast.Attribute):
        return node.attr == "BaseException"
    return False


def _names_cancelled(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "CancelledError"
    if isinstance(node, ast.Attribute):
        return node.attr == "CancelledError"
    return False


def _handler_reraises(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        for node in ast.walk(stmt):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                break
            if isinstance(node, ast.Raise):
                return True
    return False


def check(module: ParsedModule) -> List[Finding]:
    findings: List[Finding] = []
    for fn in module.functions():
        if not fn.is_async:
            continue
        is_canceller = any(
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "cancel"
            for node in body_nodes(fn.node)
        )
        for node in body_nodes(fn.node):
            if not isinstance(node, ast.ExceptHandler):
                continue
            types = []
            if node.type is None:
                kind = "bare except:"
            elif isinstance(node.type, ast.Tuple):
                types = list(node.type.elts)
                kind = None
            else:
                types = [node.type]
                kind = None
            if kind is None:
                if any(_names_base_exception(t) for t in types):
                    kind = "except BaseException"
                elif any(_names_cancelled(t) for t in types):
                    if is_canceller:
                        continue
                    kind = "except CancelledError"
                else:
                    continue
            if _handler_reraises(node):
                continue
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=module.rel,
                    line=node.lineno,
                    symbol=fn.qualname,
                    message=(
                        f"{kind} in async function without re-raise can "
                        "swallow asyncio.CancelledError; re-raise, narrow "
                        "to Exception, or cancel-and-reap explicitly"
                    ),
                )
            )
    return findings


RULE = Rule(
    name="LWC001",
    summary="async handler can swallow CancelledError",
    check=check,
)
