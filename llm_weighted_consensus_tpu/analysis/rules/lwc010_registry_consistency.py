"""LWC010 — metric-section and span-name registries vs. their uses.

``serve/metrics.py`` declares ``KNOWN_SECTIONS`` (every
``register_provider`` name that may appear in the ``/metrics``
snapshot) and ``obs/span.py`` declares ``KNOWN_SPANS`` (every span name
a trace tree can contain; trailing ``*`` covers a dynamic f-string
suffix).  Dashboards, alert queries, and the explain renderer all match
on these literal keys, so an undeclared name is telemetry that silently
falls off every consumer — and a declared-but-unused name is a dead
registry row that keeps a stale dashboard panel looking healthy.

Project-scoped (the invariant spans modules): collects every
``register_provider("name", ...)`` call and every span-creating call
(``child_span`` / ``start_trace`` / ``span`` / ``.child``) with a
literal or f-string name across the parsed set, then checks both
directions against whichever registries the set declares.  A run whose
module set declares neither registry checks nothing — single-file lint
invocations stay self-contained.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ..engine import Finding, ParsedModule, enclosing_symbol
from . import Rule

_SPAN_CALLS = {"child_span", "start_trace", "span", "child"}


def _literal_or_prefix(node: ast.AST) -> Optional[str]:
    """String constant -> itself; f-string -> its literal prefix + "*";
    anything else -> None (not statically checkable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        prefix = ""
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(
                part.value, str
            ):
                prefix += part.value
            else:
                break
        return prefix + "*"
    return None


def _declared(module: ParsedModule, name: str):
    """(line, tuple-of-names) for a module-level ``name = (...)``."""
    for node in module.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List, ast.Set)):
            names = tuple(
                el.value
                for el in node.value.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, str)
            )
            return node.lineno, names
    return None


def _matches(declared: str, use: str) -> bool:
    """``use`` may itself be a prefix pattern (f-string call site)."""
    if declared.endswith("*"):
        d = declared[:-1]
        u = use[:-1] if use.endswith("*") else use
        return u.startswith(d) or d.startswith(u)
    if use.endswith("*"):
        return declared.startswith(use[:-1])
    return declared == use


def _check_registry(
    registry: str,
    declared_at: Tuple[ParsedModule, int, Tuple[str, ...]],
    uses: List[Tuple[ParsedModule, ast.AST, str]],
    what: str,
) -> List[Finding]:
    module, line, names = declared_at
    findings: List[Finding] = []
    used = {name: False for name in names}
    for use_mod, node, use_name in uses:
        hits = [d for d in names if _matches(d, use_name)]
        for d in hits:
            used[d] = True
        if not hits:
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=use_mod.rel,
                    line=node.lineno,
                    symbol=enclosing_symbol(use_mod, node),
                    message=(
                        f"{what} `{use_name}` is not declared in "
                        f"{registry} ({module.rel}): undeclared names "
                        "fall off every dashboard/query that matches on "
                        "the registry"
                    ),
                )
            )
    for name, was_used in used.items():
        if not was_used:
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=module.rel,
                    line=line,
                    # the entry name, so (rule, path, symbol) baselining
                    # can target one stale row
                    symbol=name,
                    message=(
                        f"{registry} entry `{name}` has no call site: "
                        "delete the stale registry row (or the dashboard "
                        "panel it backs is already dark)"
                    ),
                )
            )
    return findings


def project(modules: List[ParsedModule]) -> List[Finding]:
    sections_decl = spans_decl = None
    section_uses: List[Tuple[ParsedModule, ast.AST, str]] = []
    span_uses: List[Tuple[ParsedModule, ast.AST, str]] = []
    for module in modules:
        decl = _declared(module, "KNOWN_SECTIONS")
        if decl is not None:
            sections_decl = (module, decl[0], decl[1])
        decl = _declared(module, "KNOWN_SPANS")
        if decl is not None:
            spans_decl = (module, decl[0], decl[1])
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            attr = (
                func.attr
                if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None
            )
            if attr is None:
                continue
            name = _literal_or_prefix(node.args[0])
            if name is None:
                continue
            if attr == "register_provider":
                section_uses.append((module, node, name))
            elif attr in _SPAN_CALLS:
                span_uses.append((module, node, name))
    findings: List[Finding] = []
    if sections_decl is not None:
        findings += _check_registry(
            "KNOWN_SECTIONS", sections_decl, section_uses, "metric section"
        )
    if spans_decl is not None:
        findings += _check_registry(
            "KNOWN_SPANS", spans_decl, span_uses, "span name"
        )
    return findings


RULE = Rule(
    name="LWC010",
    summary="metric-section/span-name registry out of sync with uses",
    check=None,
    project=project,
)
