"""LWC007 — structured error envelopes must carry a ``kind``.

The HTTP error contract (``errors.py``): a dict-shaped ``message``
always carries a ``kind`` discriminator so clients and the resilience
layer can branch without string-matching prose.  Two shapes are
checked:

* any ``message()`` method returning a dict literal must include a
  ``"kind"`` key;
* any dict literal with both ``"code"`` and ``"message"`` keys (the
  wire envelope shape) whose ``"message"`` value is itself a dict
  literal must include ``"kind"`` in that inner dict.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..engine import Finding, ParsedModule, body_nodes
from . import Rule


def _dict_keys(node: ast.Dict) -> List[Optional[str]]:
    keys = []
    for key in node.keys:
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            keys.append(key.value)
        else:
            keys.append(None)  # **spread or computed key: unknowable
    return keys


def _has_unknowable(node: ast.Dict) -> bool:
    return any(k is None for k in _dict_keys(node))


def check(module: ParsedModule) -> List[Finding]:
    findings: List[Finding] = []
    for fn in module.functions():
        is_message_method = fn.qualname.rsplit(".", 1)[-1] == "message"
        for node in body_nodes(fn.node):
            if is_message_method and isinstance(node, ast.Return):
                value = node.value
                if isinstance(value, ast.Dict):
                    keys = _dict_keys(value)
                    if "kind" not in keys and not _has_unknowable(value):
                        findings.append(
                            Finding(
                                rule=RULE.name,
                                path=module.rel,
                                line=value.lineno,
                                symbol=fn.qualname,
                                message=(
                                    "message() returns a dict without a "
                                    '"kind" discriminator; clients branch '
                                    "on kind, not on prose"
                                ),
                            )
                        )
            if isinstance(node, ast.Dict):
                keys = _dict_keys(node)
                if "code" in keys and "message" in keys:
                    inner = node.values[keys.index("message")]
                    if (
                        isinstance(inner, ast.Dict)
                        and "kind" not in _dict_keys(inner)
                        and not _has_unknowable(inner)
                    ):
                        findings.append(
                            Finding(
                                rule=RULE.name,
                                path=module.rel,
                                line=inner.lineno,
                                symbol=fn.qualname,
                                message=(
                                    "error envelope carries a dict message "
                                    'without a "kind" discriminator'
                                ),
                            )
                        )
    return findings


RULE = Rule(
    name="LWC007",
    summary='error envelope missing "kind"',
    check=check,
)
