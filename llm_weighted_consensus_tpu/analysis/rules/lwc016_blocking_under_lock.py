"""LWC016 — blocking while holding a threading lock.

A threading lock held across an ``await``, a ``wait_device_ready``/
``block_until_ready`` device wait, or an upstream HTTP call turns one
slow device/peer into a package-wide stall: every thread that touches
the same critical section parks behind a sleeper that is not even
running.  Flagged, for any held registered lock:

* lexically blocking operations inside the ``with`` body — including
  ``await`` (an async def that takes a *threading* lock parks the whole
  event loop behind it);
* ``Condition.wait`` / ``wait_for`` on a condition OTHER than one
  currently held — waiting on B while holding A blocks A for the full
  sleep.  Waiting on the held condition itself is the designed idiom
  (``wait`` atomically releases it) and is never flagged;
* calls that resolve to a method whose own body directly blocks — the
  one-hop call-mediated case (``self._probe()`` under the manager lock
  where ``_probe`` waits on the device).

Locks registered ``long_held: True`` (the reader/writer shape gate —
designed to be held across an entire device staging) are exempt.

Project-scoped; no declared ``CONCURRENCY_MODEL`` means no checks.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from ..concurrency import (
    _resolve_lock_expr,
    blocking_call,
    project_index,
)
from ..engine import Finding, ParsedModule
from . import Rule


def _fmt(keys: Tuple[str, ...]) -> str:
    return ", ".join(f"`{k}`" for k in keys)


def project(modules: List[ParsedModule]) -> List[Finding]:
    idx = project_index(modules)
    if idx is None:
        return []
    model = idx.model
    long_held = {
        key
        for key, entry in model.locks.items()
        if entry.get("long_held")
    }
    findings: List[Finding] = []
    for fkey, entry in idx.funcs.items():
        for node, held in entry.facts.nodes:
            eff = tuple(h for h in held if h not in long_held)
            if not eff:
                continue
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("wait", "wait_for")
            ):
                key = _resolve_lock_expr(
                    node.func.value, entry.class_name, model, idx.via
                )
                if (
                    key is not None
                    and model.locks.get(key, {}).get("kind")
                    == "condition"
                ):
                    if key in held:
                        continue  # wait() releases the held condition
                    findings.append(
                        Finding(
                            rule=RULE.name,
                            path=fkey[0],
                            line=node.lineno,
                            symbol=entry.qualname,
                            message=(
                                f"waiting on `{key}` while holding "
                                f"{_fmt(eff)}: `wait` only releases its "
                                "OWN condition — the held lock stays "
                                "taken for the whole sleep; restructure "
                                "so the wait happens outside it"
                            ),
                        )
                    )
                    continue
            desc = blocking_call(node)
            if desc is None:
                continue
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=fkey[0],
                    line=node.lineno,
                    symbol=entry.qualname,
                    message=(
                        f"{desc} while holding {_fmt(eff)}: every "
                        "thread touching that critical section parks "
                        "behind this sleep — move the blocking step "
                        "outside the `with`, or snapshot state and "
                        "release first"
                    ),
                )
            )
    # one hop of call-mediation: holding a lock, calling a method whose
    # own body directly blocks
    for callee, sites in idx.call_sites.items():
        desc = idx.direct_blocking.get(callee)
        if desc is None:
            continue
        for caller, call in sites:
            held = idx.funcs[caller].held_by_node().get(id(call), ())
            eff = tuple(h for h in held if h not in long_held)
            if not eff:
                continue
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=caller[0],
                    line=call.lineno,
                    symbol=idx.funcs[caller].qualname,
                    message=(
                        f"call into `{callee[1]}` — which performs "
                        f"{desc} — while holding {_fmt(eff)}: the lock "
                        "is held across the callee's blocking wait; "
                        "hoist the call out of the `with`"
                    ),
                )
            )
    return findings


RULE = Rule(
    name="LWC016",
    summary="blocking operation performed while holding a threading lock",
    check=None,
    project=project,
)
