"""LWC017 — full-frame serialization inside a per-chunk merge loop.

The streaming serve path used to rebuild every SSE frame from scratch —
``chunk.to_json_obj()`` + ``jsonutil.dumps`` per merged chunk — which is
exactly the O(frame) work the HOST_FASTPATH splice lane
(serve/frames.py, types/base.py SpliceEncoder) exists to avoid: the
splice encoder re-renders only the bytes a chunk changed.  This rule
keeps the slow pattern from creeping back: any ``to_json_obj(...)`` or
``jsonutil.dumps(...)`` call lexically inside an ``async for`` body is
a finding.

Exempt modules (full-frame serialization IS their contract):

* ``serve/frames.py`` — the fast-lane module itself; its slow-lane
  fallback and the splice encoder's dynamic subtrees both legitimately
  call the full writer per frame;
* ``cache/replay.py`` — the response-cache recorder stores complete
  canonical frames; serializing every chunk of a cacheable stream is
  the feature, not the bug.

Per the engine contract, nested ``def``/``lambda`` bodies inside the
loop are not flagged (they run in another dynamic context and are
linted as their own functions).
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ParsedModule, body_nodes, dotted_name
from . import Rule

_EXEMPT_SUFFIXES = (
    "serve/frames.py",
    "cache/replay.py",
)

_FULL_FRAME_CALLS = ("to_json_obj", "dumps")


def _loop_calls(loop: ast.AsyncFor):
    """Calls lexically inside the loop body (nested defs excluded)."""
    stack: List[ast.AST] = list(loop.body)
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def check(module: ParsedModule) -> List[Finding]:
    if module.rel.endswith(_EXEMPT_SUFFIXES):
        return []
    findings: List[Finding] = []
    for fn in module.functions():
        for node in body_nodes(fn.node):
            if not isinstance(node, ast.AsyncFor):
                continue
            for call in _loop_calls(node):
                func = call.func
                if isinstance(func, ast.Attribute):
                    name = func.attr
                elif isinstance(func, ast.Name):
                    name = func.id
                else:
                    continue
                if name not in _FULL_FRAME_CALLS:
                    continue
                dotted = dotted_name(func) or name
                findings.append(
                    Finding(
                        rule=RULE.name,
                        path=module.rel,
                        line=call.lineno,
                        symbol=fn.qualname,
                        message=(
                            f"`{dotted}(...)` inside an `async for` "
                            "body rebuilds the full frame per chunk — "
                            "splice-encode through serve/frames.py "
                            "(FrameEncoder) instead, or serialize "
                            "outside the merge loop"
                        ),
                    )
                )
    return findings


RULE = Rule(
    name="LWC017",
    summary="full-frame to_json_obj/dumps inside a per-chunk merge loop",
    check=check,
)
