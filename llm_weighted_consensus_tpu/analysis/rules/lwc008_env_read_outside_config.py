"""LWC008 — ``os.environ`` / ``os.getenv`` reads outside the config door.

Every serving knob flows through ``serve/config.py``'s ``Config.from_env``
(one documented, testable surface: pass an ``env`` dict, get a frozen
``Config``).  A direct ``os.environ`` read anywhere else is a knob the
README never lists, tests can't inject, and ``/healthz`` can't report —
exactly the drift LWC011 then fails to see.

Exempt by construction (they ARE the env boundary, not consumers of it):
``serve/config.py`` itself, the ``analysis/`` package (the checker's own
``ANALYSIS_*`` knobs run before any Config exists), and
``parallel/dist.py`` / ``parallel/multihost_smoke.py`` (pre-``Config``
process bootstrap: they *write* child-process environments).

Two env-var NAMESPACES are also exempt, by the same logic: ``LWC_*``
(process-environment interlocks — the random-params safety gate and the
native-library gates — deliberately NOT Config fields so a config file
or ``.env`` can never flip them, and readable at module-load time before
any Config exists) and ``FAKE_UPSTREAM_*`` (knobs of the built-in fake
provider, read per request on purpose so chaos drills can change
injected judge latency without restarting the process).  The exemption
only applies when the name is a string literal with one of those
prefixes — a computed name is still flagged.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ParsedModule, dotted_name, enclosing_symbol
from . import Rule

_EXEMPT_SUFFIXES = (
    "serve/config.py",
    "parallel/dist.py",
    "parallel/multihost_smoke.py",
)
_EXEMPT_SUBSTR = "llm_weighted_consensus_tpu/analysis/"
_EXEMPT_ENV_PREFIXES = ("LWC_", "FAKE_UPSTREAM_")


def _exempt(rel: str) -> bool:
    return rel.endswith(_EXEMPT_SUFFIXES) or _EXEMPT_SUBSTR in rel


def _exempt_name(node: ast.expr) -> bool:
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and node.value.startswith(_EXEMPT_ENV_PREFIXES)
    )


def _namespace_exempt_nodes(tree: ast.AST) -> set:
    """ids of nodes whose read targets an exempt-namespace literal.

    ``ast.walk`` is breadth-first (parents before children), so marking
    the inner ``os.environ`` attribute of an exempt ``os.environ.get``
    call here happens before the flagging pass visits it."""
    skip: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = dotted_name(node.func)
            if fn == "os.getenv" and node.args and _exempt_name(node.args[0]):
                skip.add(id(node))
            elif (
                fn == "os.environ.get"
                and node.args
                and _exempt_name(node.args[0])
            ):
                skip.add(id(node.func.value))
        elif isinstance(node, ast.Subscript):
            if dotted_name(node.value) == "os.environ" and _exempt_name(
                node.slice
            ):
                skip.add(id(node.value))
    return skip


def check(module: ParsedModule) -> List[Finding]:
    if _exempt(module.rel):
        return []
    skip = _namespace_exempt_nodes(module.tree)
    findings: List[Finding] = []
    for node in ast.walk(module.tree):
        if id(node) in skip:
            continue
        what = None
        if isinstance(node, ast.Attribute):
            if dotted_name(node) == "os.environ":
                what = "os.environ"
        elif isinstance(node, ast.Call):
            if dotted_name(node.func) == "os.getenv":
                what = "os.getenv"
        if what is None:
            continue
        findings.append(
            Finding(
                rule=RULE.name,
                path=module.rel,
                line=node.lineno,
                symbol=enclosing_symbol(module, node),
                message=(
                    f"`{what}` read outside serve/config.py: knobs enter "
                    "through Config.from_env(env) so they stay documented, "
                    "injectable in tests, and visible to the LWC011 "
                    "README-drift check"
                ),
            )
        )
    # one finding per (symbol, line): `os.environ` inside an
    # `os.environ.get(...)` call is a single read, not two
    seen = set()
    unique = []
    for f in findings:
        key = (f.line, f.symbol)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


RULE = Rule(
    name="LWC008",
    summary="os.environ read outside the serve/config.py boundary",
    check=check,
)
