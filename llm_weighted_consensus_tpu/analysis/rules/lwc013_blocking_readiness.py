"""LWC013 — blocking readiness call outside the sanctioned waiter.

The host<->device overlap contract (models/dispatch_seam.py) is that
the dispatch hot path returns at PJRT ENQUEUE: readiness — the blocking
``block_until_ready`` / ``device_get`` — belongs to the batcher's
waiter thread, reached only through ``wait_device_ready``.  One stray
bracket on the dispatch path silently re-serializes the pipeline (the
exact regression ISSUE 13 removed) without failing any functional
test, so the gate is static.

Allowed:

* ``wait_device_ready`` itself (models/dispatch_seam.py) — the ONE
  sanctioned blocking readiness call, run by waiter threads;
* ``parallel/multihost_smoke.py`` — an offline probe/benchmark, not a
  serving path; it blocks on purpose to measure.

Bench scripts live outside the package and are not linted.  Note that
``np.asarray`` on a device array also blocks, but flagging every
asarray would drown the signal — the finalize-closure convention
(serve/batcher.py) covers those by construction.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ParsedModule, body_nodes, dotted_name
from . import Rule

_BLOCKING = ("block_until_ready", "device_get")

_EXEMPT_SUFFIXES = ("parallel/multihost_smoke.py",)

# function qualnames allowed to block (the waiter seam itself)
_ALLOWED_SYMBOLS = {"wait_device_ready"}


def check(module: ParsedModule) -> List[Finding]:
    if module.rel.endswith(_EXEMPT_SUFFIXES):
        return []
    findings: List[Finding] = []
    for fn in module.functions():
        if fn.qualname in _ALLOWED_SYMBOLS:
            continue
        for node in body_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted is None:
                continue
            if dotted.rsplit(".", 1)[-1] not in _BLOCKING:
                continue
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=module.rel,
                    line=node.lineno,
                    symbol=fn.qualname,
                    message=(
                        f"`{dotted}(...)` blocks on device readiness "
                        "outside the waiter seam: the dispatch path "
                        "must return at enqueue — defer through "
                        "dispatch_seam (wait_device_ready runs on the "
                        "waiter thread)"
                    ),
                )
            )
    return findings


RULE = Rule(
    name="LWC013",
    summary="blocking device-readiness call outside the waiter seam",
    check=check,
)
