"""LWC012 — Prometheus family names vs. the declared registry.

``serve/metrics.py`` declares ``KNOWN_PROM_FAMILIES`` (every family
name the ``GET /metrics?format=prometheus`` exposition may emit) and
``prom_family(name, typ, help)`` is the single choke point that renders
a family header.  Grafana dashboards and recording rules match on these
literal family names, so an emitted-but-undeclared family is a series
no dashboard knows to scrape — and a declared-but-unemitted family is a
panel that flatlines while looking configured.  Same shape as LWC010's
section/span registries, specialized to the text exposition: collect
every ``prom_family(...)`` call with a literal first argument across
the parsed set, then check both directions.

Project-scoped; a run whose module set does not declare
``KNOWN_PROM_FAMILIES`` checks nothing (single-file lint invocations
stay self-contained).
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from ..engine import Finding, ParsedModule, enclosing_symbol
from . import Rule


def _declared(module: ParsedModule):
    """(line, tuple-of-names) for module-level KNOWN_PROM_FAMILIES."""
    for node in module.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "KNOWN_PROM_FAMILIES"
            for t in node.targets
        ):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List, ast.Set)):
            names = tuple(
                el.value
                for el in node.value.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, str)
            )
            return node.lineno, names
    return None


def project(modules: List[ParsedModule]) -> List[Finding]:
    decl = None
    uses: List[Tuple[ParsedModule, ast.Call, str]] = []
    for module in modules:
        found = _declared(module)
        if found is not None:
            decl = (module, found[0], found[1])
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            attr = (
                func.attr
                if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None
            )
            if attr != "prom_family":
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                uses.append((module, node, first.value))
            else:
                # the contract is literal-only: a computed family name
                # is invisible to this check AND to every dashboard
                # that greps the registry, so it fails outright
                uses.append((module, node, "<non-literal>"))
    if decl is None:
        return []
    decl_mod, decl_line, names = decl
    findings: List[Finding] = []
    used = {name: False for name in names}
    for use_mod, node, use_name in uses:
        if use_name in used:
            used[use_name] = True
            continue
        findings.append(
            Finding(
                rule=RULE.name,
                path=use_mod.rel,
                line=node.lineno,
                symbol=enclosing_symbol(use_mod, node),
                message=(
                    f"prometheus family `{use_name}` is not declared in "
                    f"KNOWN_PROM_FAMILIES ({decl_mod.rel}): undeclared "
                    "families are series no dashboard knows to scrape "
                    "(family names must be string literals)"
                ),
            )
        )
    for name, was_used in used.items():
        if not was_used:
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=decl_mod.rel,
                    line=decl_line,
                    symbol=name,
                    message=(
                        f"KNOWN_PROM_FAMILIES entry `{name}` has no "
                        "prom_family call site: delete the stale row (the "
                        "dashboard panel it backs is already flatlined)"
                    ),
                )
            )
    return findings


RULE = Rule(
    name="LWC012",
    summary="prometheus family registry out of sync with exposition",
    check=None,
    project=project,
)
