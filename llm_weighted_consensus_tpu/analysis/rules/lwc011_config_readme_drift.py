"""LWC011 — config-knob ↔ README documentation drift.

The README's env-var table is the operator interface; ``Config.from_env``
is the implementation.  They drift in both directions: a knob added to
``from_env`` but never documented is invisible to operators, and a
README entry whose knob no code reads anymore teaches operators a
no-op.  Both directions are mechanical, so both are lint:

* **undocumented** — an ALL_CAPS env-name literal read inside a
  ``from_env`` function that the nearest README never mentions;
* **stale** — a backticked ALL_CAPS token in that README whose family
  prefix (text up to the first ``_``: ``TRACE_``, ``PACKING_``,
  ``ANALYSIS_``, ...) matches some knob the parsed set *does* read, but
  which itself appears in no parsed module — families the repo has
  never owned (``JAX_*``, ``XLA_*`` platform vars) are out of scope.

The README is found by walking up from the ``from_env`` module's
directory (fixture configs ship their own sibling README; the real
``serve/config.py`` resolves to the repo root's).  Project-scoped: the
stale check needs every module's literals, since ``ANALYSIS_*`` knobs
are read far from ``serve/config.py``.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Optional, Set

from ..engine import Finding, ParsedModule, repo_root
from . import Rule

# an env-knob name: ALL_CAPS with at least one underscore segment
_KNOB_RE = re.compile(r"[A-Z][A-Z0-9]*(?:_[A-Z0-9]+)+")
_README_TOKEN_RE = re.compile(r"`([A-Z][A-Z0-9]*(?:_[A-Z0-9]+)+)`")


def _find_readme(start: Path) -> Optional[Path]:
    root = repo_root().resolve()
    node = start.resolve()
    while True:
        candidate = node / "README.md"
        if candidate.exists():
            return candidate
        if node == root or node.parent == node:
            return None
        node = node.parent


def _from_env_knobs(module: ParsedModule):
    """[(name, line)] for every knob literal inside a from_env body."""
    out = []
    for fn in module.functions():
        if fn.qualname.split(".")[-1] != "from_env":
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                if _KNOB_RE.fullmatch(node.value):
                    out.append((node.value, node.lineno))
    return out


def _all_knob_literals(modules: List[ParsedModule]) -> Set[str]:
    """Every knob-shaped string literal anywhere in the parsed set —
    the "somebody reads this" evidence for the stale check."""
    out: Set[str] = set()
    for module in modules:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                for match in _KNOB_RE.findall(node.value):
                    out.add(match)
    return out


def project(modules: List[ParsedModule]) -> List[Finding]:
    config_modules = [
        (m, _from_env_knobs(m)) for m in modules
    ]
    config_modules = [(m, k) for m, k in config_modules if k]
    if not config_modules:
        return []
    findings: List[Finding] = []
    all_literals = _all_knob_literals(modules)
    root = repo_root().resolve()
    stale_checked = set()
    for module, knobs in config_modules:
        readme = _find_readme(module.path.parent)
        if readme is None:
            continue
        readme_text = readme.read_text(encoding="utf-8")
        try:
            readme_rel = readme.resolve().relative_to(root).as_posix()
        except ValueError:
            readme_rel = readme.name
        seen = set()
        for name, line in knobs:
            if name in seen:
                continue
            seen.add(name)
            if name not in readme_text:
                findings.append(
                    Finding(
                        rule=RULE.name,
                        path=module.rel,
                        line=line,
                        symbol=name,
                        message=(
                            f"env knob `{name}` is read by from_env but "
                            f"{readme_rel} never documents it — "
                            "operators can't discover it"
                        ),
                    )
                )
        if readme_rel in stale_checked:
            continue
        stale_checked.add(readme_rel)
        families = {n.split("_", 1)[0] + "_" for n in all_literals}
        for i, text in enumerate(readme_text.splitlines(), start=1):
            for token in _README_TOKEN_RE.findall(text):
                family = token.split("_", 1)[0] + "_"
                if family not in families:
                    continue  # a family the code never owned (JAX_, …)
                if token not in all_literals:
                    findings.append(
                        Finding(
                            rule=RULE.name,
                            path=readme_rel,
                            line=i,
                            symbol=token,
                            message=(
                                f"README documents `{token}` but no "
                                "module reads it — stale knob docs "
                                "teach operators a no-op"
                            ),
                        )
                    )
    return findings


RULE = Rule(
    name="LWC011",
    summary="config knob vs README documentation drift",
    check=None,
    project=project,
)
