"""LWC003 — slot releases must live in ``finally``.

The codebase's resource brackets: asyncio ``acquire``/``release``
(semaphores, locks), admission ``try_acquire``/``release``, breaker
``allow``/``release_probe``-or-settle, watchdog ``begin``/``end``.
If a function both claims and releases the same receiver, the release
must be reachable on every exit — i.e. inside a ``finally`` block —
or an exception (most often a cancellation) between the two leaks the
slot.

Deliberately NOT flagged: functions that claim without any matching
release call (ownership handed to another scope — e.g.
``RetryBudget.try_acquire`` is a token *spend* with no release at
all), and ``with``/``async with`` blocks (the context manager is the
finally).
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ParsedModule, body_nodes, call_base, finally_nodes
from . import Rule

# claim attr -> the attrs that settle it
_PAIRS = {
    "acquire": {"release"},
    "try_acquire": {"release"},
    "allow": {"release_probe", "record_success", "record_failure"},
    "begin": {"end"},
}


def check(module: ParsedModule) -> List[Finding]:
    findings: List[Finding] = []
    for fn in module.functions():
        in_finally = finally_nodes(fn.node)
        calls = [
            node
            for node in body_nodes(fn.node)
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
        ]
        for claim in calls:
            settles = _PAIRS.get(claim.func.attr)
            if settles is None:
                continue
            base = call_base(claim)
            releases = [
                c
                for c in calls
                if c.func.attr in settles and call_base(c) == base
            ]
            if not releases:
                continue  # ownership escapes this function: not ours to judge
            if any(id(c) in in_finally for c in releases):
                continue
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=module.rel,
                    line=claim.lineno,
                    symbol=fn.qualname,
                    message=(
                        f"`{base}.{claim.func.attr}()` is settled by "
                        f"`{'`/`'.join(sorted(settles))}` in this function "
                        "but never inside a finally: block — a cancellation "
                        "between claim and release leaks the slot"
                    ),
                )
            )
    return findings


RULE = Rule(
    name="LWC003",
    summary="resource release not in finally",
    check=check,
)
