"""LWC009 — ``jax.*`` / ``jnp.*`` calls inside ``async def``.

Device work belongs behind the batcher/embedder boundary: the batcher
coroutine hands numpy batches to the embedder, whose jitted calls run
device dispatch (and, off the AOT table, whole XLA compilations —
seconds of blocking) on an executor thread.  A ``jax.*`` call directly
inside any other coroutine stalls the event loop for every in-flight
request AND dodges the jit-specialization accounting the JXA005 guard
audits.

Exempt modules (they ARE the boundary): ``serve/batcher.py`` and
``models/embedder.py``.  Nested ``def``s/lambdas inside coroutines are
not flagged (function-scoped contract — they usually run on the
executor), but are linted as their own functions if async.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ParsedModule, body_nodes, dotted_name
from . import Rule

_EXEMPT_SUFFIXES = (
    "serve/batcher.py",
    "models/embedder.py",
)


def check(module: ParsedModule) -> List[Finding]:
    if module.rel.endswith(_EXEMPT_SUFFIXES):
        return []
    findings: List[Finding] = []
    for fn in module.functions():
        if not fn.is_async:
            continue
        for node in body_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted is None:
                continue
            root = dotted.split(".", 1)[0]
            if root not in ("jax", "jnp"):
                continue
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=module.rel,
                    line=node.lineno,
                    symbol=fn.qualname,
                    message=(
                        f"`{dotted}(...)` inside async def: device "
                        "dispatch (or a surprise compile) blocks the "
                        "event loop — route it through the batcher/"
                        "embedder executor boundary"
                    ),
                )
            )
    return findings


RULE = Rule(
    name="LWC009",
    summary="jax call inside async def outside the batcher/embedder",
    check=check,
)
