"""LWC006 — blocking calls inside ``async def``.

One synchronous sleep / file read / HTTP round-trip inside a coroutine
stalls the whole event loop — every in-flight request on the gateway
pays it.  Flagged inside async function bodies (nested ``def``s and
lambdas are exempt — they run wherever they're shipped, usually an
executor): ``time.sleep``, plain ``open``, ``subprocess.*``,
``os.system``, ``requests.*``, ``urllib.request.urlopen``,
``socket.create_connection``.

The fix is the repo's existing idiom: ``await asyncio.sleep``,
``run_in_executor`` (see the gateway profile handlers), or aiohttp.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ParsedModule, body_nodes, dotted_name
from . import Rule

_BLOCKING_DOTTED = {
    "time.sleep",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "os.system",
    "os.popen",
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.delete",
    "requests.request",
    "requests.Session",
    "urllib.request.urlopen",
    "socket.create_connection",
}

_BLOCKING_PLAIN = {"open"}


def check(module: ParsedModule) -> List[Finding]:
    findings: List[Finding] = []
    for fn in module.functions():
        if not fn.is_async:
            continue
        for node in body_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            if dotted is None:
                continue
            hit = (
                dotted in _BLOCKING_DOTTED
                or dotted in _BLOCKING_PLAIN
            )
            if not hit:
                continue
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=module.rel,
                    line=node.lineno,
                    symbol=fn.qualname,
                    message=(
                        f"blocking call `{dotted}(...)` inside async def "
                        "stalls the event loop for every in-flight request; "
                        "use asyncio.sleep / run_in_executor / aiohttp"
                    ),
                )
            )
    return findings


RULE = Rule(
    name="LWC006",
    summary="blocking call inside async def",
    check=check,
)
