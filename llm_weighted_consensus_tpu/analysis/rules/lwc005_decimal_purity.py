"""LWC005 — no float contamination in the Decimal tally math.

The paper's consensus semantics depend on *exact* weighted tallies
(``Decimal`` end to end — weights, quorum thresholds, per-choice
sums).  Two contamination shapes are flagged:

* ``Decimal(0.1)`` — constructing a Decimal from a float literal bakes
  the binary-float error into the "exact" value (``Decimal("0.1")`` is
  the correct spelling);
* arithmetic mixing a Decimal-bound name with a float literal
  (``weight * 0.5`` where ``weight = Decimal(...)``) — in Python this
  raises TypeError at runtime on the serving path, or silently
  degrades if somebody "fixes" it with a float() cast upstream.

Explicit, labelled exports like ``float(w)`` for the explain/metrics
surface are fine and not flagged — the rule looks at construction and
binary ops only.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..engine import Finding, ParsedModule, body_nodes
from . import Rule


def _is_float_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.UnaryOp):
        node = node.operand
    return isinstance(node, ast.Constant) and isinstance(node.value, float)


def _is_decimal_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    name = (
        func.id
        if isinstance(func, ast.Name)
        else func.attr
        if isinstance(func, ast.Attribute)
        else None
    )
    return name == "Decimal"


def check(module: ParsedModule) -> List[Finding]:
    findings: List[Finding] = []
    for fn in module.functions():
        decimal_names: Set[str] = set()
        for node in body_nodes(fn.node):
            if isinstance(node, ast.Assign) and _is_decimal_ctor(node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        decimal_names.add(target.id)
        for node in body_nodes(fn.node):
            if _is_decimal_ctor(node) and node.args and _is_float_literal(
                node.args[0]
            ):
                findings.append(
                    Finding(
                        rule=RULE.name,
                        path=module.rel,
                        line=node.lineno,
                        symbol=fn.qualname,
                        message=(
                            "Decimal(<float literal>) bakes binary-float "
                            'error into the exact tally; use Decimal("...") '
                            "with a string literal"
                        ),
                    )
                )
            elif isinstance(node, ast.BinOp):
                sides = (node.left, node.right)
                if any(_is_float_literal(s) for s in sides) and any(
                    isinstance(s, ast.Name) and s.id in decimal_names
                    for s in sides
                ):
                    findings.append(
                        Finding(
                            rule=RULE.name,
                            path=module.rel,
                            line=node.lineno,
                            symbol=fn.qualname,
                            message=(
                                "float literal mixed into Decimal "
                                "arithmetic; keep tally math Decimal-pure "
                                "(float() only at the explain/metrics edge)"
                            ),
                        )
                    )
            elif isinstance(node, ast.AugAssign):
                if (
                    isinstance(node.target, ast.Name)
                    and node.target.id in decimal_names
                    and _is_float_literal(node.value)
                ):
                    findings.append(
                        Finding(
                            rule=RULE.name,
                            path=module.rel,
                            line=node.lineno,
                            symbol=fn.qualname,
                            message=(
                                "float literal folded into a Decimal "
                                "accumulator; keep tally math Decimal-pure"
                            ),
                        )
                    )
    return findings


RULE = Rule(
    name="LWC005",
    summary="float literal contaminating Decimal math",
    check=check,
)
