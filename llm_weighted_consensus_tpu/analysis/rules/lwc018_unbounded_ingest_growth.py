"""LWC018 — unbounded growable containers on ingest/serve paths.

The hostile-upstream hardening (ISSUE 19) bounds every byte an upstream
can make us hold: SSE parser caps, per-judge stream budgets, the unary
body cap, ``client_max_size`` at the gateway door, and the archive's
capped orphan queue.  This rule keeps the *shape* of those bugs from
creeping back.  Three patterns are findings:

* ``deque()`` constructed without a ``maxlen`` keyword — an unbounded
  FIFO is exactly how the archive orphan queue leaked before it was
  capped; every deque in this package must state its bound (or
  explicitly pass ``maxlen=None`` into a baseline entry that says why);
* a bytes accumulator (a name assigned ``bytearray()`` or a bytes
  literal in the same function) grown inside a loop — ``buf += chunk``
  or ``buf.extend(chunk)`` — with no ``len(buf)`` check anywhere in that
  loop body: the newline-less-flood bug (clients/sse.py checks
  ``len(self._buffer)`` against ``max_buffer_bytes`` for this reason);
* the raw network iterators (``byte_stream``/``iter_chunked``/
  ``iter_any``) drained into a container — appending or ``+=``-ing the
  loop target — with no ``len(...)`` check on the container in the loop
  body: "read the whole stream into memory" with no cap.

Heuristic limits (documented, deliberate): accumulators are recognized
per-function and by local name only (``self._buf`` growth is governed by
the class-scoped concurrency rules' module set, not here), and a cap
check is recognized as a lexical ``len(<acc>)`` call in the loop body —
the idiom every bounded reader in this package uses.  Per the engine
contract, nested ``def``/``lambda`` bodies are not descended into.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from ..engine import Finding, ParsedModule, body_nodes, dotted_name
from . import Rule

# async-for iterables that yield raw network bytes: draining one into a
# container without a length check is the whole-stream-in-memory bug
_RAW_STREAM_ITERS = ("byte_stream", "iter_chunked", "iter_any")

_GROW_CALLS = ("extend", "append", "appendleft")


def _loop_body_nodes(loop: ast.AST) -> Iterator[ast.AST]:
    """Nodes lexically inside the loop body (nested defs excluded)."""
    stack: List[ast.AST] = list(loop.body)
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _call_name(call: ast.Call) -> str:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_bytes_init(value: ast.AST) -> bool:
    if isinstance(value, ast.Call) and _call_name(value) == "bytearray":
        return True
    return isinstance(value, ast.Constant) and isinstance(
        value.value, bytes
    )


def _len_guarded_names(loop: ast.AST) -> Set[str]:
    """Local names N with a ``len(N)`` call in the loop body — the cap
    check every bounded reader performs before (or while) growing."""
    out: Set[str] = set()
    for node in _loop_body_nodes(loop):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)
        ):
            out.add(node.args[0].id)
    return out


def _raw_stream_loop(loop: ast.AST) -> bool:
    if not isinstance(loop, ast.AsyncFor):
        return False
    it = loop.iter
    if isinstance(it, ast.Call):
        it = it.func
    name = dotted_name(it) or ""
    return name.rpartition(".")[2] in _RAW_STREAM_ITERS


def check(module: ParsedModule) -> List[Finding]:
    findings: List[Finding] = []
    for fn in module.functions():
        bytes_accs: Set[str] = set()
        for node in body_nodes(fn.node):
            if isinstance(node, ast.Assign) and _is_bytes_init(node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        bytes_accs.add(target.id)
        flagged: Set[int] = set()

        def flag(node: ast.AST, message: str) -> None:
            if id(node) in flagged:
                return
            flagged.add(id(node))
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=module.rel,
                    line=node.lineno,
                    symbol=fn.qualname,
                    message=message,
                )
            )

        for node in body_nodes(fn.node):
            if isinstance(node, ast.Call) and _call_name(node) == "deque":
                if not any(k.arg == "maxlen" for k in node.keywords):
                    flag(
                        node,
                        "`deque()` without `maxlen` on a serve-path "
                        "module — state the bound (the archive orphan "
                        "queue leaked exactly this way)",
                    )
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            guarded = _len_guarded_names(node)
            raw_chunks: Set[str] = set()
            if _raw_stream_loop(node) and isinstance(
                node.target, ast.Name
            ):
                raw_chunks.add(node.target.id)
            for sub in _loop_body_nodes(node):
                if (
                    isinstance(sub, ast.AugAssign)
                    and isinstance(sub.op, ast.Add)
                    and isinstance(sub.target, ast.Name)
                ):
                    acc = sub.target.id
                    grows_bytes = acc in bytes_accs
                    grows_raw = isinstance(
                        sub.value, ast.Name
                    ) and sub.value.id in raw_chunks
                    if (grows_bytes or grows_raw) and acc not in guarded:
                        flag(
                            sub,
                            f"`{acc} += ...` grows an ingest buffer "
                            f"inside a loop with no `len({acc})` cap "
                            "check — bound it (IngestCapError) or "
                            "check the budget in the loop body",
                        )
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _GROW_CALLS
                    and isinstance(sub.func.value, ast.Name)
                ):
                    acc = sub.func.value.id
                    grows_bytes = acc in bytes_accs
                    grows_raw = any(
                        isinstance(a, ast.Name) and a.id in raw_chunks
                        for a in sub.args
                    )
                    if (grows_bytes or grows_raw) and acc not in guarded:
                        what = (
                            "raw network chunks"
                            if grows_raw
                            else "an ingest buffer"
                        )
                        flag(
                            sub,
                            f"`{acc}.{sub.func.attr}(...)` accumulates "
                            f"{what} inside a loop with no "
                            f"`len({acc})` cap check — a hostile "
                            "upstream controls how big this gets",
                        )
    return findings


RULE = Rule(
    name="LWC018",
    summary="unbounded growable container on an ingest/serve path",
    check=check,
)
