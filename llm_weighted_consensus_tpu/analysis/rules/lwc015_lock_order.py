"""LWC015 — static lock-acquisition order vs. the declared DAG.

Lockdep, statically: every ``with``/``.acquire()`` site contributes
"held -> acquired" edges — both lexically nested ``with`` blocks and
call-mediated acquisitions (holding the shape gate, the dispatch path
calls into the staging pool, which takes its own lock; the call graph's
transitive lock closure makes that edge visible).  The registry's
``order`` tuple declares the intended DAG, enforced both ways:

* an **observed edge not declared** fails — new nesting must be written
  into the registry, where the next reader (and the runtime witness)
  can see it;
* a **declared edge no longer observed** fails — stale order rows would
  let the witness bless interleavings the code no longer produces;
* a **cycle** anywhere over declared + ``order_runtime`` + observed
  edges fails — two threads walking the cycle's locks in program order
  deadlock;
* lexically **re-entering a non-reentrant ``Lock``** fails — that is a
  self-deadlock, not an ordering question (``RLock``/``Condition``
  re-entry is legal and ignored).

Project-scoped; no declared ``CONCURRENCY_MODEL`` means no checks.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from ..concurrency import project_index
from ..engine import Finding, ParsedModule
from . import Rule

Edge = Tuple[str, str]


def _observed_edges(idx) -> Tuple[Dict[Edge, tuple], List[Finding]]:
    """-> ({(held, acquired): (path, line, symbol, how)}, re-entry
    findings)."""
    model = idx.model
    edges: Dict[Edge, tuple] = {}
    findings: List[Finding] = []
    for fkey, entry in idx.funcs.items():
        for lock, node, held in entry.facts.acquisitions:
            for h in held:
                if h == lock:
                    if model.locks[lock].get("kind") == "lock":
                        findings.append(
                            Finding(
                                rule=RULE.name,
                                path=fkey[0],
                                line=node.lineno,
                                symbol=entry.qualname,
                                message=(
                                    f"`{lock}` is a non-reentrant "
                                    "threading.Lock already held here: "
                                    "this nested acquisition deadlocks "
                                    "the thread against itself"
                                ),
                            )
                        )
                    continue
                edges.setdefault(
                    (h, lock),
                    (fkey[0], node.lineno, entry.qualname, "nested with"),
                )
    # call-mediated: holding H, a call reaches code that acquires M
    for callee, sites in idx.call_sites.items():
        locks = idx.trans_locks.get(callee, set())
        if not locks:
            continue
        for caller, call in sites:
            held = idx.funcs[caller].held_by_node().get(id(call), ())
            if not held:
                continue
            for h in held:
                for m in locks:
                    if m == h:
                        continue
                    edges.setdefault(
                        (h, m),
                        (
                            caller[0],
                            call.lineno,
                            idx.funcs[caller].qualname,
                            f"call into `{callee[1]}`",
                        ),
                    )
    return edges, findings


def _cycles(edge_set: Set[Edge]) -> List[Tuple[str, ...]]:
    adj: Dict[str, List[str]] = {}
    for u, v in edge_set:
        adj.setdefault(u, []).append(v)
    seen_cycles: Set[Tuple[str, ...]] = set()
    out: List[Tuple[str, ...]] = []

    def dfs(node: str, stack: List[str], on_stack: Set[str]) -> None:
        for nxt in adj.get(node, ()):
            if nxt in on_stack:
                cycle = tuple(stack[stack.index(nxt):])
                pivot = cycle.index(min(cycle))
                canon = cycle[pivot:] + cycle[:pivot]
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    out.append(canon)
                continue
            if nxt in visited:
                continue
            visited.add(nxt)
            stack.append(nxt)
            on_stack.add(nxt)
            dfs(nxt, stack, on_stack)
            stack.pop()
            on_stack.remove(nxt)

    visited: Set[str] = set()
    for start in sorted(adj):
        if start in visited:
            continue
        visited.add(start)
        dfs(start, [start], {start})
    return out


def project(modules: List[ParsedModule]) -> List[Finding]:
    idx = project_index(modules)
    if idx is None:
        return []
    model = idx.model
    observed, findings = _observed_edges(idx)
    declared = {tuple(e) for e in model.order}
    runtime = {tuple(e[:2]) for e in model.order_runtime}
    for edge, (path, line, symbol, how) in sorted(observed.items()):
        if edge in declared or edge in runtime:
            continue
        findings.append(
            Finding(
                rule=RULE.name,
                path=path,
                line=line,
                symbol=symbol,
                message=(
                    f"lock-order edge `{edge[0]}` -> `{edge[1]}` "
                    f"({how}) is not declared in the registry's "
                    "`order`: declare it so the DAG (and the runtime "
                    "witness) audit this nesting"
                ),
            )
        )
    for edge in sorted(declared):
        if edge in observed:
            continue
        if not (
            edge[0] in model.locks
            and edge[1] in model.locks
            and model.in_scope(edge[0], modules)
            and model.in_scope(edge[1], modules)
        ):
            continue
        findings.append(
            Finding(
                rule=RULE.name,
                path=model.module.rel,
                line=model.line,
                symbol=f"{edge[0]}->{edge[1]}",
                message=(
                    f"declared order edge `{edge[0]}` -> `{edge[1]}` "
                    "is no longer observed at any with/acquire site: "
                    "delete the stale row (or move it to "
                    "`order_runtime` with a reason if only real "
                    "interleavings exercise it)"
                ),
            )
        )
    for cycle in _cycles(set(observed) | declared | runtime):
        path = " -> ".join(cycle + (cycle[0],))
        findings.append(
            Finding(
                rule=RULE.name,
                path=model.module.rel,
                line=model.line,
                symbol=cycle[0],
                message=(
                    f"lock-order cycle {path}: two threads walking "
                    "these acquisitions in program order deadlock — "
                    "break the cycle by reordering one site (declared "
                    "+ observed edges considered together)"
                ),
            )
        )
    return findings


RULE = Rule(
    name="LWC015",
    summary="lock-acquisition order inverts or escapes the declared DAG",
    check=None,
    project=project,
)
