"""LWC004 — contextvar tokens must be reset in ``finally``.

The deadline/span/budget idiom: ``token = thing.activate()`` (or
``token = _VAR.set(value)``) establishes ambient context, and the
matching ``deactivate(token)``/``reset(token)`` must sit in a
``finally`` so a cancellation mid-request can't leave a stale
deadline/span/budget bound to the event-loop context.

Exemptions (ownership leaves the function, so pairing happens
elsewhere):

* the token is returned (``return _VAR.set(self)`` — the
  ``activate()`` implementations themselves);
* the token is stored on an object (``self._token = ...`` — the
  ``_SpanScope.__enter__``/``__exit__`` cross-method bracket);
* ``__enter__``/``__aenter__`` methods generally.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..engine import Finding, ParsedModule, body_nodes, call_base, finally_nodes
from . import Rule

_RESET_ATTRS = {"reset", "deactivate"}


def _module_contextvars(module: ParsedModule) -> Set[str]:
    names: Set[str] = set()
    for node in module.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if not isinstance(value, ast.Call):
            continue
        func = value.func
        name = (
            func.id
            if isinstance(func, ast.Name)
            else func.attr
            if isinstance(func, ast.Attribute)
            else None
        )
        if name != "ContextVar":
            continue
        for target in targets:
            if isinstance(target, ast.Name):
                names.add(target.id)
    return names


def _token_call(node: ast.Call, ctxvars: Set[str]) -> bool:
    """Is this call one that mints a context token?"""
    if not isinstance(node.func, ast.Attribute):
        return False
    if node.func.attr == "activate":
        return True
    if node.func.attr == "set":
        return (call_base(node) or "") in ctxvars
    return False


def check(module: ParsedModule) -> List[Finding]:
    ctxvars = _module_contextvars(module)
    findings: List[Finding] = []
    for fn in module.functions():
        name = fn.qualname.rsplit(".", 1)[-1]
        if name in ("__enter__", "__aenter__"):
            continue
        in_finally = finally_nodes(fn.node)
        # token name -> the minting call (only simple-Name bindings; an
        # attribute target means ownership escaped the function)
        minted = {}
        for node in body_nodes(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                len(node.targets) == 1 and isinstance(node.targets[0], ast.Name)
            ):
                continue
            value = node.value
            if isinstance(value, ast.Await):
                value = value.value
            if isinstance(value, ast.Call) and _token_call(value, ctxvars):
                minted[node.targets[0].id] = value
        if not minted:
            continue
        # reset/deactivate calls in finally blocks, by token-arg name
        reset_tokens: Set[str] = set()
        for node in body_nodes(fn.node):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _RESET_ATTRS
                and id(node) in in_finally
            ):
                continue
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        reset_tokens.add(sub.id)
        for token, mint in minted.items():
            if token in reset_tokens:
                continue
            findings.append(
                Finding(
                    rule=RULE.name,
                    path=module.rel,
                    line=mint.lineno,
                    symbol=fn.qualname,
                    message=(
                        f"context token `{token}` from "
                        f"`{call_base(mint)}.{mint.func.attr}()` has no "
                        "reset/deactivate in a finally: block — a "
                        "cancellation here leaks ambient context into the "
                        "event-loop"
                    ),
                )
            )
    return findings


RULE = Rule(
    name="LWC004",
    summary="contextvar token not reset in finally",
    check=check,
)
