"""LWC002 — ``asyncio.create_task`` result discarded.

A task whose handle is dropped can never be awaited or cancelled: its
exceptions vanish into "Task exception was never retrieved" and drain/
shutdown cannot reap it.  The rule flags create_task/ensure_future
calls used as bare expression statements (result discarded).  Binding
the handle — assignment, ``tasks.append(...)``, passing it onward —
satisfies the rule; whether the holder then awaits-or-cancels is
enforced by review plus the drain tests, not by this pass.

``TaskGroup.create_task`` receivers are exempt: the group owns the
handle structurally.
"""

from __future__ import annotations

import ast
from typing import List

from ..engine import Finding, ParsedModule, body_nodes, call_base
from . import Rule

_SPAWN_ATTRS = {"create_task", "ensure_future"}


def _is_orphaning_spawn(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        # plain ensure_future(...) / create_task(...) from a star-import
        return isinstance(call.func, ast.Name) and call.func.id in _SPAWN_ATTRS
    if call.func.attr not in _SPAWN_ATTRS:
        return False
    base = call_base(call) or ""
    # asyncio.TaskGroup retains the handle itself
    if "taskgroup" in base.lower() or base == "tg":
        return False
    return True


def check(module: ParsedModule) -> List[Finding]:
    findings: List[Finding] = []
    for fn in module.functions():
        for node in body_nodes(fn.node):
            if not isinstance(node, ast.Expr):
                continue
            value = node.value
            if isinstance(value, ast.Await):
                continue  # awaited inline: retained by definition
            if isinstance(value, ast.Call) and _is_orphaning_spawn(value):
                findings.append(
                    Finding(
                        rule=RULE.name,
                        path=module.rel,
                        line=value.lineno,
                        symbol=fn.qualname,
                        message=(
                            "create_task result discarded; keep the handle "
                            "so the task can be awaited or cancelled "
                            "(drain/shutdown cannot reap orphans)"
                        ),
                    )
                )
    return findings


RULE = Rule(
    name="LWC002",
    summary="create_task handle dropped",
    check=check,
)
