"""Per-bucket resource budgets for the mesh audit (JXA009/JXA010).

``analysis/budgets.json`` commits, for every padded and packed AOT
bucket the simulated-mesh audit lowers, the measured static footprint:
``hbm_bytes`` (argument + output + temp buffer bytes from XLA's
``memory_analysis``), ``flops`` and ``bytes_accessed`` (XLA
``cost_analysis``).  The audit re-measures on every run and compares
here:

* **JXA009 budget breach** — a measured figure above its committed
  value by more than the tolerance band: someone made the serving path
  bigger/heavier and CI should fail exactly like a lint error, BEFORE
  the regression meets real HBM.  (Shrinking below the band is reported
  too — as a prompt to re-baseline, not a failure.)
* **JXA010 coverage drift** — an audited bucket with no committed
  budget (new bucket: measure and commit it), or a committed bucket the
  audit no longer lowers (stale entry: delete it).  The committed file
  also pins the audit scope (model, mesh shape) so figures are only
  ever compared like-for-like.

Re-baselining is deliberate and explicit:
``python -m llm_weighted_consensus_tpu.analysis.mesh_audit
--write-budgets`` rewrites the file from fresh measurements; the diff
then shows every figure that moved, and review owns the judgement call.
Policy details: DESIGN.md "Static analysis v2".

Stdlib-only (json/pathlib); the jax-touching measurement lives in
``mesh_audit.py``.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .engine import Finding

# figures compared against the committed budget, in render order
METRICS = ("hbm_bytes", "flops", "bytes_accessed")

DEFAULT_TOLERANCE = 0.25  # ±25%: CPU-simulated figures are stable, but
# XLA version bumps jitter constant folding; the band absorbs noise
# while still catching the 2x-and-up regressions that matter


def default_budgets_path() -> Path:
    return Path(__file__).resolve().parent / "budgets.json"


def load_budgets(path: Optional[Path] = None) -> dict:
    path = path or default_budgets_path()
    if not path.exists():
        return {}
    return json.loads(path.read_text(encoding="utf-8"))


def scope_of(budgets: dict) -> dict:
    return budgets.get("scope", {})


def tolerance_of(budgets: dict, metric: str) -> float:
    return float(
        budgets.get("tolerance", {}).get(metric, DEFAULT_TOLERANCE)
    )


def compare_budgets(
    measured: Dict[str, Dict[str, float]],
    budgets: dict,
    scope: Optional[dict] = None,
) -> List[Finding]:
    """Measured per-bucket figures vs the committed file.

    ``measured`` maps bucket label -> {metric: value}.  ``scope`` is the
    audit's current (model, mesh, ...) identity; when it differs from
    the committed scope the figures aren't comparable and the whole file
    is reported as one JXA010 finding instead of N bogus breaches."""
    findings: List[Finding] = []
    committed = budgets.get("buckets", {})
    if not budgets:
        findings.append(
            Finding(
                rule="JXA010",
                path="analysis/budgets.json",
                line=0,
                message=(
                    "no committed budgets: run `python -m "
                    "llm_weighted_consensus_tpu.analysis.mesh_audit "
                    "--write-budgets` and commit the result so capacity "
                    "regressions fail CI"
                ),
            )
        )
        return findings
    if scope is not None and scope_of(budgets) != scope:
        findings.append(
            Finding(
                rule="JXA010",
                path="analysis/budgets.json",
                line=0,
                message=(
                    f"committed budget scope {scope_of(budgets)} does not "
                    f"match the audited configuration {scope}; re-baseline "
                    "with --write-budgets under the new configuration"
                ),
            )
        )
        return findings
    for label, figures in sorted(measured.items()):
        entry = committed.get(label)
        if entry is None:
            findings.append(
                Finding(
                    rule="JXA010",
                    path="analysis/budgets.json",
                    line=0,
                    message=(
                        f"audited bucket `{label}` has no committed "
                        "budget entry; measure and commit it "
                        "(--write-budgets)"
                    ),
                )
            )
            continue
        for metric in METRICS:
            if metric not in figures or metric not in entry:
                continue
            got, want = float(figures[metric]), float(entry[metric])
            if want <= 0:
                continue
            band = tolerance_of(budgets, metric)
            ratio = got / want
            if ratio > 1.0 + band:
                findings.append(
                    Finding(
                        rule="JXA009",
                        path="analysis/budgets.json",
                        line=0,
                        symbol=label,
                        message=(
                            f"`{label}` {metric} measured {got:.0f} vs "
                            f"budget {want:.0f} ({ratio:.2f}x, band "
                            f"±{band:.0%}): the serving path outgrew its "
                            "committed resource envelope"
                        ),
                    )
                )
            elif ratio < 1.0 - band:
                findings.append(
                    Finding(
                        rule="JXA009",
                        path="analysis/budgets.json",
                        line=0,
                        symbol=label,
                        message=(
                            f"`{label}` {metric} measured {got:.0f} vs "
                            f"budget {want:.0f} ({ratio:.2f}x, band "
                            f"±{band:.0%}): the path shrank well below "
                            "budget — re-baseline so the envelope stays "
                            "tight"
                        ),
                    )
                )
    for label in sorted(committed):
        if label not in measured:
            findings.append(
                Finding(
                    rule="JXA010",
                    path="analysis/budgets.json",
                    line=0,
                    symbol=label,
                    message=(
                        f"stale budget entry `{label}`: the audit no "
                        "longer lowers this bucket — delete the entry "
                        "(budgets only ever shrink honestly)"
                    ),
                )
            )
    return findings


def replicated_allowlist(budgets: dict) -> List[dict]:
    return budgets.get("replicated_allowlist", [])


def replicated_threshold(budgets: dict) -> int:
    return int(budgets.get("replicated_threshold_bytes", 1 << 20))


def check_allowlist_stale(
    allowlist: Sequence[dict], matched_patterns: set
) -> List[Finding]:
    """Allowlist rows whose pattern matched no oversized-replicated leaf
    in the whole audit — stale permission that would silently cover a
    future regression (JXA010, same delete-it contract as budgets)."""
    findings: List[Finding] = []
    for entry in allowlist:
        if entry.get("pattern") not in matched_patterns:
            findings.append(
                Finding(
                    rule="JXA010",
                    path="analysis/budgets.json",
                    line=0,
                    symbol=entry.get("pattern"),
                    message=(
                        "stale replicated_allowlist entry "
                        f"`{entry.get('pattern')}`: it matches no "
                        "oversized replicated tensor anymore — delete it"
                    ),
                )
            )
    return findings


def allowlisted(path: str, allowlist: Sequence[dict]) -> Optional[str]:
    """First allowlist pattern fully matching the leaf path, or None."""
    for entry in allowlist:
        pattern = entry.get("pattern", "")
        if pattern and re.fullmatch(pattern, path):
            return pattern
    return None
