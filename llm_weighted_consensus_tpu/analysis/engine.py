"""The AST lint engine: parse once, run every rule, apply the baseline.

Design constraints that shaped this module:

* **stdlib only** — the tier-1 gate runs this over the whole package on
  every CI pass, so it must cost parse time, not import time (no jax,
  no aiohttp; ``rules/`` modules are equally import-light);
* **function-scoped analysis** — every rule reasons about one function
  body at a time and does NOT descend into nested ``def``/``lambda``
  (those execute in a different dynamic context: an executor thread, a
  later task, a callback).  Nested definitions are visited as their own
  functions instead;
* **symbol-stable baselining** — suppressions match on
  ``(rule, path, symbol)``, never on line numbers, so unrelated edits
  above a known-intentional site don't churn ``baseline.json``.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

# directories never linted inside the package tree
_EXCLUDED_DIRS = {"__pycache__"}


@dataclass
class Finding:
    """One invariant violation (or audit failure).

    ``path`` is repo-relative posix for lint findings and a virtual
    ``jaxpr:<label>`` path for audit findings; ``symbol`` is the
    enclosing function's qualname (``Class.method``) when one exists —
    the baseline matching key alongside rule and path.
    """

    rule: str
    path: str
    line: int
    message: str
    symbol: Optional[str] = None

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}{sym}: {self.message}"


@dataclass
class FunctionInfo:
    """One function definition, with the context rules need."""

    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_async: bool
    # immediately enclosing class name, if any ("" at module scope)
    class_name: str = ""


@dataclass
class ClassInfo:
    """One class definition with its direct methods — the unit the
    class-scoped concurrency rules (LWC014–016) reason over, where the
    per-function rules reason over one body at a time."""

    name: str
    qualname: str
    node: ast.ClassDef
    # direct methods only (nested defs inside a method are separate
    # FunctionInfo entries in ``functions()``, per the engine contract)
    methods: List[FunctionInfo] = field(default_factory=list)


@dataclass
class ParsedModule:
    path: Path
    rel: str  # repo-relative posix path
    source: str
    tree: ast.Module
    _functions: Optional[List[FunctionInfo]] = field(
        default=None, repr=False
    )
    _classes: Optional[List[ClassInfo]] = field(default=None, repr=False)

    def functions(self) -> List[FunctionInfo]:
        """Every function/method in the module (nested ones included,
        each as its own entry), with dotted qualnames."""
        if self._functions is None:
            self._functions = list(_collect_functions(self.tree))
        return self._functions

    def classes(self) -> List[ClassInfo]:
        """Every class in the module (nested ones included), each with
        its direct methods as FunctionInfo entries."""
        if self._classes is None:
            self._classes = list(_collect_classes(self.tree))
        return self._classes


def _collect_functions(
    tree: ast.Module,
) -> Iterator[FunctionInfo]:
    def walk(node: ast.AST, prefix: str, class_name: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}" if prefix else child.name
                yield FunctionInfo(
                    qualname=qual,
                    node=child,
                    is_async=isinstance(child, ast.AsyncFunctionDef),
                    class_name=class_name,
                )
                yield from walk(child, f"{qual}.", class_name)
            elif isinstance(child, ast.ClassDef):
                qual = f"{prefix}{child.name}" if prefix else child.name
                yield from walk(child, f"{qual}.", child.name)
            else:
                yield from walk(child, prefix, class_name)

    yield from walk(tree, "", "")


def _collect_classes(tree: ast.Module) -> Iterator[ClassInfo]:
    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                qual = f"{prefix}{child.name}" if prefix else child.name
                methods = [
                    FunctionInfo(
                        qualname=f"{qual}.{m.name}",
                        node=m,
                        is_async=isinstance(m, ast.AsyncFunctionDef),
                        class_name=child.name,
                    )
                    for m in child.body
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                ]
                yield ClassInfo(
                    name=child.name,
                    qualname=qual,
                    node=child,
                    methods=methods,
                )
                yield from walk(child, f"{qual}.")
            elif isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                qual = f"{prefix}{child.name}" if prefix else child.name
                yield from walk(child, f"{qual}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def body_nodes(func: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested function or
    lambda definitions — the function-scoped analysis contract."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def finally_nodes(func: ast.AST) -> set:
    """The set of nodes (by id) living under any ``finally:`` block of
    this function — where releases/resets must land."""
    out: set = set()
    for node in body_nodes(func):
        if isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    out.add(id(sub))
    return out


def call_attr(call: ast.Call) -> Optional[str]:
    """``x.y.z(...)`` -> ``"z"``; None for plain-name calls."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    return None


def call_base(call: ast.Call) -> Optional[str]:
    """``x.y.z(...)`` -> ``"x.y"`` (the receiver expression's source)."""
    if isinstance(call.func, ast.Attribute):
        try:
            return ast.unparse(call.func.value)
        except Exception:  # malformed/exotic node: no receiver match
            return None
    return None


def enclosing_symbol(
    module: ParsedModule, node: ast.AST
) -> Optional[str]:
    """Qualname of the innermost function whose span contains ``node``
    (None for module/class-level code) — the baseline symbol key for
    rules that walk the whole tree instead of per-function bodies."""
    line = getattr(node, "lineno", 0)
    best = None
    best_span = None
    for fn in module.functions():
        end = getattr(fn.node, "end_lineno", fn.node.lineno)
        if fn.node.lineno <= line <= end:
            span = end - fn.node.lineno
            if best_span is None or span < best_span:
                best, best_span = fn.qualname, span
    return best


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` / ``a`` -> its dotted source; None otherwise."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


# ---------------------------------------------------------------------------
# Walking + running
# ---------------------------------------------------------------------------


def package_root() -> Path:
    """The ``llm_weighted_consensus_tpu`` package directory."""
    return Path(__file__).resolve().parent.parent


def repo_root() -> Path:
    return package_root().parent


def source_files(root: Optional[Path] = None) -> List[Path]:
    root = root or package_root()
    files = []
    for path in sorted(root.rglob("*.py")):
        if any(part in _EXCLUDED_DIRS for part in path.parts):
            continue
        files.append(path)
    return files


def parse_module(path: Path, rel_to: Optional[Path] = None) -> ParsedModule:
    source = path.read_text(encoding="utf-8")
    tree = ast.parse(source, filename=str(path))
    base = rel_to or repo_root()
    try:
        rel = path.resolve().relative_to(base.resolve()).as_posix()
    except ValueError:
        rel = path.name
    return ParsedModule(path=path, rel=rel, source=source, tree=tree)


def run_lint(
    paths: Optional[Sequence[Path]] = None,
    rules: Optional[Sequence] = None,
    rel_to: Optional[Path] = None,
) -> List[Finding]:
    """Parse every file once, run every rule over each parsed module,
    then every project-scoped rule over the whole parsed set (rules
    whose invariant spans modules — e.g. declared-vs-used registries —
    set ``Rule.project`` instead of/alongside ``check``)."""
    from .rules import ALL_RULES

    rules = list(rules) if rules is not None else list(ALL_RULES)
    files = list(paths) if paths is not None else source_files()
    modules = [parse_module(path, rel_to=rel_to) for path in files]
    findings: List[Finding] = []
    for module in modules:
        for rule in rules:
            if rule.check is not None:
                findings.extend(rule.check(module))
    for rule in rules:
        if getattr(rule, "project", None) is not None:
            findings.extend(rule.project(modules))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# Baseline: (rule, path, symbol) suppressions with written reasons
# ---------------------------------------------------------------------------


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Optional[Path] = None) -> List[dict]:
    path = path or default_baseline_path()
    if not path.exists():
        return []
    data = json.loads(path.read_text(encoding="utf-8"))
    entries = data["suppressions"] if isinstance(data, dict) else data
    for entry in entries:
        if "reason" not in entry or not str(entry["reason"]).strip():
            raise ValueError(
                f"baseline entry {entry!r} has no reason: every "
                "suppression must say WHY the pattern is intentional"
            )
    return entries


def baseline_entry(finding: Finding, reason: str) -> dict:
    return {
        "rule": finding.rule,
        "path": finding.path,
        "symbol": finding.symbol,
        "reason": reason,
    }


def _matches(entry: dict, finding: Finding) -> bool:
    return (
        entry.get("rule") == finding.rule
        and entry.get("path") == finding.path
        and entry.get("symbol") == finding.symbol
    )


def apply_baseline(
    findings: Iterable[Finding], baseline: Sequence[dict]
) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """-> (kept, suppressed, stale_entries).

    ``stale_entries`` are baseline rows that matched nothing — the
    underlying code was fixed, so the suppression must be deleted (the
    CLI fails on them; a baseline only ever shrinks honestly)."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    used = [False] * len(baseline)
    for finding in findings:
        hit = False
        for i, entry in enumerate(baseline):
            if _matches(entry, finding):
                used[i] = True
                hit = True
        (suppressed if hit else kept).append(finding)
    stale = [entry for entry, u in zip(baseline, used) if not u]
    return kept, suppressed, stale
