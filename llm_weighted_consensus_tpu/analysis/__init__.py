"""First-party invariant checker: the conventions this codebase relies
on, machine-enforced.

Two engines, one CLI (``python -m llm_weighted_consensus_tpu.analysis``):

* **AST lint** (``engine.py`` + ``rules/``) — walks the package source
  and enforces the async-cancellation / resource-release / contextvar
  -token / Decimal-purity / error-envelope invariants that earlier PRs
  had to hand-audit (the PR 2 review alone closed six cancellation and
  budget holes that these rules now catch mechanically).
* **jaxpr audit** (``jaxpr_audit.py``) — lowers the embedder/consensus
  serving functions for each AOT bucket on CPU and statically asserts
  the compiled hot path's invariants: no host callbacks or transfers,
  no int8->float dequant regressions in the fused W8A8 path, no f64
  promotion leaks, and every serving bucket resolving to a precompiled
  executable with zero stray jit specializations.

Both report :class:`~.engine.Finding` objects; intentional deviations
live in ``analysis/baseline.json`` with a written ``reason`` — the CLI
fails on any non-baselined finding AND on stale baseline entries, so
the suppression list can only shrink honestly.

The lint engine imports nothing heavy (stdlib ``ast`` only); jax is
imported only when the jaxpr audit actually runs.
"""

from __future__ import annotations

from .engine import (  # noqa: F401
    Finding,
    ParsedModule,
    apply_baseline,
    baseline_entry,
    default_baseline_path,
    load_baseline,
    package_root,
    parse_module,
    run_lint,
    source_files,
)

__all__ = [
    "Finding",
    "ParsedModule",
    "apply_baseline",
    "baseline_entry",
    "default_baseline_path",
    "load_baseline",
    "package_root",
    "parse_module",
    "run_lint",
    "source_files",
]
