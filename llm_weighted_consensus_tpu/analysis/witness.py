"""LockWitness: runtime lockdep against the declared acquisition DAG.

The static rules (LWC014–016) prove what the call graph can see; the
witness checks what actually happens.  Opt-in (``LOCK_WITNESS=1`` on
the server, explicit wiring in the chaos/soak drills), it wraps the
registered threading primitives in thin proxies that record, per
thread, the order locks are really taken in, and validates every new
edge against the union of the registry's ``order`` + ``order_runtime``
DAG and the edges observed so far:

* acquiring B while holding A records edge ``A -> B``; if ``B -> A``
  is already reachable in the union graph, two threads can walk the
  cycle from opposite ends and deadlock — an **inversion** violation;
* re-acquiring a non-reentrant ``Lock`` the same thread already holds
  is a **reentrant** violation (a guaranteed self-deadlock — the
  static rule catches the lexical case, the witness the dynamic one);
* an observed edge absent from the declared DAG lands in
  ``undeclared`` — the drills assert it stays empty, which is the
  runtime half of the registry's both-ways contract;
* ``Condition.wait`` atomically releases the condition for the sleep:
  the proxy pops the held entry before waiting and re-pushes on wake,
  so edges are judged against what the thread REALLY holds.

The witness never blocks the application: proxies delegate to the real
primitive first and record after, so a violation is reported, not
injected.  Overhead is one dict update per acquisition (the soak bench
holds it under 2%); cross-thread state lives behind the witness's own
leaf mutex, held only for the bookkeeping instant.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

Edge = Tuple[str, str]


class _LockProxy:
    """Wraps a ``threading.Lock``/``RLock``/``Condition``; records
    acquire/release order through the owning witness.  Supports the
    ``with`` protocol, raw acquire/release, and the condition surface
    (``wait``/``wait_for``/``notify``/``notify_all``)."""

    def __init__(self, witness: "LockWitness", key: str, lock) -> None:
        self._witness = witness
        self._key = key
        self._lock = lock

    def acquire(self, *args, **kwargs):
        got = self._lock.acquire(*args, **kwargs)
        if got:
            self._witness._on_acquire(self._key)
        return got

    def release(self) -> None:
        self._witness._on_release(self._key)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- condition surface (delegated; wait releases the held entry) ---------

    def wait(self, timeout: Optional[float] = None):
        self._witness._on_release(self._key)
        try:
            return self._lock.wait(timeout)
        finally:
            self._witness._on_acquire(self._key)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._witness._on_release(self._key)
        try:
            return self._lock.wait_for(predicate, timeout)
        finally:
            self._witness._on_acquire(self._key)

    def __getattr__(self, name):
        return getattr(self._lock, name)


class LockWitness:
    def __init__(self, model: Optional[dict] = None) -> None:
        if model is None:
            from .concurrency_model import CONCURRENCY_MODEL as model
        self._kinds: Dict[str, str] = {
            key: entry.get("kind", "lock")
            for key, entry in model["locks"].items()
        }
        self._declared: Set[Edge] = {
            tuple(e) for e in model.get("order", ())
        } | {tuple(e[:2]) for e in model.get("order_runtime", ())}
        self._local = threading.local()
        self._mu = threading.Lock()
        self._edges: Dict[Edge, int] = {}
        self._violations: List[dict] = []
        self._acquisitions = 0

    # -- wiring --------------------------------------------------------------

    def wrap_lock(self, key: str, lock) -> _LockProxy:
        """``obj._lock = witness.wrap_lock("Class._lock", obj._lock)``."""
        return _LockProxy(self, key, lock)

    def wrap_gate(self, gate, key: str = "_ShapeGate._cond"):
        """Patch a ``_ShapeGate`` instance so holding its shared or
        exclusive side counts as holding the gate's logical lock
        (``dispatch_guard`` delegates to ``shared`` and is covered).
        The internal condition is NOT separately wrapped — the gate is
        one logical lock, bookkeeping instants included."""
        from contextlib import contextmanager

        for name in ("shared", "exclusive"):
            orig = getattr(gate, name)

            @contextmanager
            def wrapped(_orig=orig):
                with _orig():
                    self._on_acquire(key)
                    try:
                        yield
                    finally:
                        self._on_release(key)

            setattr(gate, name, wrapped)
        return gate

    # -- recording -----------------------------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _on_acquire(self, key: str) -> None:
        stack = self._stack()
        if key in stack and self._kinds.get(key, "lock") == "lock":
            with self._mu:
                self._acquisitions += 1
                self._violations.append(
                    {
                        "kind": "reentrant",
                        "lock": key,
                        "thread": threading.current_thread().name,
                        "held": list(stack),
                    }
                )
            stack.append(key)
            return
        new_edges = [(h, key) for h in dict.fromkeys(stack) if h != key]
        stack.append(key)
        with self._mu:
            self._acquisitions += 1
            for edge in new_edges:
                first = edge not in self._edges
                self._edges[edge] = self._edges.get(edge, 0) + 1
                if first and self._reachable_locked(edge[1], edge[0]):
                    self._violations.append(
                        {
                            "kind": "inversion",
                            "edge": list(edge),
                            "thread": threading.current_thread().name,
                            "held": list(stack[:-1]),
                        }
                    )

    def _on_release(self, key: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == key:
                del stack[i]
                return

    # caller-holds-lock: LockWitness._mu (only _on_acquire calls this, inside its with-_mu block)
    def _reachable_locked(self, src: str, dst: str) -> bool:
        """Whether ``src -> ... -> dst`` exists in declared+observed
        edges (caller holds ``self._mu``; the new edge is excluded by
        construction — it was just inserted, reverse reach means
        cycle)."""
        adj: Dict[str, Set[str]] = {}
        for u, v in self._declared | set(self._edges):
            adj.setdefault(u, set()).add(v)
        frontier, seen = [src], {src}
        while frontier:
            node = frontier.pop()
            if node == dst:
                return True
            for nxt in adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return dst in seen

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._mu:
            edges = {e: c for e, c in self._edges.items()}
            violations = [dict(v) for v in self._violations]
            acquisitions = self._acquisitions
        undeclared = sorted(e for e in edges if e not in self._declared)
        return {
            "acquisitions": acquisitions,
            "edges": [
                {"edge": list(e), "count": c}
                for e, c in sorted(edges.items())
            ],
            "undeclared": [list(e) for e in undeclared],
            "violations": violations,
        }

    def summary_line(self) -> str:
        snap = self.snapshot()
        return (
            f"lock witness: {snap['acquisitions']} acquisitions, "
            f"{len(snap['edges'])} edge(s), "
            f"{len(snap['undeclared'])} undeclared, "
            f"{len(snap['violations'])} violation(s)"
        )
