"""Shared machinery for the concurrency-discipline rules (LWC014–016).

The per-function lints never cross function boundaries; the concurrency
auditor must — a data race is by definition a property of two call
paths.  This module builds, once per parsed set, a ``ProjectIndex``:

* the **lock model** (``CONCURRENCY_MODEL``, declared by
  ``analysis/concurrency_model.py`` for the package or inline by a
  fixture — a parsed set that declares no model checks nothing, so
  single-file lint invocations stay self-contained);
* every **lock creation site** (``self.x = threading.Lock()`` et al.)
  for the both-ways registry check;
* per-function **lock facts**: which registered locks each statement
  lexically holds (``with`` nesting), and every acquisition event with
  the locks held at that instant — the static lock-order graph's raw
  edges;
* a name-resolved **call graph** with transitive lock closure, so
  "holding the gate, the dispatch calls into the staging pool which
  takes its own lock" becomes a visible order edge.  Resolution is
  deliberately over-approximate (attribute calls resolve to every
  same-named method/function in the package, minus a blacklist of
  container/stdlib method names that would wire dict.get to
  ``ChoiceIndexer.get``) — over-approximation can only add edges to
  declare, never hide a real one.  Local aliases (``fn = self._x``)
  and the batcher's ``getattr(self, "_dispatch_" + kind)`` prefix
  dispatch are resolved so the guarded dispatch path stays visible;
* **thread entry points**: ``threading.Thread(target=...)`` roots,
  executor ``submit``/``run_in_executor`` roots (an executor root
  counts double — every pool here has >= 2 workers), and the asyncio
  loop (all ``async def`` share ONE entry — the loop is one thread),
  propagated over the call graph.  A field whose accessing methods are
  reached from >= 2 entry weights is cross-thread state.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import ParsedModule, body_nodes

MODEL_NAME = "CONCURRENCY_MODEL"

# threading constructors that create a registrable primitive
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_CTOR_KIND = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# attribute names never resolved through the call graph: container /
# stdlib / future methods whose package-level namesakes (dict.get vs
# ChoiceIndexer.get) would wire false edges through every critical
# section that touches a dict
_GENERIC_ATTRS = {
    "get",
    "pop",
    "popleft",
    "append",
    "appendleft",
    "extend",
    "extendleft",
    "insert",
    "items",
    "keys",
    "values",
    "setdefault",
    "update",
    "add",
    "clear",
    "remove",
    "discard",
    "sort",
    "copy",
    "count",
    "index",
    "join",
    "split",
    "strip",
    "startswith",
    "endswith",
    "format",
    "encode",
    "decode",
    "lower",
    "upper",
    "replace",
    "put",
    "done",
    "cancel",
    "cancelled",
    "set",
    "is_set",
    "snapshot",
    "render",
    "total_seconds",
}

# modules whose functions are never bare-name call-resolution TARGETS:
# the witness's proxies are injected dynamically (never statically
# reachable), and over-approximate resolution would otherwise wire
# every ``cond.wait()``/``lock.acquire()`` in the package into the
# proxy's bookkeeping (and its leaf mutex), fabricating order edges.
# Their own bodies are still indexed and checked (LWC014 guards the
# witness's fields; ``self.m()`` resolution inside them stays precise).
_OUT_OF_GRAPH_SUFFIXES = ("analysis/witness.py",)

_EXEMPT_RE = re.compile(
    r"#\s*caller-holds-lock:\s*(?P<lock>[\w.]+)\s*(?:[(\[—–-]\s*"
    r"(?P<reason>[^)\]]*\S)\s*[)\]]?)?"
)


# ---------------------------------------------------------------------------
# Lock model
# ---------------------------------------------------------------------------


@dataclass
class LockModel:
    locks: Dict[str, dict]
    order: List[Tuple[str, str]]
    order_runtime: List[tuple]
    module: ParsedModule
    line: int

    def in_scope(self, key: str, modules: Sequence[ParsedModule]) -> bool:
        """Whether the entry's declaring module is part of this parsed
        set — staleness is only judged when it is (single-file runs
        must not call every other entry stale)."""
        suffix = self.locks[key].get("module", "")
        return any(m.rel.endswith(suffix) for m in modules)

    def lock_for(self, class_name: str, attr: str) -> Optional[str]:
        key = f"{class_name}.{attr}"
        return key if key in self.locks else None

    def via(self) -> Dict[str, str]:
        """acquire_via method name -> lock key."""
        out: Dict[str, str] = {}
        for key, entry in self.locks.items():
            for name in entry.get("acquire_via", ()):
                out[name] = key
        return out


def load_model(modules: Sequence[ParsedModule]) -> Optional[LockModel]:
    """The parsed set's ``CONCURRENCY_MODEL`` literal, if any module
    declares one at module level (last declaration wins)."""
    found = None
    for module in modules:
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not any(
                isinstance(t, ast.Name) and t.id == MODEL_NAME
                for t in node.targets
            ):
                continue
            try:
                data = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                continue
            if isinstance(data, dict) and isinstance(
                data.get("locks"), dict
            ):
                found = LockModel(
                    locks=dict(data["locks"]),
                    order=[tuple(e) for e in data.get("order", ())],
                    order_runtime=[
                        tuple(e) for e in data.get("order_runtime", ())
                    ],
                    module=module,
                    line=node.lineno,
                )
    return found


@dataclass
class LockSite:
    """One ``<target> = threading.Lock()`` creation site."""

    key: str  # "Class.attr" (or bare name at module level)
    kind: str  # "lock" | "rlock" | "condition"
    module: ParsedModule
    node: ast.AST
    class_name: str  # "" for module-level locks


def _ctor_kind(value: ast.AST) -> Optional[str]:
    """``threading.Lock()`` / ``Lock()`` -> "lock"; None otherwise."""
    if not isinstance(value, ast.Call):
        return None
    func = value.func
    name = None
    if isinstance(func, ast.Attribute) and isinstance(
        func.value, ast.Name
    ):
        if func.value.id == "threading":
            name = func.attr
    elif isinstance(func, ast.Name):
        name = func.id
    return _CTOR_KIND.get(name) if name in _LOCK_CTORS else None


def lock_sites(modules: Sequence[ParsedModule]) -> List[LockSite]:
    """Every threading-primitive creation site in the parsed set."""
    sites: List[LockSite] = []
    for module in modules:
        # module-level: NAME = threading.Lock()
        for node in module.tree.body:
            if isinstance(node, ast.Assign):
                kind = _ctor_kind(node.value)
                if kind and isinstance(node.targets[0], ast.Name):
                    sites.append(
                        LockSite(
                            key=node.targets[0].id,
                            kind=kind,
                            module=module,
                            node=node,
                            class_name="",
                        )
                    )
        # instance fields: self.x = threading.Lock() in any method
        for cls in module.classes():
            for method in cls.methods:
                for node in body_nodes(method.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    kind = _ctor_kind(node.value)
                    if kind is None:
                        continue
                    target = node.targets[0]
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        sites.append(
                            LockSite(
                                key=f"{cls.name}.{target.attr}",
                                kind=kind,
                                module=module,
                                node=node,
                                class_name=cls.name,
                            )
                        )
    return sites


# ---------------------------------------------------------------------------
# Per-function lock facts
# ---------------------------------------------------------------------------


@dataclass
class LockFacts:
    """Lexical lock state for one function body."""

    # (lock key, acquisition node, locks held just before)
    acquisitions: List[Tuple[str, ast.AST, Tuple[str, ...]]] = field(
        default_factory=list
    )
    # every body node with the registered locks lexically held there
    nodes: List[Tuple[ast.AST, Tuple[str, ...]]] = field(
        default_factory=list
    )


def _resolve_lock_expr(
    expr: ast.AST, class_name: str, model: LockModel, via: Dict[str, str]
) -> Optional[str]:
    """A with-item context expression (or acquire receiver) -> lock key.

    ``self._lock`` resolves inside the owning class; ``x.shared()`` /
    ``x.exclusive()`` / ``x.dispatch_guard()`` resolve through
    ``acquire_via``; a bare name resolves to a module-level lock key.
    """
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Attribute):
            hit = via.get(expr.func.attr)
            if hit is not None:
                return hit
        if isinstance(expr.func, ast.Name):
            hit = via.get(expr.func.id)
            if hit is not None:
                return hit
        return None
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and class_name
    ):
        return model.lock_for(class_name, expr.attr)
    if isinstance(expr, ast.Name) and expr.id in model.locks:
        return expr.id
    return None


def lock_facts(
    func_node: ast.AST,
    class_name: str,
    model: LockModel,
    via: Dict[str, str],
) -> LockFacts:
    facts = LockFacts()

    def note(node: ast.AST, held: Tuple[str, ...]) -> None:
        facts.nodes.append((node, held))
        # raw lock.acquire() call: an acquisition event for the order
        # graph (no held-region tracking — `with` is the idiom)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            key = _resolve_lock_expr(
                node.func.value, class_name, model, via
            )
            if key is not None:
                facts.acquisitions.append((key, node, held))

    def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    note(sub, inner)
                key = _resolve_lock_expr(
                    item.context_expr, class_name, model, via
                )
                if key is not None:
                    facts.acquisitions.append((key, node, inner))
                    inner = inner + (key,)
            for stmt in node.body:
                visit(stmt, inner)
            return
        note(node, held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in func_node.body:
        visit(stmt, ())
    return facts


# ---------------------------------------------------------------------------
# Exemption comments
# ---------------------------------------------------------------------------


@dataclass
class Exemption:
    lock: str
    reason: Optional[str]
    line: int


def method_exemptions(
    module: ParsedModule, func_node: ast.AST
) -> List[Exemption]:
    """``# caller-holds-lock: <Lock.key> (reason)`` on the ``def`` line
    or the line immediately above it."""
    lines = module.source.splitlines()
    out: List[Exemption] = []
    for lineno in (func_node.lineno - 1, func_node.lineno):
        if 1 <= lineno <= len(lines):
            match = _EXEMPT_RE.search(lines[lineno - 1])
            if match:
                reason = match.group("reason")
                out.append(
                    Exemption(
                        lock=match.group("lock"),
                        reason=reason.strip() if reason else None,
                        line=lineno,
                    )
                )
    return out


# ---------------------------------------------------------------------------
# Project index: call graph, entry points, transitive locks
# ---------------------------------------------------------------------------

FKey = Tuple[str, str]  # (module.rel, qualname)


@dataclass
class FuncEntry:
    module: ParsedModule
    qualname: str
    node: ast.AST
    is_async: bool
    class_name: str
    facts: LockFacts
    _held_map: Optional[Dict[int, Tuple[str, ...]]] = None

    def held_by_node(self) -> Dict[int, Tuple[str, ...]]:
        """id(node) -> registered locks lexically held at that node."""
        if self._held_map is None:
            self._held_map = {
                id(node): held for node, held in self.facts.nodes
            }
        return self._held_map


class ProjectIndex:
    def __init__(
        self, modules: Sequence[ParsedModule], model: LockModel
    ) -> None:
        self.modules = list(modules)
        self.model = model
        self.via = model.via()
        self.funcs: Dict[FKey, FuncEntry] = {}
        self.methods_by_name: Dict[str, List[FKey]] = {}
        self.module_funcs_by_name: Dict[str, List[FKey]] = {}
        self.class_methods: Dict[Tuple[str, str], Dict[str, FKey]] = {}
        for module in modules:
            in_graph = not module.rel.endswith(_OUT_OF_GRAPH_SUFFIXES)
            for cls in module.classes():
                table: Dict[str, FKey] = {}
                for m in cls.methods:
                    fkey = (module.rel, m.qualname)
                    table[m.node.name] = fkey
                    if in_graph:
                        self.methods_by_name.setdefault(
                            m.node.name, []
                        ).append(fkey)
                self.class_methods[(module.rel, cls.name)] = table
            for fn in module.functions():
                fkey = (module.rel, fn.qualname)
                self.funcs[fkey] = FuncEntry(
                    module=module,
                    qualname=fn.qualname,
                    node=fn.node,
                    is_async=fn.is_async,
                    class_name=fn.class_name,
                    facts=lock_facts(
                        fn.node, fn.class_name, model, self.via
                    ),
                )
                if (
                    in_graph
                    and fn.class_name == ""
                    and "." not in fn.qualname
                ):
                    self.module_funcs_by_name.setdefault(
                        fn.qualname, []
                    ).append(fkey)
        self.call_edges: Dict[FKey, Set[FKey]] = {}
        self.entry_sets: Dict[FKey, Set[str]] = {
            k: set() for k in self.funcs
        }
        self._build_graph()
        self.direct_locks: Dict[FKey, Set[str]] = {
            k: {key for key, _, _ in e.facts.acquisitions}
            for k, e in self.funcs.items()
        }
        self.trans_locks = self._closure(self.direct_locks)
        self.direct_blocking: Dict[FKey, Optional[str]] = {
            k: _first_blocking(e.node) for k, e in self.funcs.items()
        }
        self._propagate_entries()

    # -- resolution ---------------------------------------------------------

    def _local_aliases(
        self, fkey: FKey, entry: FuncEntry
    ) -> Dict[str, Set[FKey]]:
        """``fn = self._dispatch_packed`` / ``fn = getattr(self,
        "_dispatch_" + kind)`` local single-name aliases."""
        aliases: Dict[str, Set[FKey]] = {}
        table = self.class_methods.get(
            (entry.module.rel, entry.class_name), {}
        )
        for node in body_nodes(entry.node):
            if not isinstance(node, ast.Assign):
                continue
            if len(node.targets) != 1 or not isinstance(
                node.targets[0], ast.Name
            ):
                continue
            name = node.targets[0].id
            targets = self._ref_targets(node.value, entry, table)
            if targets:
                aliases.setdefault(name, set()).update(targets)
        return aliases

    def _prefix_methods(
        self, table: Dict[str, FKey], prefix: str
    ) -> Set[FKey]:
        return {
            fkey
            for mname, fkey in table.items()
            if mname.startswith(prefix)
        }

    def _ref_targets(
        self,
        expr: ast.AST,
        entry: FuncEntry,
        table: Dict[str, FKey],
    ) -> Set[FKey]:
        """A callable *reference* (not a call) -> candidate functions:
        ``self.m``, ``x.m``, a bare name, ``functools.partial(f, ...)``
        or ``getattr(self, "prefix" + dynamic)``."""
        if isinstance(expr, ast.Attribute):
            if (
                isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in table
            ):
                return {table[expr.attr]}
            if expr.attr in _GENERIC_ATTRS:
                return set()
            out = set(self.methods_by_name.get(expr.attr, ()))
            out.update(self.module_funcs_by_name.get(expr.attr, ()))
            return out
        if isinstance(expr, ast.Name):
            hits = self.module_funcs_by_name.get(expr.id, ())
            same = {k for k in hits if k[0] == entry.module.rel}
            return same or set(hits)
        if isinstance(expr, ast.Call):
            func = expr.func
            fname = (
                func.id
                if isinstance(func, ast.Name)
                else func.attr if isinstance(func, ast.Attribute) else None
            )
            if fname == "partial" and expr.args:
                return self._ref_targets(expr.args[0], entry, table)
            if fname == "getattr" and len(expr.args) >= 2:
                prefix = _literal_prefix(expr.args[1])
                if prefix is not None:
                    return self._prefix_methods(table, prefix)
        return set()

    def _call_targets(
        self,
        call: ast.Call,
        entry: FuncEntry,
        table: Dict[str, FKey],
        aliases: Dict[str, Set[FKey]],
    ) -> Set[FKey]:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in aliases:
                return aliases[func.id]
            hits = self.module_funcs_by_name.get(func.id, ())
            same = {k for k in hits if k[0] == entry.module.rel}
            return same or set(hits)
        if isinstance(func, ast.Attribute):
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr in table
            ):
                return {table[func.attr]}
            if func.attr in _GENERIC_ATTRS:
                return set()
            out = set(self.methods_by_name.get(func.attr, ()))
            out.update(self.module_funcs_by_name.get(func.attr, ()))
            return out
        if isinstance(func, ast.Call):  # getattr(self, "...")(args)
            return self._ref_targets(func, entry, table)
        return set()

    # -- graph construction -------------------------------------------------

    def _build_graph(self) -> None:
        self.call_sites: Dict[FKey, List[Tuple[FKey, ast.Call]]] = {}
        for fkey, entry in self.funcs.items():
            table = self.class_methods.get(
                (entry.module.rel, entry.class_name), {}
            )
            aliases = self._local_aliases(fkey, entry)
            edges: Set[FKey] = set()
            for node in body_nodes(entry.node):
                if not isinstance(node, ast.Call):
                    continue
                targets = self._call_targets(node, entry, table, aliases)
                for target in targets:
                    self.call_sites.setdefault(target, []).append(
                        (fkey, node)
                    )
                edges |= targets
                self._note_entry_roots(node, entry, table, aliases)
            edges.discard(fkey)
            self.call_edges[fkey] = edges
            if entry.is_async:
                self.entry_sets[fkey].add("loop")

    def _note_entry_roots(
        self,
        call: ast.Call,
        entry: FuncEntry,
        table: Dict[str, FKey],
        aliases: Dict[str, Set[FKey]],
    ) -> None:
        func = call.func
        fname = (
            func.id
            if isinstance(func, ast.Name)
            else func.attr if isinstance(func, ast.Attribute) else None
        )
        ref = None
        category = None
        if fname == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    ref, category = kw.value, "thread"
        elif fname == "submit" and call.args:
            ref, category = call.args[0], "executor"
        elif fname == "run_in_executor" and len(call.args) >= 2:
            ref, category = call.args[1], "executor"
        if ref is None:
            return
        if isinstance(ref, ast.Name) and ref.id in aliases:
            targets = aliases[ref.id]
        else:
            targets = self._ref_targets(ref, entry, table)
        for fkey in targets:
            self.entry_sets[fkey].add(f"{category}:{fkey[1]}")

    def _closure(
        self, direct: Dict[FKey, Set[str]]
    ) -> Dict[FKey, Set[str]]:
        """Caller inherits every lock its callees (transitively)
        acquire — fixpoint over the call graph."""
        trans = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for fkey, callees in self.call_edges.items():
                mine = trans[fkey]
                before = len(mine)
                for callee in callees:
                    mine |= trans.get(callee, set())
                changed = changed or len(mine) != before
        return trans

    def _propagate_entries(self) -> None:
        changed = True
        while changed:
            changed = False
            for fkey, callees in self.call_edges.items():
                src = self.entry_sets[fkey]
                if not src:
                    continue
                for callee in callees:
                    dst = self.entry_sets.get(callee)
                    if dst is None or src <= dst:
                        continue
                    dst |= src
                    changed = True

    # -- queries ------------------------------------------------------------

    def entry_weight(self, fkey: FKey) -> int:
        """Distinct thread-entry weight reaching this function: each
        Thread target and the loop count 1; an executor root counts 2
        (every pool in the package has >= 2 workers, so one root is
        already concurrent with itself)."""
        return sum(
            2 if entry.startswith("executor:") else 1
            for entry in self.entry_sets.get(fkey, ())
        )


def _literal_prefix(node: ast.AST) -> Optional[str]:
    """``"_dispatch_" + kind`` / f-string / constant -> literal prefix."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _literal_prefix(node.left)
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(
            head.value, str
        ):
            return head.value
    return None


# blocking-call classification for LWC016 (extends LWC006/013 to
# held-lock context): device readiness waits and upstream HTTP
_BLOCKING_NAMES = {"wait_device_ready", "block_until_ready"}
_HTTP_DOTTED = {
    "requests.get",
    "requests.post",
    "requests.put",
    "requests.delete",
    "requests.request",
    "requests.head",
    "urllib.request.urlopen",
}


def blocking_call(node: ast.AST) -> Optional[str]:
    """Human-readable description if ``node`` is a blocking operation
    LWC016 forbids under a held threading lock; None otherwise."""
    if isinstance(node, ast.Await):
        return "an `await`"
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Name) and func.id in _BLOCKING_NAMES:
        return f"`{func.id}(...)`"
    if isinstance(func, ast.Attribute):
        if func.attr in _BLOCKING_NAMES:
            return f"`.{func.attr}(...)`"
        try:
            dotted = ast.unparse(func)
        except Exception:
            dotted = ""
        for known in _HTTP_DOTTED:
            if dotted == known or dotted.endswith("." + known):
                return f"upstream HTTP call `{dotted}(...)`"
    return None


def _first_blocking(func_node: ast.AST) -> Optional[str]:
    for node in body_nodes(func_node):
        desc = blocking_call(node)
        if desc is not None:
            return desc
    return None


# index cache: the three rules each call project() over the same parsed
# set within one run_lint; build the (call graph + closures) once.
# Keyed by object ids — valid because run_lint holds the modules alive
# across its project-rule loop.
_INDEX_CACHE: Dict[tuple, ProjectIndex] = {}


def project_index(
    modules: Sequence[ParsedModule],
) -> Optional[ProjectIndex]:
    model = load_model(modules)
    if model is None:
        return None
    cache_key = tuple(id(m) for m in modules)
    idx = _INDEX_CACHE.get(cache_key)
    if idx is None:
        idx = ProjectIndex(modules, model)
        _INDEX_CACHE.clear()
        _INDEX_CACHE[cache_key] = idx
    return idx
