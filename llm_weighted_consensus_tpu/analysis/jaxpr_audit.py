"""CPU-only jaxpr audit of the compiled serving path.

Lowers the embedder/consensus serving functions exactly as the gateway
dispatches them (per AOT bucket, int8 path pinned) and statically
asserts the compiled hot path's invariants — no TPU required, because
every check is on the *traced* structure (``jax.make_jaxpr``) or the
jit-dispatch bookkeeping (PR 3's ``jit_stats``), not on device output:

* **JXA001 host-transfer** — no host↔device primitives inside the
  jitted hot path: callbacks (``pure_callback``/``io_callback``/
  ``debug_callback``), ``device_put``, infeed/outfeed.  A
  ``jax.device_get``/``np.asarray`` on a traced value never reaches
  the jaxpr — it explodes at trace time — so the auditor catches the
  concretization error and reports it as the same class of finding.
* **JXA002 dequant-regression** — the W8A8 int8 path keeps its shape:
  at least one Pallas kernel in the forward, and ZERO
  ``convert_element_type`` int8→float (the storage-format anti-pattern
  — dequantizing ``kernel_q`` back to bf16 before a bf16 matmul — that
  the fused path replaced; same predicate as the dispatch evidence
  committed in the PR 3 bench records).
* **JXA003 f64-promotion** — no float64 avals anywhere in the traced
  serving math (an x64 leak doubles every buffer and halves MXU rate).
* **JXA004 missing-aot-bucket / JXA005 stray-specialization** — after
  ``aot_warmup`` over the serving specs, every expected bucket key is
  present in the executable table, and driving one of everything the
  gateway dispatches creates ZERO new jit specializations
  (``jit_stats`` delta per entry point).

The packed serving path (PR 7 continuous batching) is audited with the
same rules: ``bert.embed_packed`` is traced per packed-capacity bucket
``("packed", B, L, K)`` for JXA001/2/3, and the AOT guard warms the
packed buckets (``aot_warmup(..., packed_buckets=...)``), asserts their
keys landed (JXA004), and drives packed traffic through them asserting
zero new ``embed_packed`` jit specializations (JXA005).

The quantized-path rules extend to the packed-int4 W4A8 mode: the
serving entry points are re-traced with ``int4-pallas`` pinned
(JXA002's dequant predicate widens to uint8 nibble storage -> float,
the lost-int4-kernel regression), and a second AOT guard warms and
drives an ``int4-pallas`` embedder through the shared bucket-key
namespace (JXA004/005).  The sequence-parallel ring entry points
(``parallel.ring._ring_embed_jit`` / ``_ring_embed_and_vote``) are
traced under a live ``sp`` mesh for JXA001/2/3 whenever the backend has
>= 2 devices (tier-1 always does).

Env knobs (all optional): ``ANALYSIS_JAXPR_MODEL`` (preset, default
``test-tiny``), ``ANALYSIS_JAXPR_SPECS`` (comma list of ``NxS``,
default ``4x16``), ``ANALYSIS_JAXPR_R_BUCKETS`` (comma list, default
``2``), ``ANALYSIS_JAXPR_PACKED_BUCKETS`` (comma list of ``BxLxK``,
default ``1x64x8,2x64x8``; empty value audits no packed buckets),
``ANALYSIS_SKIP_JAXPR=1`` to skip the audit entirely (the CLI honors
it; tier-1 does not set it).

jax is imported lazily inside the entry points so importing
``analysis`` stays stdlib-cheap.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

from .engine import Finding

# primitive names that mean the "hot loop never touches the host"
# contract is broken
_HOST_PRIMS = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "callback",
    "device_put",
    "infeed",
    "outfeed",
}

_DEFAULT_MODEL = "test-tiny"
_DEFAULT_SPECS = ((4, 16),)
_DEFAULT_R_BUCKETS = (2,)
# small CPU-sized packed-capacity buckets ("packed", B, L, K): enough to
# trace the segment-masked forward and exercise the AOT lookup without
# compiling serving-width shapes in tier-1
_DEFAULT_PACKED_BUCKETS = ((1, 64, 8), (2, 64, 8))


def _env_specs() -> Tuple[Tuple[int, int], ...]:
    raw = os.environ.get("ANALYSIS_JAXPR_SPECS", "")
    if not raw.strip():
        return _DEFAULT_SPECS
    specs = []
    for part in raw.split(","):
        n, s = part.strip().lower().split("x")
        specs.append((int(n), int(s)))
    return tuple(specs)


def _env_r_buckets() -> Tuple[int, ...]:
    raw = os.environ.get("ANALYSIS_JAXPR_R_BUCKETS", "")
    if not raw.strip():
        return _DEFAULT_R_BUCKETS
    return tuple(int(p) for p in raw.split(",") if p.strip())


def _env_packed_buckets() -> Tuple[Tuple[int, int, int], ...]:
    raw = os.environ.get("ANALYSIS_JAXPR_PACKED_BUCKETS")
    if raw is None:
        return _DEFAULT_PACKED_BUCKETS
    buckets = []
    for part in raw.split(","):
        if not part.strip():
            continue
        b, l, k = part.strip().lower().split("x")
        buckets.append((int(b), int(l), int(k)))
    return tuple(buckets)


# ---------------------------------------------------------------------------
# jaxpr walking + the three structural checks
# ---------------------------------------------------------------------------


def walk_jaxpr(jaxpr, visit) -> None:
    """Depth-first over every equation, descending into sub-jaxprs
    (pjit bodies, scan/cond branches, Pallas kernel bodies — the same
    recursion as the PR 3 dispatch-evidence walker, so the dequant
    predicate here matches the committed bench records)."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for sub in eqn.params.values():
            if hasattr(sub, "eqns"):
                walk_jaxpr(sub, visit)
            elif hasattr(sub, "jaxpr"):
                walk_jaxpr(sub.jaxpr, visit)


def audit_closed_jaxpr(
    closed, label: str, *, expect_pallas: bool = False, int4: bool = False
) -> List[Finding]:
    """The structural checks over one traced function (a
    ``jax.make_jaxpr`` result).  ``expect_pallas`` additionally asserts
    the fused quantized kernel is still present (JXA002's other half);
    ``int4`` widens the dequant predicate to the packed W4A8 layout
    (uint8 nibble storage -> float is the lost-int4-kernel regression,
    exactly as int8 -> float is the lost-int8-kernel one)."""
    import jax.numpy as jnp

    findings: List[Finding] = []
    pallas_calls = 0

    def visit(eqn):
        nonlocal pallas_calls
        name = eqn.primitive.name
        if name == "pallas_call":
            pallas_calls += 1
        if name in _HOST_PRIMS or name.endswith("_callback"):
            findings.append(
                Finding(
                    rule="JXA001",
                    path=f"jaxpr:{label}",
                    line=0,
                    message=(
                        f"host-transfer primitive `{name}` inside the "
                        "jitted serving path; the hot loop must not "
                        "touch the host"
                    ),
                )
            )
        if name == "convert_element_type":
            src = eqn.invars[0].aval
            dst = eqn.outvars[0].aval
            if src.dtype == jnp.int8 and jnp.issubdtype(
                dst.dtype, jnp.floating
            ):
                findings.append(
                    Finding(
                        rule="JXA002",
                        path=f"jaxpr:{label}",
                        line=0,
                        message=(
                            "`convert_element_type` int8->"
                            f"{dst.dtype.name}: a dequantize-then-"
                            "float-matmul regression in the W8A8 path"
                        ),
                    )
                )
            if int4 and src.dtype == jnp.uint8 and jnp.issubdtype(
                dst.dtype, jnp.floating
            ):
                findings.append(
                    Finding(
                        rule="JXA002",
                        path=f"jaxpr:{label}",
                        line=0,
                        message=(
                            "`convert_element_type` uint8->"
                            f"{dst.dtype.name}: the packed int4 nibbles "
                            "were dequantized to float outside the "
                            "fused W4A8 kernel"
                        ),
                    )
                )
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and str(dtype) == "float64":
                findings.append(
                    Finding(
                        rule="JXA003",
                        path=f"jaxpr:{label}",
                        line=0,
                        message=(
                            f"float64 aval out of `{eqn.primitive.name}`"
                            ": x64 promotion leaked into the serving "
                            "math"
                        ),
                    )
                )

    walk_jaxpr(closed.jaxpr, visit)
    if expect_pallas and pallas_calls == 0:
        kernel = "W4A8" if int4 else "W8A8"
        findings.append(
            Finding(
                rule="JXA002",
                path=f"jaxpr:{label}",
                line=0,
                message=(
                    f"{'int4' if int4 else 'int8'} path traced with ZERO "
                    f"pallas_call equations; the fused {kernel} kernel "
                    "fell out of the forward"
                ),
            )
        )
    return findings


def audit_traced(
    fn,
    example_args: Sequence,
    label: str,
    *,
    expect_pallas: bool = False,
    int4: bool = False,
) -> List[Finding]:
    """Trace ``fn(*example_args)`` and run the structural checks.

    Trace-time concretization failures (``jax.device_get`` /
    ``np.asarray`` on a tracer) are reported as JXA001 rather than
    raised: they are the most literal form of "host transfer inside the
    jitted path"."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*example_args)
    except (
        jax.errors.TracerArrayConversionError,
        jax.errors.ConcretizationTypeError,
        jax.errors.UnexpectedTracerError,
    ) as exc:
        return [
            Finding(
                rule="JXA001",
                path=f"jaxpr:{label}",
                line=0,
                message=(
                    "host transfer at trace time (device_get/"
                    "np.asarray on a traced value): "
                    f"{type(exc).__name__}"
                ),
            )
        ]
    return audit_closed_jaxpr(
        closed, label, expect_pallas=expect_pallas, int4=int4
    )


# ---------------------------------------------------------------------------
# the serving-path audit proper
# ---------------------------------------------------------------------------


def _structure_findings(
    model: str, specs, r_buckets, packed_buckets
) -> List[Finding]:
    """Trace every serving entry point with the Pallas int8 impl pinned
    (``int8-pallas`` traces fine off-TPU; compilation isn't needed for
    structure) and run the JXA001/2/3 checks per AOT bucket — including
    the packed entry point per packed-capacity bucket."""
    import jax
    import jax.numpy as jnp

    from ..models.embedder import (
        TpuEmbedder,
        _bucket,
        _embed_and_vote,
        _embed_and_vote_many,
        _seq_bucket,
        _stream_vote_update,
    )
    from ..models import bert

    embedder = TpuEmbedder(model, max_tokens=64, seed=0, quantize="int8-pallas")
    sds = jax.ShapeDtypeStruct
    temp = sds((), jnp.float32)
    findings: List[Finding] = []
    hidden = embedder.config.hidden_size
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        ids = sds((n, s), jnp.int32)
        for use_fused in (True, False):
            findings.extend(
                audit_traced(
                    lambda p, i, m, t, _n=n, _f=use_fused: _embed_and_vote(
                        p, i, m, t, _n, embedder.config, embedder.pooling, _f
                    ),
                    (embedder.params, ids, ids, temp),
                    f"vote1(n={n},s={s},fused={use_fused})",
                    expect_pallas=True,
                )
            )
        pad_b = _bucket(n, embedder.MAX_DEVICE_BATCH)
        bids = sds((pad_b, s), jnp.int32)
        findings.extend(
            audit_traced(
                lambda p, i, m: bert.embed(
                    p, i, m, embedder.config,
                    pooling=embedder.pooling, normalize=True,
                ),
                (embedder.params, bids, bids),
                f"embed(b={pad_b},s={s})",
                expect_pallas=True,
            )
        )
        for r in r_buckets:
            if r < 2:
                continue
            flat = sds((r * n, s), jnp.int32)
            findings.extend(
                audit_traced(
                    lambda p, i, m, t, _r=r, _n=n: _embed_and_vote_many(
                        p, i, m, t, _r, _n, embedder.config, embedder.pooling
                    ),
                    (embedder.params, flat, flat, temp),
                    f"many(r={r},n={n},s={s})",
                    expect_pallas=True,
                )
            )
        # one streaming-consensus step at this bucket's capacity
        cap = _bucket(n, embedder.MAX_DEVICE_BATCH)
        findings.extend(
            audit_traced(
                lambda p, i, m, b, v, pos, t: _stream_vote_update(
                    p, i, m, b, v, pos, embedder.config,
                    embedder.pooling, t,
                ),
                (
                    embedder.params,
                    sds((1, s), jnp.int32),
                    sds((1, s), jnp.int32),
                    sds((cap, hidden), jnp.float32),
                    sds((cap,), jnp.float32),
                    sds((), jnp.int32),
                    temp,
                ),
                f"stream(cap={cap},s={s})",
                expect_pallas=True,
            )
        )
    # packed entry point (continuous batching): the segment-masked
    # forward must satisfy the same invariants at every capacity bucket
    for b, l, k in packed_buckets:
        pids = sds((b, l), jnp.int32)
        pstarts = sds((b, k), jnp.int32)
        findings.extend(
            audit_traced(
                lambda p, i, g, pos, st: bert.embed_packed(
                    p, i, g, pos, st, embedder.config,
                    pooling=embedder.pooling, normalize=True,
                ),
                (embedder.params, pids, pids, pids, pstarts),
                f"packed(b={b},l={l},k={k})",
                expect_pallas=True,
            )
        )
    findings += _int4_structure_findings(model, specs)
    findings += _ring_structure_findings(model, specs)
    return findings


def _int4_structure_findings(model: str, specs) -> List[Finding]:
    """The W4A8 twin of the int8 structure audit: trace the serving
    entry points with ``int4-pallas`` pinned and assert the fused packed
    kernel is present (and that no uint8->float dequant crept in — the
    lost-int4-kernel regression, JXA002)."""
    import jax
    import jax.numpy as jnp

    from ..models.embedder import (
        TpuEmbedder,
        _bucket,
        _embed_and_vote,
        _seq_bucket,
    )
    from ..models import bert

    embedder = TpuEmbedder(
        model, max_tokens=64, seed=0, quantize="int4-pallas"
    )
    sds = jax.ShapeDtypeStruct
    temp = sds((), jnp.float32)
    findings: List[Finding] = []
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        ids = sds((n, s), jnp.int32)
        findings.extend(
            audit_traced(
                lambda p, i, m, t, _n=n: _embed_and_vote(
                    p, i, m, t, _n, embedder.config, embedder.pooling, True
                ),
                (embedder.params, ids, ids, temp),
                f"int4:vote1(n={n},s={s})",
                expect_pallas=True,
                int4=True,
            )
        )
        pad_b = _bucket(n, embedder.MAX_DEVICE_BATCH)
        bids = sds((pad_b, s), jnp.int32)
        findings.extend(
            audit_traced(
                lambda p, i, m: bert.embed(
                    p, i, m, embedder.config,
                    pooling=embedder.pooling, normalize=True,
                ),
                (embedder.params, bids, bids),
                f"int4:embed(b={pad_b},s={s})",
                expect_pallas=True,
                int4=True,
            )
        )
    return findings


def _ring_structure_findings(model: str, specs) -> List[Finding]:
    """JXA001/2/3 over the sequence-parallel (ring attention) serving
    entry points — the exact jitted functions the sp-mesh batcher
    dispatches (``parallel.ring._ring_embed_jit`` /
    ``_ring_embed_and_vote``).  The ring shard_map needs a live mesh
    with an ``sp`` axis, so this leg runs only when the backend has at
    least two devices (tier-1's 8 virtual CPUs always qualify; a bare
    single-device CLI run skips it — the mesh audit still covers the
    compiled ring executables there)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    if jax.device_count() < 2:
        return []

    from ..models.embedder import TpuEmbedder, _seq_bucket
    from ..parallel.mesh import make_mesh
    from ..parallel.ring import _ring_embed_and_vote, _ring_embed_jit

    sp = 2
    mesh = make_mesh(dp=1, tp=1, sp=sp)
    embedder = TpuEmbedder(model, max_tokens=64, seed=0, quantize="int8-pallas")
    ring_config = dataclasses.replace(
        embedder.config, attention_impl="ring", ring_axis="sp"
    )
    sds = jax.ShapeDtypeStruct
    temp = sds((), jnp.float32)
    findings: List[Finding] = []
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        s = min(s + (-s) % sp, embedder.max_tokens)
        ids = sds((n, s), jnp.int32)
        findings.extend(
            audit_traced(
                lambda p, i, m: _ring_embed_jit(
                    p, i, m, ring_config, mesh, "sp", "dp",
                    embedder.pooling, True,
                ),
                (embedder.params, ids, ids),
                f"ring(b={n},s={s})",
                expect_pallas=True,
            )
        )
        findings.extend(
            audit_traced(
                lambda p, i, m, t, _n=n: _ring_embed_and_vote(
                    p, i, m, t, _n, ring_config, mesh, "sp", "dp",
                    embedder.pooling,
                ),
                (embedder.params, ids, ids, temp),
                f"ring_vote(n={n},s={s})",
                expect_pallas=True,
            )
        )
    return findings


def _aot_findings(model: str, specs, r_buckets, packed_buckets) -> List[Finding]:
    """The specialization guard: warm every serving bucket with the
    auto int8 impl (the one CPU can execute), assert every expected
    key landed in the executable table, drive one of everything the
    gateway dispatches — padded AND packed — and assert the jit caches
    did not grow."""
    import numpy as np

    from ..models.embedder import TpuEmbedder, _bucket, _seq_bucket

    embedder = TpuEmbedder(model, max_tokens=64, seed=0, quantize="int8")
    findings: List[Finding] = []
    warm_specs = [(n, s) for n, s in specs]
    embedder.aot_warmup(
        warm_specs,
        r_buckets=list(r_buckets),
        packed_buckets=list(packed_buckets),
    )

    rng = np.random.default_rng(7)
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        expected = [("vote1", n, s, True), ("vote1", n, s, False)]
        expected.append(("embed", _bucket(n, embedder.MAX_DEVICE_BATCH), s))
        for r in r_buckets:
            if r >= 2:
                expected.append(("many", r, n, s))
        for key in expected:
            if key not in embedder._aot:
                findings.append(
                    Finding(
                        rule="JXA004",
                        path=f"jaxpr:aot({model})",
                        line=0,
                        message=(
                            f"serving bucket {key} missing from the AOT "
                            "executable table after warmup — this shape "
                            "will lazily specialize under live traffic"
                        ),
                    )
                )
    for b, l, k in packed_buckets:
        key = ("packed", b, l, k)
        if key not in embedder._aot:
            findings.append(
                Finding(
                    rule="JXA004",
                    path=f"jaxpr:aot({model})",
                    line=0,
                    message=(
                        f"packed-capacity bucket {key} missing from the "
                        "AOT executable table after warmup — packed "
                        "dispatches at this shape will lazily specialize"
                    ),
                )
            )
    stats0 = embedder.jit_stats()["specializations"]
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        vocab = embedder.config.vocab_size
        ids = rng.integers(3, vocab, (n, s)).astype(np.int32)
        mask = np.ones((n, s), np.int32)
        embedder.consensus_confidence_tokens(ids, mask)
        embedder.consensus_confidence_tokens(ids, mask, temperature=0.2)
        embedder.embed_tokens(ids, mask)
        for r in r_buckets:
            if r < 2:
                continue
            embedder.consensus_confidence_tokens_many(
                np.stack([ids] * r), np.stack([mask] * r)
            )
    for b, l, k in packed_buckets:
        # two segments per row, ragged fills — exactly what the
        # continuous batcher dispatches at this capacity bucket
        pids = np.zeros((b, l), np.int32)
        pseg = np.zeros((b, l), np.int32)
        ppos = np.zeros((b, l), np.int32)
        pstarts = np.zeros((b, k), np.int32)
        vocab = embedder.config.vocab_size
        for r in range(b):
            n0, n1 = 5 + r, 3
            pids[r, : n0 + n1] = rng.integers(3, vocab, n0 + n1)
            pseg[r, :n0] = 1
            pseg[r, n0 : n0 + n1] = 2
            ppos[r, :n0] = np.arange(n0)
            ppos[r, n0 : n0 + n1] = np.arange(n1)
            pstarts[r, 1] = n0
        embedder.embed_packed(pids, pseg, ppos, pstarts)
    stats1 = embedder.jit_stats()["specializations"]
    for entry, count in stats1.items():
        grew = count - stats0.get(entry, 0)
        if grew > 0:
            findings.append(
                Finding(
                    rule="JXA005",
                    path=f"jaxpr:aot({model})",
                    line=0,
                    message=(
                        f"`{entry}` grew {grew} jit specialization(s) "
                        "under post-warmup traffic at warmed buckets — "
                        "the AOT table is not being consulted"
                    ),
                )
            )
    findings += _int4_aot_findings(model, specs)
    return findings


def _int4_aot_findings(model: str, specs) -> List[Finding]:
    """JXA004/JXA005 for the ``int4-pallas`` serving mode: the packed
    W4A8 path shares the AOT key namespace with every other quantize
    mode, so warmup must land the same bucket keys and post-warmup
    traffic must ride them with zero jit growth.  The fused kernel runs
    in interpret mode on CPU, so this drives real dispatches in tier-1."""
    import numpy as np

    from ..models.embedder import TpuEmbedder, _bucket, _seq_bucket

    embedder = TpuEmbedder(
        model, max_tokens=64, seed=0, quantize="int4-pallas"
    )
    findings: List[Finding] = []
    embedder.aot_warmup([(n, s) for n, s in specs])
    rng = np.random.default_rng(11)
    vocab = embedder.config.vocab_size
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        expected = [
            ("vote1", n, s, True),
            ("vote1", n, s, False),
            ("embed", _bucket(n, embedder.MAX_DEVICE_BATCH), s),
        ]
        for key in expected:
            if key not in embedder._aot:
                findings.append(
                    Finding(
                        rule="JXA004",
                        path=f"jaxpr:aot({model},int4)",
                        line=0,
                        message=(
                            f"int4-pallas serving bucket {key} missing "
                            "from the AOT executable table after warmup "
                            "— this shape will lazily specialize under "
                            "live traffic"
                        ),
                    )
                )
    stats0 = embedder.jit_stats()["specializations"]
    for n, s in specs:
        s = _seq_bucket(s, embedder.max_tokens)
        ids = rng.integers(3, vocab, (n, s)).astype(np.int32)
        mask = np.ones((n, s), np.int32)
        embedder.consensus_confidence_tokens(ids, mask)
        embedder.embed_tokens(ids, mask)
    stats1 = embedder.jit_stats()["specializations"]
    for entry, count in stats1.items():
        grew = count - stats0.get(entry, 0)
        if grew > 0:
            findings.append(
                Finding(
                    rule="JXA005",
                    path=f"jaxpr:aot({model},int4)",
                    line=0,
                    message=(
                        f"`{entry}` grew {grew} jit specialization(s) "
                        "under post-warmup int4-pallas traffic — the "
                        "AOT table is not being consulted"
                    ),
                )
            )
    return findings


def run_jaxpr_audit(
    model: Optional[str] = None,
    specs: Optional[Sequence[Tuple[int, int]]] = None,
    r_buckets: Optional[Sequence[int]] = None,
    packed_buckets: Optional[Sequence[Tuple[int, int, int]]] = None,
) -> List[Finding]:
    """The full audit: structure (traced int8-pallas path, padded and
    packed entry points) + AOT coverage/specialization guard.  CPU-safe;
    ~seconds on test-tiny."""
    model = model or os.environ.get("ANALYSIS_JAXPR_MODEL", _DEFAULT_MODEL)
    specs = tuple(specs) if specs is not None else _env_specs()
    r_buckets = (
        tuple(r_buckets) if r_buckets is not None else _env_r_buckets()
    )
    packed_buckets = (
        tuple(packed_buckets)
        if packed_buckets is not None
        else _env_packed_buckets()
    )
    findings = _structure_findings(model, specs, r_buckets, packed_buckets)
    findings += _aot_findings(model, specs, r_buckets, packed_buckets)
    return findings
