"""CLI: ``python -m llm_weighted_consensus_tpu.analysis``.

Runs the AST lint over the package, then the jaxpr audit and the
simulated-mesh sharding/resource audit (unless skipped), applies
``baseline.json``, and reports.

Exit codes: **0** clean (every finding baselined or none), **1**
non-baselined findings, **2** baseline problems (a stale suppression —
the code it covered was fixed, so the entry must be deleted — or an
entry missing its mandatory ``reason``).

Flags/env: ``--no-jaxpr`` or ``ANALYSIS_SKIP_JAXPR=1`` skips the jaxpr
audit (lint stays); ``--no-mesh`` or ``ANALYSIS_SKIP_MESH=1`` skips the
mesh audit; ``--no-concurrency`` or ``ANALYSIS_SKIP_CONCURRENCY=1``
skips the whole-program concurrency audit (LWC014–016 — the lock-model
registry, guarded-field, lock-order, and blocking-under-lock rules);
``--baseline PATH`` / ``ANALYSIS_BASELINE`` overrides the baseline
file; ``--rules LWC001,...`` restricts lint rules; ``--json`` emits
machine-readable findings; positional paths lint specific files
instead of the whole package.  The jaxpr audit's own knobs
(``ANALYSIS_JAXPR_MODEL`` / ``_SPECS`` / ``_R_BUCKETS``) are documented
in ``jaxpr_audit.py``; the mesh audit's (``ANALYSIS_MESH_MODEL`` /
``_DP`` / ``_TP`` / ``_SPECS`` / ``_R_BUCKETS`` / ``_PACKED_BUCKETS``,
``ANALYSIS_BUDGETS``) in ``mesh_audit.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

from .engine import (
    apply_baseline,
    default_baseline_path,
    load_baseline,
    run_lint,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m llm_weighted_consensus_tpu.analysis",
        description="first-party invariant checker (AST lint + jaxpr audit)",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="lint only these files (default: the whole package)",
    )
    parser.add_argument(
        "--no-jaxpr", action="store_true",
        help="skip the jaxpr serving-path audit (ANALYSIS_SKIP_JAXPR=1)",
    )
    parser.add_argument(
        "--no-mesh", action="store_true",
        help="skip the simulated-mesh sharding/resource audit "
        "(ANALYSIS_SKIP_MESH=1)",
    )
    parser.add_argument(
        "--no-concurrency", action="store_true",
        help="skip the concurrency-discipline audit, rules LWC014-016 "
        "(ANALYSIS_SKIP_CONCURRENCY=1)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help="suppression baseline (default analysis/baseline.json; "
        "ANALYSIS_BASELINE overrides)",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated lint rule subset, e.g. LWC001,LWC003",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    args = parser.parse_args(argv)

    from .rules import ALL_RULES, RULES_BY_NAME

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name}  {rule.summary}")
        return 0

    rules = list(ALL_RULES)
    if args.rules:
        try:
            rules = [RULES_BY_NAME[n.strip()] for n in args.rules.split(",")]
        except KeyError as exc:
            print(f"unknown rule {exc}", file=sys.stderr)
            return 2

    # the concurrency trio runs as its own timed pass (bench_host.py
    # budgets it alongside the jaxpr/mesh audits), skippable without
    # touching the per-function lint
    conc_names = {"LWC014", "LWC015", "LWC016"}
    skip_conc = args.no_concurrency or bool(
        os.environ.get("ANALYSIS_SKIP_CONCURRENCY")
    )
    conc_rules = [r for r in rules if r.name in conc_names]
    base_rules = [r for r in rules if r.name not in conc_names]

    t0 = time.perf_counter()
    findings = run_lint(paths=args.paths or None, rules=base_rules)
    lint_s = time.perf_counter() - t0

    concurrency_s = 0.0
    if conc_rules and not skip_conc:
        t0 = time.perf_counter()
        findings += run_lint(paths=args.paths or None, rules=conc_rules)
        concurrency_s = time.perf_counter() - t0
        findings.sort(key=lambda f: (f.path, f.line, f.rule))

    jaxpr_s = 0.0
    skip_jaxpr = args.no_jaxpr or bool(os.environ.get("ANALYSIS_SKIP_JAXPR"))
    if not skip_jaxpr:
        from .jaxpr_audit import run_jaxpr_audit

        t0 = time.perf_counter()
        findings += run_jaxpr_audit()
        jaxpr_s = time.perf_counter() - t0

    mesh_s = 0.0
    skip_mesh = args.no_mesh or bool(os.environ.get("ANALYSIS_SKIP_MESH"))
    if not skip_mesh:
        from .mesh_audit import run_mesh_audit

        t0 = time.perf_counter()
        findings += run_mesh_audit()
        mesh_s = time.perf_counter() - t0

    baseline_path = args.baseline or (
        Path(os.environ["ANALYSIS_BASELINE"])
        if os.environ.get("ANALYSIS_BASELINE")
        else default_baseline_path()
    )
    try:
        baseline = load_baseline(baseline_path)
    except ValueError as exc:
        print(f"baseline error: {exc}", file=sys.stderr)
        return 2
    kept, suppressed, stale = apply_baseline(findings, baseline)

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [vars(f) for f in kept],
                    "suppressed": [vars(f) for f in suppressed],
                    "stale_baseline": stale,
                    "lint_seconds": round(lint_s, 3),
                    "concurrency_seconds": round(concurrency_s, 3),
                    "jaxpr_seconds": round(jaxpr_s, 3),
                    "mesh_seconds": round(mesh_s, 3),
                }
            )
        )
    else:
        for finding in kept:
            print(finding.render())
        summary = (
            f"analysis: {len(kept)} finding(s), {len(suppressed)} "
            f"baselined, lint {lint_s:.2f}s"
        )
        if conc_rules and not skip_conc:
            summary += f", concurrency audit {concurrency_s:.2f}s"
        if not skip_jaxpr:
            summary += f", jaxpr audit {jaxpr_s:.2f}s"
        if not skip_mesh:
            summary += f", mesh audit {mesh_s:.2f}s"
        print(summary, file=sys.stderr)

    if stale:
        for entry in stale:
            print(
                "stale baseline entry (the finding it suppressed is "
                f"gone — delete it): {json.dumps(entry)}",
                file=sys.stderr,
            )
        return 2
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
