"""Per-bucket speed-of-light roofline for the serving path (JXA013).

ROADMAP item 1 asks that "fast as the hardware allows" become a checked
invariant.  ``analysis/roofline.json`` commits, next to the JXA009/010
resource budgets, everything needed to compute each AOT bucket's
speed-of-light (SoL) time on a given backend:

* ``peaks`` — PER-CHIP peak compute (``flops_per_sec``) and HBM
  bandwidth (``hbm_bytes_per_sec``) per jax backend name.  The ``tpu``
  row is TPU v5e (bf16 peak ~197 TFLOP/s, ~819 GB/s HBM per chip); the
  ``cpu`` row is a deliberately rough laptop-class figure so the gauge
  stays meaningful (and testable) on the CPU-simulated stack.
* ``buckets`` — per-bucket ``flops`` / ``bytes_accessed`` from XLA's
  ``cost_analysis``, the same figures the mesh audit measures for the
  budgets file.  Rows are committed mesh-shape-free: the runtime gauge
  scales peaks by the chip count parsed from the serving label's
  ``@dp{dp}xtp{tp}[xsp{sp}]`` suffix, so ONE committed row covers every
  mesh-ladder rung (dp-halving keeps per-bucket totals, splits chips)
  and every sequence-parallel ring bucket.

``sol_ms = max(flops / (peak_flops * chips),
               bytes_accessed / (peak_bw * chips)) * 1e3``

The live gauge (``RooflineGauge``, the ``roofline`` /metrics section)
divides SoL by the measured block-until-ready device p50 per
(mesh-shape, bucket) from the phase aggregator:
``attainment = sol_ms / device_p50_ms`` — 1.0 means the dispatch runs
at the hardware roofline; 0.1 means 10× headroom.

**JXA013** gates the file exactly like budgets.py gates JXA009/010:
missing file, scope mismatch, audited bucket without a row, stale row
without a bucket, or committed figures drifted beyond the tolerance
band vs fresh measurement — all fail the analyzer.  Re-baseline:
``python -m llm_weighted_consensus_tpu.analysis.mesh_audit
--write-roofline`` (peaks and tolerance survive; figures do not).

Stdlib-only (json/pathlib); the jax-touching measurement lives in
``mesh_audit.py`` and the device timings in ``obs/phases.py``.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .engine import Finding

# figures a roofline row must carry; both come from XLA cost_analysis
ROOFLINE_METRICS = ("flops", "bytes_accessed")

DEFAULT_TOLERANCE = 0.25  # same band rationale as budgets.py

# Committed starting peaks, used when --write-roofline creates the file
# from scratch.  Per chip.  tpu = v5e: 394 TFLOP/s int8 / ~197 bf16; we
# commit the bf16 figure because the serving matmuls are bf16/f32 with
# only the int8-pallas path below it.  cpu = rough one-core-ish figure
# so CPU-simulated runs report a stable, obviously-not-TPU attainment.
DEFAULT_PEAKS = {
    "tpu": {"flops_per_sec": 1.97e14, "hbm_bytes_per_sec": 8.19e11},
    "cpu": {"flops_per_sec": 5.0e10, "hbm_bytes_per_sec": 2.0e10},
}

_MESH_SUFFIX = re.compile(
    r"^(?P<base>.+)@dp(?P<dp>\d+)xtp(?P<tp>\d+)(?:xsp(?P<sp>\d+))?$"
)


def default_roofline_path() -> Path:
    return Path(__file__).resolve().parent / "roofline.json"


def load_roofline(path: Optional[Path] = None) -> dict:
    path = path or default_roofline_path()
    if not path.exists():
        return {}
    return json.loads(path.read_text(encoding="utf-8"))


def split_label(label: str) -> Tuple[str, int]:
    """Runtime device-timing label -> (committed row label, chip count).

    ``vote1(n=8,s=16)@dp4xtp2`` -> (``vote1(n=8,s=16)``, 8); the
    sequence-parallel suffix multiplies too (``ring(b=16,s=64)
    @dp2xtp2xsp2`` -> 8 chips); an unsuffixed single-device label
    counts as one chip."""
    m = _MESH_SUFFIX.match(label)
    if m is None:
        return label, 1
    chips = int(m.group("dp")) * int(m.group("tp"))
    if m.group("sp"):
        chips *= int(m.group("sp"))
    return m.group("base"), chips


def sol_ms(figures: dict, peaks: dict, chips: int = 1) -> Optional[float]:
    """Speed-of-light time in ms for one bucket on ``chips`` chips of a
    backend described by ``peaks``; None when either side is unusable."""
    try:
        flops = float(figures["flops"])
        bytes_accessed = float(figures["bytes_accessed"])
        peak_flops = float(peaks["flops_per_sec"]) * max(1, chips)
        peak_bw = float(peaks["hbm_bytes_per_sec"]) * max(1, chips)
    except (KeyError, TypeError, ValueError):
        return None
    if peak_flops <= 0 or peak_bw <= 0:
        return None
    return max(flops / peak_flops, bytes_accessed / peak_bw) * 1e3


def tolerance_of(roofline: dict, metric: str) -> float:
    return float(roofline.get("tolerance", {}).get(metric, DEFAULT_TOLERANCE))


def compare_roofline(
    measured: Dict[str, Dict[str, float]],
    roofline: dict,
    scope: Optional[dict] = None,
) -> List[Finding]:
    """JXA013: every audited AOT bucket must have a live, in-band
    roofline row, both ways — the gauge is only as honest as this file."""
    findings: List[Finding] = []
    if not roofline:
        findings.append(
            Finding(
                rule="JXA013",
                path="analysis/roofline.json",
                line=0,
                message=(
                    "no committed roofline: run `python -m "
                    "llm_weighted_consensus_tpu.analysis.mesh_audit "
                    "--write-roofline` and commit the result so every AOT "
                    "bucket reports a speed-of-light attainment gauge"
                ),
            )
        )
        return findings
    if scope is not None and roofline.get("scope", {}) != scope:
        findings.append(
            Finding(
                rule="JXA013",
                path="analysis/roofline.json",
                line=0,
                message=(
                    f"committed roofline scope {roofline.get('scope', {})} "
                    f"does not match the audited configuration {scope}; "
                    "re-baseline with --write-roofline"
                ),
            )
        )
        return findings
    peaks = roofline.get("peaks", {})
    for backend in ("tpu", "cpu"):
        row = peaks.get(backend, {})
        if not all(float(row.get(k, 0)) > 0 for k in (
            "flops_per_sec", "hbm_bytes_per_sec"
        )):
            findings.append(
                Finding(
                    rule="JXA013",
                    path="analysis/roofline.json",
                    line=0,
                    symbol=backend,
                    message=(
                        f"peaks entry for backend `{backend}` is missing or "
                        "non-positive; the attainment gauge needs per-chip "
                        "flops_per_sec and hbm_bytes_per_sec"
                    ),
                )
            )
    committed = roofline.get("buckets", {})
    for label, figures in sorted(measured.items()):
        entry = committed.get(label)
        if entry is None:
            findings.append(
                Finding(
                    rule="JXA013",
                    path="analysis/roofline.json",
                    line=0,
                    symbol=label,
                    message=(
                        f"audited bucket `{label}` has no roofline row; it "
                        "would serve without an attainment gauge — "
                        "re-baseline with --write-roofline"
                    ),
                )
            )
            continue
        for metric in ROOFLINE_METRICS:
            if metric not in figures or metric not in entry:
                continue
            got, want = float(figures[metric]), float(entry[metric])
            if want <= 0:
                continue
            band = tolerance_of(roofline, metric)
            ratio = got / want
            if ratio > 1.0 + band or ratio < 1.0 - band:
                findings.append(
                    Finding(
                        rule="JXA013",
                        path="analysis/roofline.json",
                        line=0,
                        symbol=label,
                        message=(
                            f"roofline row `{label}` {metric} is stale: "
                            f"measured {got:.0f} vs committed {want:.0f} "
                            f"({ratio:.2f}x, band ±{band:.0%}) — the gauge "
                            "would report attainment against the wrong "
                            "speed of light; re-baseline with "
                            "--write-roofline"
                        ),
                    )
                )
    for label in sorted(committed):
        if label not in measured:
            findings.append(
                Finding(
                    rule="JXA013",
                    path="analysis/roofline.json",
                    line=0,
                    symbol=label,
                    message=(
                        f"stale roofline row `{label}`: the audit no longer "
                        "lowers this bucket — delete the row"
                    ),
                )
            )
    return findings


def write_roofline(
    path: Path,
    measured: Dict[str, Dict[str, float]],
    scope: dict,
    previous: dict,
) -> None:
    """Fresh cost figures under the committed policy knobs (peaks and
    tolerance survive a re-baseline; figures do not)."""
    payload = {
        "_doc": (
            "Committed per-bucket speed-of-light roofline (JXA013). "
            "peaks are PER-CHIP; the runtime gauge scales by the "
            "dp*tp parsed from the serving label. Re-baseline: python -m "
            "llm_weighted_consensus_tpu.analysis.mesh_audit "
            "--write-roofline, then review the diff. Math: DESIGN.md "
            "'Performance observability'."
        ),
        "scope": scope,
        "tolerance": previous.get(
            "tolerance", {m: DEFAULT_TOLERANCE for m in ROOFLINE_METRICS}
        ),
        "peaks": previous.get("peaks", DEFAULT_PEAKS),
        "buckets": {
            label: {
                m: round(float(figures[m]), 1)
                for m in ROOFLINE_METRICS
                if m in figures
            }
            for label, figures in sorted(measured.items())
        },
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


class RooflineGauge:
    """The live ``roofline`` /metrics section: per observed
    (mesh-shape, bucket) device-time key, SoL time for the serving
    backend and ``attainment = sol_ms / device_p50_ms``."""

    def __init__(self, roofline: dict, backend: str) -> None:
        self._peaks = roofline.get("peaks", {}).get(backend)
        self._buckets = roofline.get("buckets", {})
        self._backend = backend

    def snapshot(self) -> dict:
        from ..obs import phases as _phases

        rows: Dict[str, dict] = {}
        for label, stats in _phases.aggregator().device_snapshot().items():
            base, chips = split_label(label)
            row = {"count": stats["count"]}
            p50 = stats.get("p50_ms")
            if p50 is not None:
                row["device_p50_ms"] = p50
            figures = self._buckets.get(base)
            if figures is not None and self._peaks is not None:
                sol = sol_ms(figures, self._peaks, chips)
                if sol is not None:
                    row["sol_ms"] = round(sol, 4)
                    if p50:
                        row["attainment"] = round(sol / p50, 4)
            rows[label] = row
        return {
            "backend": self._backend,
            "known_peaks": self._peaks is not None,
            "buckets": rows,
        }
