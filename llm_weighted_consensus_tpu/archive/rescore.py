"""Archive batch re-scoring: recompute consensus over stored completions.

BASELINE config 4: "completions_archive batch re-score (10k archived
candidates, pmap)".  The use case: judge weights change (a panel is
re-weighted, a training table is updated) and every archived score
completion's consensus is recomputed — WITHOUT re-querying any judge.
Votes are already stored per judge choice (``message.vote``); re-scoring is
pure device math:

1. extract the [M, N] vote matrix + weight vector per archived completion;
2. stack into one [B, M, N] batch (padded to the panel-size max);
3. one dp-sharded batched tally over the mesh (parallel.batch);
4. write per-candidate weight/confidence back into wire form.
"""

from __future__ import annotations

from decimal import Decimal
from typing import Optional

import numpy as np


def vote_matrix(completion, max_judges: Optional[int] = None):
    """Archived score ChatCompletion -> (votes[M, N], weights[M], mask[M]).

    N = candidate choices (index < first judge index); judges without a
    stored vote (errored) get zero rows + zero mask.
    """
    # candidates carry model_index=None (score client initial chunk);
    # judge choices always carry their judge's model_index
    n_choices = 0
    judge_choices = []
    for choice in completion.choices:
        if choice.model_index is None:
            n_choices += 1
        else:
            judge_choices.append(choice)
    m = max(len(judge_choices), 1)
    if max_judges is not None:
        m = max_judges
    votes = np.zeros((m, n_choices), dtype=np.float32)
    weights = np.zeros((m,), dtype=np.float32)
    mask = np.zeros((m,), dtype=np.float32)
    for i, choice in enumerate(judge_choices[:m]):
        if choice.weight is not None:
            weights[i] = float(choice.weight)
        vote = getattr(choice.message, "vote", None)
        if vote is not None:
            votes[i, : len(vote)] = [float(v) for v in vote[:n_choices]]
            mask[i] = 1.0
    return votes, weights, mask


def rescore_archive(
    store,
    *,
    mesh=None,
    weight_overrides: Optional[dict] = None,
    ids: Optional[list] = None,
) -> dict:
    """Re-tally every archived score completion in one device batch.

    ``weight_overrides``: {judge model id -> new weight} applied before the
    tally (the re-weighting scenario).  Returns {completion id:
    {"weight": [...], "confidence": [...]}} aligned to candidate indices.
    Completions with differing shapes are grouped by (M, N) so each group
    is one static-shape batch.
    """
    from ..parallel.batch import rescore_batch

    ids = list(ids if ids is not None else store.score_ids())
    groups: dict = {}
    for cid in ids:
        completion = store._score[cid]
        votes, weights, mask = vote_matrix(completion)
        if weight_overrides:
            for i, choice in enumerate(
                c for c in completion.choices if c.model_index is not None
            ):
                if choice.model in weight_overrides and i < len(weights):
                    weights[i] = float(weight_overrides[choice.model])
        groups.setdefault(votes.shape, []).append((cid, votes, weights, mask))

    results: dict = {}
    for shape, rows in groups.items():
        batch_votes = np.stack([r[1] for r in rows])
        batch_weights = np.stack([r[2] for r in rows])
        batch_mask = np.stack([r[3] for r in rows])
        cw, conf = rescore_batch(
            batch_votes, batch_weights, batch_mask, mesh=mesh
        )
        cw = np.asarray(cw)
        conf = np.asarray(conf)
        for i, (cid, *_rest) in enumerate(rows):
            results[cid] = {
                "weight": [Decimal(repr(float(x))) for x in cw[i]],
                "confidence": [Decimal(repr(float(x))) for x in conf[i]],
            }
    return results


def apply_rescore(store, results: dict) -> int:
    """Write re-scored weights/confidences back into the archived wire
    objects (the checkpoint-update step).  Returns completions updated."""
    updated = 0
    for cid, scores in results.items():
        completion = store._score.get(cid)
        if completion is None:
            continue
        n = len(scores["confidence"])
        for choice in completion.choices:
            if choice.index < n and choice.model_index is None:
                choice.weight = scores["weight"][choice.index]
                choice.confidence = scores["confidence"][choice.index]
        updated += 1
    return updated
