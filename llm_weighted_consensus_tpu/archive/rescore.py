"""Archive batch re-scoring: recompute consensus over stored completions.

BASELINE config 4: "completions_archive batch re-score (10k archived
candidates, pmap)".  The use case: judge weights change (a panel is
re-weighted, a training table is updated) and every archived score
completion's consensus is recomputed — WITHOUT re-querying any judge.
Votes are already stored per judge choice (``message.vote``); re-scoring is
pure device math:

1. extract the [M, N] vote matrix + weight vector per archived completion;
2. stack into one [B, M, N] batch (padded to the panel-size max);
3. one dp-sharded batched tally over the mesh (parallel.batch);
4. write per-candidate weight/confidence back into wire form.

``revote=True`` additionally RE-EXTRACTS soft votes from stored judge
logprobs instead of trusting the stored vote vectors (SURVEY §3.5 hot loop
#2 on device): host code re-aligns each judge's ballot key against its
archived ``logprobs.content`` (the same alignment the live path uses —
ballot/vote.py), and the numeric tail — exp over the ``top_logprobs``
alternatives, scatter to candidates, normalize — runs as ONE batched
``ops.votes.softmax_votes`` dispatch over every judge of every completion.
Requires archived ballots (``InMemoryArchive.put_ballot``, fed by
``ScoreClient.ballot_sink``); judges without a ballot record, content key,
or logprobs fall back to their stored vote row.
"""

from __future__ import annotations

from decimal import Decimal
from typing import Optional

import numpy as np

from ..ballot import PrefixTree
from ..ballot.vote import (
    align_key_token,
    final_letter,
    find_key,
    soft_vote_alternatives,
)

MAX_LOGPROB_FAN = 20  # top_logprobs hard cap (llm/mod.rs:455-467)


def vote_matrix(completion, max_judges: Optional[int] = None):
    """Archived score ChatCompletion -> (votes[M, N], weights[M], mask[M]).

    N = candidate choices (index < first judge index); judges without a
    stored vote (errored) get zero rows + zero mask.
    """
    # candidates carry model_index=None (score client initial chunk);
    # judge choices always carry their judge's model_index
    n_choices = 0
    judge_choices = []
    for choice in completion.choices:
        if choice.model_index is None:
            n_choices += 1
        else:
            judge_choices.append(choice)
    m = max(len(judge_choices), 1)
    if max_judges is not None:
        m = max_judges
    votes = np.zeros((m, n_choices), dtype=np.float32)
    weights = np.zeros((m,), dtype=np.float32)
    mask = np.zeros((m,), dtype=np.float32)
    for i, choice in enumerate(judge_choices[:m]):
        if choice.weight is not None:
            weights[i] = float(choice.weight)
        vote = getattr(choice.message, "vote", None)
        if vote is not None:
            votes[i, : len(vote)] = [float(v) for v in vote[:n_choices]]
            mask[i] = 1.0
    return votes, weights, mask


def revote_inputs(completion, ballots, m: int, n_choices: int):
    """Host-side alignment for device re-extraction: per judge row, the
    ``softmax_votes`` inputs (logprobs[m, K], candidate_ids[m, K],
    valid[m, K]) plus use[m] — True where re-extraction is possible.

    One-hot fallbacks (no alignable logprobs, client.rs:1796-1798) are
    encoded as a single alternative with logprob 0: exp(0)=1 normalizes to
    the one-hot row, so the device kernel needs no special case.
    """
    k = MAX_LOGPROB_FAN
    lp = np.zeros((m, k), dtype=np.float32)
    cid = np.full((m, k), -1, dtype=np.int64)
    valid = np.zeros((m, k), dtype=np.float32)
    use = np.zeros((m,), dtype=bool)
    judge_choices = [
        c for c in completion.choices if c.model_index is not None
    ]
    for i, choice in enumerate(judge_choices[:m]):
        key_indices = (ballots or {}).get(choice.model_index)
        if not key_indices:
            continue
        keys = [key for key, _ in key_indices]
        with_ticks, without_ticks = PrefixTree.regex_patterns(keys)
        content = choice.message.content if choice.message else None
        key = find_key(content, with_ticks, without_ticks)
        if key is None:
            continue
        branch = PrefixTree.leaf_branch_of(key_indices, key)
        final = final_letter(key)
        tokens = (
            choice.logprobs.content if choice.logprobs is not None else None
        )
        alts = []
        aligned = align_key_token(key, final, tokens)
        if aligned is not None:
            alts = soft_vote_alternatives(branch, *aligned)
        # stale/corrupt ballot records could map outside this completion's
        # candidate range; such rows keep their stored vote
        alts = [a for a in alts if 0 <= a[0] < n_choices]
        if not alts:
            leaf = branch.get(final)
            if not isinstance(leaf, int) or not 0 <= leaf < n_choices:
                continue
            alts = [(leaf, 0.0)]
        for slot, (leaf, logprob) in enumerate(alts[:k]):
            lp[i, slot] = float(logprob)
            cid[i, slot] = leaf
            valid[i, slot] = 1.0
        use[i] = True
    return lp, cid, valid, use


def rescore_archive(
    store,
    *,
    mesh=None,
    weight_overrides: Optional[dict] = None,
    ids: Optional[list] = None,
    revote: bool = False,
) -> dict:
    """Re-tally every archived score completion in one device batch.

    ``weight_overrides``: {judge model id -> new weight} applied before the
    tally (the re-weighting scenario).  ``revote=True`` re-extracts soft
    votes from stored logprobs on device first (see module docstring).
    Returns {completion id: {"weight": [...], "confidence": [...]}} aligned
    to candidate indices.  Completions with differing shapes are grouped by
    (M, N) so each group is one static-shape batch.
    """
    from ..parallel.batch import rescore_batch

    ids = list(ids if ids is not None else store.score_ids())
    groups: dict = {}
    for cid in ids:
        completion = store.score_completion(cid)
        if completion is None:  # evicted/unknown id: nothing to re-tally
            continue
        votes, weights, mask = vote_matrix(completion)
        if weight_overrides:
            for i, choice in enumerate(
                c for c in completion.choices if c.model_index is not None
            ):
                if choice.model in weight_overrides and i < len(weights):
                    weights[i] = float(weight_overrides[choice.model])
        groups.setdefault(votes.shape, []).append((cid, votes, weights, mask))

    results: dict = {}
    for shape, rows in groups.items():
        batch_votes = np.stack([r[1] for r in rows])
        batch_weights = np.stack([r[2] for r in rows])
        batch_mask = np.stack([r[3] for r in rows])
        if revote:
            batch_votes, batch_mask = _revote_group(
                store, rows, batch_votes, batch_mask, shape
            )
        cw, conf = rescore_batch(
            batch_votes, batch_weights, batch_mask, mesh=mesh
        )
        cw = np.asarray(cw)
        conf = np.asarray(conf)
        for i, (cid, *_rest) in enumerate(rows):
            results[cid] = {
                "weight": [Decimal(repr(float(x))) for x in cw[i]],
                "confidence": [Decimal(repr(float(x))) for x in conf[i]],
            }
    return results


def _revote_group(store, rows, batch_votes, batch_mask, shape):
    """Device re-extraction for one (M, N) shape group: one batched
    ``softmax_votes`` dispatch over every judge of every completion; rows
    where re-extraction isn't possible keep their stored vote + mask."""
    from ..ops.votes import softmax_votes

    m, n = shape
    b = len(rows)
    lp = np.zeros((b, m, MAX_LOGPROB_FAN), dtype=np.float32)
    cid = np.full((b, m, MAX_LOGPROB_FAN), -1, dtype=np.int64)
    valid = np.zeros((b, m, MAX_LOGPROB_FAN), dtype=np.float32)
    use = np.zeros((b, m), dtype=bool)
    for bi, (completion_id, *_rest) in enumerate(rows):
        completion = store.score_completion(completion_id)
        if completion is None:  # vanished mid-pass: keep stored votes
            continue
        ballots = store.score_ballots(completion_id)
        lp[bi], cid[bi], valid[bi], use[bi] = revote_inputs(
            completion, ballots, m, n
        )
    if not use.any():
        return batch_votes, batch_mask
    device_votes = np.asarray(
        softmax_votes(
            lp.reshape(b * m, MAX_LOGPROB_FAN),
            cid.reshape(b * m, MAX_LOGPROB_FAN),
            valid.reshape(b * m, MAX_LOGPROB_FAN),
            n,
        )
    ).reshape(b, m, n)
    votes = np.where(use[:, :, None], device_votes, batch_votes)
    mask = np.where(use, 1.0, batch_mask).astype(batch_mask.dtype)
    return votes, mask


def apply_rescore(store, results: dict) -> int:
    """Write re-scored weights/confidences back into the archived wire
    objects (the checkpoint-update step).  Returns completions updated."""
    updated = 0
    for cid, scores in results.items():
        completion = store.score_completion(cid)
        if completion is None:
            continue
        n = len(scores["confidence"])
        for choice in completion.choices:
            if choice.index < n and choice.model_index is None:
                choice.weight = scores["weight"][choice.index]
                choice.confidence = scores["confidence"][choice.index]
        updated += 1
    return updated
