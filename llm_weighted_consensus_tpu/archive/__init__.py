"""Completions archive: addressable past completions (checkpoint/resume analog).

Every completion type (chat / score / multichat) is addressable by id and can
be rehydrated into later requests — as conversation messages (the custom
``chat_completion`` / ``score_completion`` / ``multichat_completion`` roles)
or as score candidates.  Parity targets: reference
src/completions_archive/{mod,fetcher}.rs (seam + union + unimplemented stub),
src/chat/completions/client.rs:437-645 (prefetch + rehydration).

The archive is also the batch re-score source: ``InMemoryArchive`` backs the
pmap archive re-scoring path (BASELINE config 4) and can be snapshotted to
disk, which is this framework's checkpoint/resume story (SURVEY §5).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..errors import (
    ArchiveFetchError,
    InvalidCompletionChoiceIndex,
    ResponseError,
)
from ..types import chat_request, chat_response, multichat_response, score_response

# Completion union (completions_archive/mod.rs:5-9): a fetched completion is
# one of the three unary completion types, discriminated by source kind.
KIND_CHAT = "chat"
KIND_SCORE = "score"
KIND_MULTICHAT = "multichat"


class Fetcher:
    """Archive seam (completions_archive/fetcher.rs:3-29).

    All three methods are async and return the unary completion types from
    ``types``.  Failures raise :class:`ResponseError` (converted to
    ``ArchiveFetchError`` by callers).
    """

    async def fetch_chat_completion(self, ctx, completion_id: str):
        raise NotImplementedError

    async def fetch_score_completion(self, ctx, completion_id: str):
        raise NotImplementedError

    async def fetch_multichat_completion(self, ctx, completion_id: str):
        raise NotImplementedError


class UnimplementedFetcher(Fetcher):
    """Default stub — the service runs without an archive store, and any
    archive-reference message is a client error (mod.rs:31-65 panics; we map
    to a 501 ResponseError instead of crashing the process)."""

    async def fetch_chat_completion(self, ctx, completion_id: str):
        raise ResponseError(code=501, message="completions archive not configured")

    fetch_score_completion = fetch_chat_completion
    fetch_multichat_completion = fetch_chat_completion


class InMemoryArchive(Fetcher):
    """Dict-backed archive store, used by tests and the batch re-score path.

    ``max_completions`` bounds EACH completion table (chat / score /
    multichat) with FIFO eviction — a long-running service with
    ARCHIVE_WRITE on must not grow with traffic forever (the shutdown
    snapshot re-serializes everything it holds).  Evicting a score
    completion drops its ballots and request record too (useless without
    the completion).  ``None`` = unbounded (library use; the service
    default is ``ARCHIVE_MAX_COMPLETIONS``, serve/config.py).
    """

    def __init__(self, max_completions: Optional[int] = None):
        self.max_completions = max_completions
        self._chat: dict = {}
        self._score: dict = {}
        self._multichat: dict = {}
        # score completion id -> {judge model_index: [(key, candidate)]}:
        # the archivable ballot form enabling logprob re-extraction
        # (archive/rescore.py revote; populated via ScoreClient.ballot_sink)
        self._ballots: dict = {}
        # score completion id -> originating request params (the training
        # signal source: prompts are embedded for table rows)
        self._score_requests: dict = {}
        # FIFO of ballot cids not (yet) archived — the O(1) eviction
        # candidate queue for put_ballot (entries are lazily discarded
        # when they turn out to be archived by the time they surface) —
        # plus the live count of orphans (ballot cids NOT in _score):
        # the cap must bound the orphan population, not total ballots,
        # or an archive holding >cap archived-with-ballots completions
        # would drain every in-flight request's ballots on each
        # put_ballot (ADVICE r3)
        from collections import deque

        # maxlen bounds the queue STRUCTURE, not just the orphan count:
        # cids that get archived after queueing stay in the deque as
        # stale entries (lazily discarded), and a streaming-heavy service
        # whose completions all get archived would otherwise grow the
        # deque forever while _n_orphan_ballots sat at zero.  2x the
        # orphan cap leaves room for a full cap of live orphans plus as
        # many stale entries; displacement past that is handled (and
        # counted) explicitly in put_ballot
        self._ballot_orphans = deque(maxlen=2 * self.MAX_BALLOT_COMPLETIONS)
        self._orphan_queue_drops = 0
        self._n_orphan_ballots = 0

    def _evict_over_cap(self, table: dict) -> None:
        if self.max_completions is None:
            return
        cap = max(0, self.max_completions)  # negative never drains past 0
        while len(table) > cap:
            victim = next(iter(table))  # dicts preserve insertion order
            table.pop(victim)
            if table is self._score:
                self._ballots.pop(victim, None)
                self._score_requests.pop(victim, None)

    def enforce_cap(self) -> None:
        """Apply the cap to every table now (e.g. after loading an
        over-cap snapshot or lowering ``max_completions``)."""
        for table in (self._chat, self._score, self._multichat):
            self._evict_over_cap(table)

    def put_chat(self, completion) -> str:
        self._chat[completion.id] = completion
        self._evict_over_cap(self._chat)
        return completion.id

    def put_score(self, completion) -> str:
        if completion.id not in self._score and completion.id in self._ballots:
            # orphan -> archived transition: its ballots leave the capped
            # population (revote needs them for as long as the completion
            # lives)
            self._n_orphan_ballots -= 1
        self._score[completion.id] = completion
        self._evict_over_cap(self._score)
        return completion.id

    def put_score_request(self, completion_id: str, params) -> None:
        """Keep the originating request beside its completion — training
        tables learn from the PROMPT embedding (weights/learning.py), and
        the prompt lives in the request, not the completion."""
        self._score_requests[completion_id] = params

    def score_request(self, completion_id: str):
        return self._score_requests.get(completion_id)

    def score_completion(self, completion_id: str):
        """Sync accessor (the async fetch_* trio serves the client seam)."""
        return self._score.get(completion_id)

    # ballots are recorded for EVERY score request (the sink fires inside
    # create_streaming) but only archived completions keep needing theirs;
    # cap the table so streaming-heavy services can't grow it unboundedly
    # (FIFO eviction of the oldest completion's ballots — dicts preserve
    # insertion order, and in-flight requests are by definition newest)
    MAX_BALLOT_COMPLETIONS = 4096

    def put_ballot(
        self, completion_id: str, judge_index: int, key_indices: list
    ) -> None:
        """ScoreClient.ballot_sink-shaped recorder:
        ``ScoreClient(..., ballot_sink=store.put_ballot)``."""
        if completion_id not in self._ballots:
            if (
                self._ballot_orphans.maxlen is not None
                and len(self._ballot_orphans) == self._ballot_orphans.maxlen
            ):
                # the append below would silently displace the head; make
                # the displacement an honest eviction instead — if the
                # head is still a live orphan its ballots go with it
                # (it was the oldest candidate anyway), and either way
                # the drop is counted for /metrics-side forensics
                dropped = self._ballot_orphans[0]
                self._orphan_queue_drops += 1
                if (
                    dropped != completion_id
                    and dropped not in self._score
                    and dropped in self._ballots
                ):
                    self._ballots.pop(dropped)
                    self._n_orphan_ballots -= 1
            self._ballot_orphans.append(completion_id)
            if completion_id not in self._score:
                self._n_orphan_ballots += 1
        self._ballots.setdefault(completion_id, {})[judge_index] = list(
            key_indices
        )
        # the cap bounds ORPHANS (streaming requests whose completions
        # never get archived), oldest first via the FIFO — O(1) amortized
        # per eviction, not a scan of every key.  Archived completions'
        # ballots — and the in-flight request being recorded right now —
        # are never evicted: revote needs the former, put_score hasn't
        # had its chance at the latter; neither counts against the cap
        # (archived growth legitimately tracks the archive's size).
        rotated = False
        while self._n_orphan_ballots > self.MAX_BALLOT_COMPLETIONS:
            if not self._ballot_orphans:
                break
            victim = self._ballot_orphans[0]
            if victim == completion_id:
                if rotated:
                    break  # full cycle: nothing else left to evict
                # rotate the in-flight id to the back so eviction can
                # continue past it to newer orphans (a late ballot for an
                # old completion must not wedge the queue, ADVICE r3)
                self._ballot_orphans.popleft()
                self._ballot_orphans.append(completion_id)
                rotated = True
                continue
            self._ballot_orphans.popleft()
            if victim in self._score or victim not in self._ballots:
                # archived since queued (keep forever) or already dropped
                continue
            self._ballots.pop(victim)
            self._n_orphan_ballots -= 1

    def score_ballots(self, completion_id: str) -> Optional[dict]:
        return self._ballots.get(completion_id)

    def put_multichat(self, completion) -> str:
        self._multichat[completion.id] = completion
        self._evict_over_cap(self._multichat)
        return completion.id

    def chat_ids(self) -> list:
        return list(self._chat)

    def score_ids(self) -> list:
        return list(self._score)

    def multichat_ids(self) -> list:
        return list(self._multichat)

    async def _get(self, table: dict, completion_id: str):
        completion = table.get(completion_id)
        if completion is None:
            raise ResponseError(
                code=404, message=f"completion not found: {completion_id}"
            )
        return completion

    async def fetch_chat_completion(self, ctx, completion_id: str):
        return await self._get(self._chat, completion_id)

    async def fetch_score_completion(self, ctx, completion_id: str):
        return await self._get(self._score, completion_id)

    async def fetch_multichat_completion(self, ctx, completion_id: str):
        return await self._get(self._multichat, completion_id)

    # -- disk snapshot (checkpoint/resume, SURVEY §5) -----------------------

    SNAPSHOT_VERSION = 1

    def save(self, path: str) -> None:
        """Snapshot every table (+ ballot records) to one JSON file.
        Written atomically (temp + rename); Decimal-exact via jsonutil."""
        from ..utils import jsonutil

        obj = {
            "version": self.SNAPSHOT_VERSION,
            "chat": {k: v.to_json_obj() for k, v in self._chat.items()},
            "score": {k: v.to_json_obj() for k, v in self._score.items()},
            "multichat": {
                k: v.to_json_obj() for k, v in self._multichat.items()
            },
            # ballots for never-archived completions (e.g. streaming
            # requests whose fold was not stored) would accumulate forever
            "ballots": {
                cid: b
                for cid, b in self._ballots.items()
                if cid in self._score
            },
            "score_requests": {
                cid: params.to_json_obj()
                for cid, params in self._score_requests.items()
                if cid in self._score
            },
        }
        from ..utils.io import atomic_write

        atomic_write(path, lambda f: f.write(jsonutil.dumps(obj).encode("utf-8")))

    @classmethod
    def load(cls, path: str) -> "InMemoryArchive":
        """Rebuild an archive from a :meth:`save` snapshot."""
        from ..utils import jsonutil

        with open(path, encoding="utf-8") as f:
            obj = jsonutil.loads(f.read())
        version = obj.get("version")
        if version != cls.SNAPSHOT_VERSION:
            raise ValueError(
                f"unsupported archive snapshot version {version!r}"
            )
        store = cls()
        store._chat = {
            k: chat_response.ChatCompletion.from_json_obj(v)
            for k, v in obj.get("chat", {}).items()
        }
        store._score = {
            k: score_response.ChatCompletion.from_json_obj(v)
            for k, v in obj.get("score", {}).items()
        }
        store._multichat = {
            k: multichat_response.ChatCompletion.from_json_obj(v)
            for k, v in obj.get("multichat", {}).items()
        }
        # JSON stringifies the judge-index keys; restore them as ints
        store._ballots = {
            cid: {int(judge): pairs for judge, pairs in judges.items()}
            for cid, judges in obj.get("ballots", {}).items()
        }
        # rebuild the orphan queue/count the snapshot doesn't carry, so
        # loaded not-yet-archived ballots stay evictable and the cap
        # arithmetic starts consistent
        for cid in store._ballots:
            if cid not in store._score:
                store._ballot_orphans.append(cid)
                store._n_orphan_ballots += 1
        from ..types import score_request

        store._score_requests = {
            cid: score_request.ChatCompletionCreateParams.from_json_obj(v)
            for cid, v in obj.get("score_requests", {}).items()
        }
        return store


# ---------------------------------------------------------------------------
# Prefetch + rehydration (chat client.rs:437-645)
# ---------------------------------------------------------------------------

_MESSAGE_KIND = {
    chat_request.ChatCompletionMessage: KIND_CHAT,
    chat_request.ScoreCompletionMessage: KIND_SCORE,
    chat_request.MultichatCompletionMessage: KIND_MULTICHAT,
}


def fetch_fn(fetcher: Fetcher, kind: str):
    return {
        KIND_CHAT: fetcher.fetch_chat_completion,
        KIND_SCORE: fetcher.fetch_score_completion,
        KIND_MULTICHAT: fetcher.fetch_multichat_completion,
    }[kind]


def message_refs(messages: list, seen: set) -> list:
    """Unique (id, kind) pairs referenced by archive-role messages."""
    refs = []
    for message in messages:
        kind = _MESSAGE_KIND.get(type(message))
        if kind is None or message.id in seen:
            continue
        seen.add(message.id)
        refs.append((message.id, kind))
    return refs


async def fetch_archived(
    fetcher: Fetcher, ctx, refs: list, error_cls=None
) -> dict:
    """Concurrently fetch archived completions for (id, kind) pairs;
    returns {id: (kind, completion)}.

    Mirrors fetch_completion_futs_from_messages (chat client.rs:437-514):
    one future per unique id, all awaited together; ``error_cls`` wraps
    ResponseError failures (chat vs score error envelope).
    """
    if not refs:
        return {}
    try:
        completions = await asyncio.gather(
            *(fetch_fn(fetcher, kind)(ctx, cid) for cid, kind in refs)
        )
    except ResponseError as e:
        raise (error_cls or ArchiveFetchError)(e) from e
    return {cid: (kind, c) for (cid, kind), c in zip(refs, completions)}


async def fetch_archived_for_messages(
    fetcher: Fetcher, ctx, messages: list
) -> dict:
    return await fetch_archived(fetcher, ctx, message_refs(messages, set()))


def completion_choice_message(kind: str, completion, choice_index: int):
    """The unary response message of choice ``choice_index``, or None."""
    for choice in completion.choices:
        if choice.index == choice_index:
            message = choice.message
            if kind == KIND_SCORE:
                # score choices wrap the chat message (inner) next to the vote
                return message.inner()
            return message
    return None


def replace_archive_messages(completions: dict, messages: list) -> list:
    """Replace archive-reference messages with real assistant messages.

    Mirrors replace_completion_messages_with_assistant_messages (chat
    client.rs:516-581).  Returns a new message list; raises
    :class:`InvalidCompletionChoiceIndex` for an out-of-range choice.
    """
    if not completions:
        return messages
    out = []
    for message in messages:
        kind = _MESSAGE_KIND.get(type(message))
        if kind is None:
            out.append(message)
            continue
        stored_kind, completion = completions[message.id]
        response_message = completion_choice_message(
            stored_kind, completion, message.choice_index
        )
        if response_message is None:
            raise InvalidCompletionChoiceIndex(message.id, message.choice_index)
        out.append(
            response_message_to_assistant_message(response_message, message.name)
        )
    return out


def response_message_to_assistant_message(
    message, name: Optional[str] = None
) -> chat_request.AssistantMessage:
    """Convert a unary response message back into request form.

    Mirrors convert_completion_choice_message_to_assistant_message (chat
    client.rs:583-645): generated images become input image parts; response
    tool calls become request tool calls; reasoning is dropped.
    """
    image_parts = [
        chat_request.ImageUrlPart(
            image_url=chat_request.ImageUrl(url=image.image_url.url)
        )
        for image in (message.images or [])
    ]
    content = None
    if message.content is not None and image_parts:
        content = [chat_request.TextPart(text=message.content), *image_parts]
    elif message.content is not None:
        content = message.content
    elif image_parts:
        content = image_parts
    tool_calls = None
    if message.tool_calls is not None:
        tool_calls = [
            chat_request.AssistantToolCall(
                id=tc.id,
                function=chat_request.AssistantToolCallFunction(
                    name=tc.function.name, arguments=tc.function.arguments
                ),
            )
            for tc in message.tool_calls
        ]
    return chat_request.AssistantMessage(
        content=content,
        name=name,
        refusal=message.refusal,
        tool_calls=tool_calls,
        reasoning=None,
    )
