"""Model registry seam: stored panels addressable by 22-char content id.

Parity target: reference src/score/model/fetcher.rs (trait + unimplemented
stub).  ``InMemoryModelRegistry`` adds the obvious store the reference leaves
external: panels registered by their content-addressed id.
"""

from __future__ import annotations

from .errors import ResponseError


class ModelFetcher:
    async def fetch(self, ctx, model_id: str):
        """Return a validated ``identity.model.Model`` or raise ResponseError."""
        raise NotImplementedError


class UnimplementedModelFetcher(ModelFetcher):
    async def fetch(self, ctx, model_id: str):
        raise ResponseError(code=501, message="model registry not configured")


class InMemoryModelRegistry(ModelFetcher):
    def __init__(self) -> None:
        self._models: dict = {}

    def put(self, model) -> str:
        """Register a validated Model under its content id."""
        self._models[model.id] = model
        return model.id

    async def fetch(self, ctx, model_id: str):
        model = self._models.get(model_id)
        if model is None:
            raise ResponseError(code=404, message=f"model not found: {model_id}")
        return model
