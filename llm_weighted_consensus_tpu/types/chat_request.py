"""Chat completion request surface (OpenAI + OpenRouter superset).

Parity target: reference src/chat/completions/request.rs:4-753 — the full
request params, the 8-role message tree (including the three custom archive
reference roles ``chat_completion`` / ``score_completion`` /
``multichat_completion``, request.rs:328-333), rich content parts, tools,
provider preferences, and the ``template_content`` flattener (request.rs:78-91)
that feeds the trained-weight embedding input.
"""

from __future__ import annotations

from typing import Optional

from .base import (
    RAW,
    Const,
    Enum,
    List,
    Map,
    SchemaError,
    Struct,
    TaggedUnion,
    Union,
    field,
)

# ---------------------------------------------------------------------------
# Simple enums / small structs
# ---------------------------------------------------------------------------

SERVICE_TIER = Enum("auto", "default", "flex")
REASONING_EFFORT = Enum("minimal", "low", "medium", "high")
VERBOSITY = Enum("low", "medium", "high")


class PredictionContentPart(Struct):
    text: str = field(str)
    type: str = field(Const("text"), default="text")


class Prediction(Struct):
    content: object = field(Union(str, List(PredictionContentPart)))
    type: str = field(Const("content"), default="content")


class JsonSchema(Struct):
    name: str = field(str)
    description: Optional[str] = field(str, default=None)
    schema: object = field(RAW, default=None)
    strict: Optional[bool] = field(bool, default=None)


class ResponseFormat(Struct):
    """serde ``#[serde(tag = "type")]`` enum flattened into one struct.

    ``type`` is one of ``text`` / ``json_object`` / ``json_schema``;
    ``json_schema`` present only for the last (request.rs:184-193).
    """

    type: str = field(Enum("text", "json_object", "json_schema"))
    json_schema: Optional[JsonSchema] = field(JsonSchema, default=None)

    def is_json(self) -> bool:
        return self.type in ("json_object", "json_schema")


class StreamOptions(Struct):
    include_usage: Optional[bool] = field(bool, default=None)


class ToolChoiceFunctionFunction(Struct):
    name: str = field(str)


class ToolChoiceFunction(Struct):
    type: str = field(Const("function"), default="function")
    function: ToolChoiceFunctionFunction = field(ToolChoiceFunctionFunction, default=None)


# ToolChoice = "none" | "auto" | "required" | ToolChoiceFunction
TOOL_CHOICE = Union(Enum("none", "auto", "required"), ToolChoiceFunction)


class FunctionDefinition(Struct):
    name: str = field(str)
    description: Optional[str] = field(str, default=None)
    parameters: object = field(RAW, default=None)
    strict: Optional[bool] = field(bool, default=None)


class Tool(Struct):
    function: FunctionDefinition = field(FunctionDefinition)
    type: str = field(Const("function"), default="function")


class UserLocationApproximate(Struct):
    city: Optional[str] = field(str, default=None)
    country: Optional[str] = field(str, default=None)
    region: Optional[str] = field(str, default=None)
    timezone: Optional[str] = field(str, default=None)


class UserLocation(Struct):
    approximate: UserLocationApproximate = field(UserLocationApproximate)
    type: str = field(Const("approximate"), default="approximate")


class WebSearchOptions(Struct):
    search_context_size: Optional[str] = field(Enum("low", "medium", "high"), default=None)
    user_location: Optional[UserLocation] = field(UserLocation, default=None)


class ProviderPreferences(Struct):
    """OpenRouter provider routing preferences (request.rs:682-713)."""

    order: Optional[list] = field(List(str), default=None)
    allow_fallbacks: Optional[bool] = field(bool, default=None)
    require_parameters: Optional[bool] = field(bool, default=None)
    data_collection: Optional[str] = field(Enum("allow", "deny"), default=None)
    only: Optional[list] = field(List(str), default=None)
    ignore: Optional[list] = field(List(str), default=None)
    quantizations: Optional[list] = field(List(str), default=None)
    sort: Optional[str] = field(str, default=None)

    def is_empty(self) -> bool:
        return all(
            getattr(self, f) is None
            for f in (
                "order",
                "allow_fallbacks",
                "require_parameters",
                "data_collection",
                "only",
                "ignore",
                "quantizations",
                "sort",
            )
        )


class Plugin(Struct):
    # serde flattens unknown fields into `fields`; we keep them raw.
    id: str = field(str)
    fields: Optional[dict] = field(Map(RAW), default=None)

    def to_json_obj(self):
        out = {"id": self.id}
        if self.fields:
            out.update(self.fields)
        return out

    @classmethod
    def from_json_obj(cls, obj, *, path: str = ""):
        if not isinstance(obj, dict) or "id" not in obj:
            raise SchemaError(path, "expected plugin object with `id`")
        rest = {k: v for k, v in obj.items() if k != "id"}
        return cls(id=obj["id"], fields=rest or None)


class Reasoning(Struct):
    max_tokens: Optional[int] = field(int, default=None)
    effort: Optional[str] = field(REASONING_EFFORT, default=None)
    enabled: Optional[bool] = field(bool, default=None)


class UsageInclude(Struct):
    include: bool = field(bool)


# ---------------------------------------------------------------------------
# Content
# ---------------------------------------------------------------------------


class SimpleContentPart(Struct):
    text: str = field(str)
    type: str = field(Const("text"), default="text")


# SimpleContent = str | [SimpleContentPart]
SIMPLE_CONTENT = Union(str, List(SimpleContentPart))


class ImageUrl(Struct):
    url: str = field(str)
    detail: Optional[str] = field(Enum("auto", "low", "high"), default=None)


class InputAudio(Struct):
    data: str = field(str)
    format: str = field(Enum("wav", "mp3"))


class VideoUrl(Struct):
    url: str = field(str)


class FilePart(Struct):
    file_data: Optional[str] = field(str, default=None)
    file_id: Optional[str] = field(str, default=None)
    filename: Optional[str] = field(str, default=None)


class TextPart(Struct):
    text: str = field(str)


class ImageUrlPart(Struct):
    image_url: ImageUrl = field(ImageUrl)


class InputAudioPart(Struct):
    input_audio: InputAudio = field(InputAudio)


class InputVideoPart(Struct):
    video_url: VideoUrl = field(VideoUrl)


class FileContentPart(Struct):
    file: FilePart = field(FilePart)


RICH_CONTENT_PART = TaggedUnion(
    "type",
    {
        "text": TextPart,
        "image_url": ImageUrlPart,
        "input_audio": InputAudioPart,
        "input_video": InputVideoPart,
        "file": FileContentPart,
    },
)

# RichContent = str | [RichContentPart]
RICH_CONTENT = Union(str, List(RICH_CONTENT_PART))


def simple_content_text(content) -> str:
    """Flatten SimpleContent to plain text (request.rs:514-523)."""
    if isinstance(content, str):
        return content
    return "".join(part.text for part in content)


def rich_content_text(content) -> str:
    """Flatten RichContent keeping only text parts (request.rs:550-583)."""
    if isinstance(content, str):
        return content
    return "".join(part.text for part in content if isinstance(part, TextPart))


# ---------------------------------------------------------------------------
# Tool calls (request side)
# ---------------------------------------------------------------------------


class AssistantToolCallFunction(Struct):
    name: str = field(str)
    arguments: str = field(str)


class AssistantToolCall(Struct):
    id: str = field(str)
    function: AssistantToolCallFunction = field(AssistantToolCallFunction)
    type: str = field(Const("function"), default="function")

    def template_content(self) -> str:
        from ..utils import jsonutil

        return "<tool_call>%s</tool_call>" % jsonutil.dumps(self.to_json_obj())


# ---------------------------------------------------------------------------
# Messages (tagged by role; request.rs:315-334)
# ---------------------------------------------------------------------------


class DeveloperMessage(Struct):
    content: object = field(SIMPLE_CONTENT)
    name: Optional[str] = field(str, default=None)

    def template_content(self) -> str:
        who = f"developer ({self.name})" if self.name else "developer"
        return f"{who}: {simple_content_text(self.content)}"


class SystemMessage(Struct):
    content: object = field(SIMPLE_CONTENT)
    name: Optional[str] = field(str, default=None)

    def template_content(self) -> str:
        who = f"system ({self.name})" if self.name else "system"
        return f"{who}: {simple_content_text(self.content)}"


class UserMessage(Struct):
    content: object = field(RICH_CONTENT)
    name: Optional[str] = field(str, default=None)

    def template_content(self) -> str:
        who = f"user ({self.name})" if self.name else "user"
        return f"{who}: {rich_content_text(self.content)}"


class ToolMessage(Struct):
    content: object = field(RICH_CONTENT)
    tool_call_id: str = field(str)

    def template_content(self) -> str:
        return f"tool ({self.tool_call_id}): {rich_content_text(self.content)}"


class AssistantMessage(Struct):
    content: object = field(RICH_CONTENT, default=None)
    name: Optional[str] = field(str, default=None)
    refusal: Optional[str] = field(str, default=None)
    tool_calls: Optional[list] = field(List(AssistantToolCall), default=None)
    reasoning: Optional[str] = field(str, default=None)

    def template_content(self) -> str:
        # request.rs:442-478: content / refusal / tool_calls lines, each
        # prefixed with the role tag, newline-joined.
        who = f"assistant ({self.name})" if self.name else "assistant"
        lines = []
        if self.content is not None:
            lines.append(f"{who}: {rich_content_text(self.content)}")
        if self.refusal is not None:
            lines.append(f"{who}: {self.refusal}")
        if self.tool_calls is not None:
            lines.append(
                f"{who}: " + "".join(tc.template_content() for tc in self.tool_calls)
            )
        return "\n".join(lines)


class ChatCompletionMessage(Struct):
    """Archive reference role ``chat_completion`` (request.rs:480-487)."""

    id: str = field(str)
    choice_index: int = field(int, default=0)
    name: Optional[str] = field(str, default=None)

    def template_content(self) -> str:
        return ""


class ScoreCompletionMessage(Struct):
    id: str = field(str)
    choice_index: int = field(int, default=0)
    name: Optional[str] = field(str, default=None)

    def template_content(self) -> str:
        return ""


class MultichatCompletionMessage(Struct):
    id: str = field(str)
    choice_index: int = field(int, default=0)
    name: Optional[str] = field(str, default=None)

    def template_content(self) -> str:
        return ""


MESSAGE = TaggedUnion(
    "role",
    {
        "developer": DeveloperMessage,
        "system": SystemMessage,
        "user": UserMessage,
        "assistant": AssistantMessage,
        "tool": ToolMessage,
        "chat_completion": ChatCompletionMessage,
        "score_completion": ScoreCompletionMessage,
        "multichat_completion": MultichatCompletionMessage,
    },
)

ARCHIVE_MESSAGE_TYPES = (
    ChatCompletionMessage,
    ScoreCompletionMessage,
    MultichatCompletionMessage,
)


# Stop = str | [str]
STOP = Union(str, List(str))


def stop_to_list(stop) -> list:
    if stop is None:
        return []
    if isinstance(stop, str):
        return [stop]
    return list(stop)


# ---------------------------------------------------------------------------
# The request params
# ---------------------------------------------------------------------------


class ChatCompletionCreateParams(Struct):
    """Full request body for POST /chat/completions (request.rs:4-76)."""

    messages: list = field(List(MESSAGE))
    model: str = field(str)
    frequency_penalty: Optional[float] = field(float, default=None)
    logit_bias: Optional[dict] = field(Map(int), default=None)
    logprobs: Optional[bool] = field(bool, default=None)
    max_completion_tokens: Optional[int] = field(int, default=None)
    modalities: Optional[list] = field(List(str), default=None)
    n: Optional[int] = field(int, default=None)
    parallel_tool_calls: Optional[bool] = field(bool, default=None)
    prediction: Optional[Prediction] = field(Prediction, default=None)
    presence_penalty: Optional[float] = field(float, default=None)
    reasoning_effort: Optional[str] = field(REASONING_EFFORT, default=None)
    response_format: Optional[ResponseFormat] = field(ResponseFormat, default=None)
    seed: Optional[int] = field(int, default=None)
    service_tier: Optional[str] = field(SERVICE_TIER, default=None)
    stop: object = field(STOP, default=None)
    stream: Optional[bool] = field(bool, default=None)
    stream_options: Optional[StreamOptions] = field(StreamOptions, default=None)
    temperature: Optional[float] = field(float, default=None)
    tool_choice: object = field(TOOL_CHOICE, default=None)
    tools: Optional[list] = field(List(Tool), default=None)
    top_logprobs: Optional[int] = field(int, default=None)
    top_p: Optional[float] = field(float, default=None)
    web_search_options: Optional[WebSearchOptions] = field(WebSearchOptions, default=None)
    # openrouter fields
    max_tokens: Optional[int] = field(int, default=None)
    min_p: Optional[float] = field(float, default=None)
    plugins: Optional[list] = field(List(Plugin), default=None)
    provider: Optional[ProviderPreferences] = field(ProviderPreferences, default=None)
    reasoning: Optional[Reasoning] = field(Reasoning, default=None)
    repetition_penalty: Optional[float] = field(float, default=None)
    top_a: Optional[float] = field(float, default=None)
    top_k: Optional[int] = field(int, default=None)
    usage: Optional[UsageInclude] = field(UsageInclude, default=None)
    verbosity: Optional[str] = field(VERBOSITY, default=None)
    models: Optional[list] = field(List(str), default=None)

    def template_content(self) -> str:
        """Newline-join each message's template line (request.rs:78-91)."""
        return "\n".join(m.template_content() for m in self.messages)
