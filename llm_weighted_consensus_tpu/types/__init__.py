"""Pure wire-type core (no IO, no JAX) — the wasm-safe-core analog.

Submodules:

* ``base``               — declarative schema + generic merge (``push``) algebra
* ``chat_request``       — OpenAI/OpenRouter chat request surface
* ``chat_response``      — streaming/unary chat responses, usage, logprobs
* ``score_request``      — score request (messages + model + choices)
* ``score_response``     — score responses (weights/confidences/votes)
* ``multichat_response`` — multi-model fan-out responses
* ``embeddings``         — embedding request/response types
"""

from . import (  # noqa: F401
    base,
    chat_request,
    chat_response,
    embeddings,
    multichat_response,
    score_request,
    score_response,
)
from .base import fold_chunks  # noqa: F401
