"""Multichat completion response types — one request, many models.

Parity target: reference src/multichat/completions/response.rs (229 LoC) —
chat-completion-shaped responses whose choices each carry ``error`` /
``model`` / ``model_index`` / ``completion_metadata``.  Types-only in the
reference; this framework implements the actual fan-out client
(clients/multichat.py).
"""

from __future__ import annotations

from typing import Optional

from .base import ResponseError
from .base import Const, KEEP, KEYED, List, Map, NESTED, Struct, field
from .chat_response import (
    Delta,
    FINISH_REASON,
    FINISH_REASON_DEFAULT,
    Logprobs,
    Message,
    Usage,
)
from .score_response import CompletionMetadata


class StreamingChoice(Struct):
    delta: Delta = field(Delta, default_factory=Delta, merge=NESTED)
    finish_reason: Optional[str] = field(FINISH_REASON, default=None, skip_if_none=False)
    index: int = field(int, default=0, merge=KEEP, skip_if_none=False)
    logprobs: Optional[Logprobs] = field(Logprobs, default=None, merge=NESTED)
    # custom fields
    error: Optional[ResponseError] = field(ResponseError, default=None)
    model: Optional[str] = field(str, default=None)
    model_index: Optional[int] = field(int, default=None)
    completion_metadata: Optional[CompletionMetadata] = field(
        CompletionMetadata, default=None, merge=NESTED
    )

    def has_finish_reason_or_usage(self) -> bool:
        return self.finish_reason is not None or (
            self.completion_metadata is not None
            and self.completion_metadata.usage is not None
        )


class ChatCompletionChunk(Struct):
    id: str = field(str, merge=KEEP)
    choices: list = field(
        List(StreamingChoice), default_factory=list, merge=KEYED,
        skip_if_none=False, required=True
    )
    created: int = field(int, default=0, merge=KEEP, skip_if_none=False, required=True)
    model: str = field(str, default="", merge=KEEP, skip_if_none=False, required=True)
    object: str = field(
        Const("chat.completion.chunk"), default="chat.completion.chunk", merge=KEEP
    )
    usage: Optional[Usage] = field(Usage, default=None, merge=NESTED)

    def clone_without_choices(self) -> "ChatCompletionChunk":
        clone = self.clone()
        clone.choices = []
        return clone


class UnaryChoice(Struct):
    message: Message = field(Message)
    finish_reason: str = field(
        FINISH_REASON, default=FINISH_REASON_DEFAULT, skip_if_none=False
    )
    index: int = field(int, default=0, skip_if_none=False)
    logprobs: Optional[Logprobs] = field(Logprobs, default=None, skip_if_none=False)
    # custom fields
    error: Optional[ResponseError] = field(ResponseError, default=None, skip_if_none=False)
    model: Optional[str] = field(str, default=None, skip_if_none=False)
    model_index: Optional[int] = field(int, default=None, skip_if_none=False)
    completion_metadata: Optional[CompletionMetadata] = field(
        CompletionMetadata, default=None, skip_if_none=False
    )

    @classmethod
    def from_streaming(cls, choice: StreamingChoice) -> "UnaryChoice":
        return cls(
            message=Message.from_delta(choice.delta),
            finish_reason=choice.finish_reason or FINISH_REASON_DEFAULT,
            index=choice.index,
            logprobs=choice.logprobs,
            error=choice.error,
            model=choice.model,
            model_index=choice.model_index,
            completion_metadata=choice.completion_metadata,
        )


class ChatCompletion(Struct):
    id: str = field(str, default="")
    choices: list = field(List(UnaryChoice), default_factory=list, skip_if_none=False)
    created: int = field(int, default=0, skip_if_none=False)
    model: str = field(str, default="", skip_if_none=False)
    object: str = field(Const("chat.completion"), default="chat.completion")
    usage: Optional[Usage] = field(Usage, default=None)
    # wire extension (no reference analog — the reference has no multichat
    # client): the unary view of the streaming ``multichat.consensus``
    # frames, {slot: confidence} over finished candidates, present when the
    # request set ``consensus: true`` and the gateway has an embedder
    consensus: Optional[dict] = field(Map(float), default=None)

    @classmethod
    def from_streaming(cls, chunk: ChatCompletionChunk) -> "ChatCompletion":
        return cls(
            id=chunk.id,
            choices=[UnaryChoice.from_streaming(c) for c in chunk.choices],
            created=chunk.created,
            model=chunk.model,
            object="chat.completion",
            usage=chunk.usage,
        )
