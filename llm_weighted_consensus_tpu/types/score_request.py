"""Score completion request types.

Parity target: reference src/score/completions/request.rs (128 LoC) — messages
+ ``model`` (22-char id | author-prefixed slug | inline JSON | structured
body) + >=2 ``choices``; the choice union covers plain text, archived
chat/score/multichat completion references, and raw chat messages.
"""

from __future__ import annotations

from typing import Optional

from .base import Lazy, List, Struct, TaggedUnion, Union, field
from .chat_request import (
    MESSAGE,
    SERVICE_TIER,
    StreamOptions,
    Tool,
    UsageInclude,
)
from .chat_response import Message as ChatResponseMessage


class ChatCompletionChoiceRef(Struct):
    """Archived chat completion choice reference (request.rs:70-76)."""

    id: str = field(str)
    choice_index: int = field(int, default=0)


class ScoreCompletionChoiceRef(Struct):
    id: str = field(str)
    choice_index: int = field(int, default=0)


class MultichatCompletionChoiceRef(Struct):
    id: str = field(str)
    choice_index: int = field(int, default=0)


ARCHIVE_CHOICE_REF = TaggedUnion(
    "type",
    {
        "chat_completion": ChatCompletionChoiceRef,
        "score_completion": ScoreCompletionChoiceRef,
        "multichat_completion": MultichatCompletionChoiceRef,
    },
)

# Choice = text | archived completion ref | raw chat response message
# (untagged; declaration order mirrors request.rs:68-91)
CHOICE = Union(str, ARCHIVE_CHOICE_REF, ChatResponseMessage)


def _model_spec():
    # Model = Id(String) | Provided(ModelBase) — untagged (request.rs:42-47).
    from ..identity.model import ModelBase

    return Union(str, ModelBase)


MODEL = Lazy(_model_spec)


class ChatCompletionCreateParams(Struct):
    messages: list = field(List(MESSAGE))
    model: object = field(MODEL)
    seed: Optional[int] = field(int, default=None)
    service_tier: Optional[str] = field(SERVICE_TIER, default=None)
    stream: Optional[bool] = field(bool, default=None)
    stream_options: Optional[StreamOptions] = field(StreamOptions, default=None)
    tools: Optional[list] = field(List(Tool), default=None)  # readonly passthrough
    # openrouter fields
    usage: Optional[UsageInclude] = field(UsageInclude, default=None)
    # custom fields
    choices: list = field(List(CHOICE), default_factory=list, skip_if_none=False)
    # opt out of the consensus result cache for this request (cache/);
    # non-semantic: never part of the request fingerprint
    cache_bypass: Optional[bool] = field(bool, default=None)

    def template_content(self) -> str:
        return "\n".join(m.template_content() for m in self.messages)
