"""Embedding wire types (OpenAI ``CreateEmbeddingResponse`` shape).

Parity target: reference src/embeddings/response.rs:4-30 — types only in the
reference; this framework implements the request side and a real on-TPU
encoder behind them (models/encoder.py, serve/gateway.py ``/embeddings``).
"""

from __future__ import annotations

from typing import Optional

from .base import Const, List, Struct, Union, field
from .chat_response import Usage


class Embedding(Struct):
    embedding: list = field(List(float))
    index: int = field(int, default=0, skip_if_none=False)
    object: str = field(Const("embedding"), default="embedding")


class CreateEmbeddingResponse(Struct):
    data: list = field(List(Embedding), default_factory=list, skip_if_none=False)
    model: str = field(str, default="", skip_if_none=False)
    object: str = field(Const("list"), default="list")
    usage: Optional[Usage] = field(Usage, default=None)


class CreateEmbeddingParams(Struct):
    """Request side (not present in the reference crate; OpenAI-compatible)."""

    input: object = field(Union(str, List(str)))
    model: str = field(str)
    encoding_format: Optional[str] = field(str, default=None)
    dimensions: Optional[int] = field(int, default=None)
    user: Optional[str] = field(str, default=None)

    def inputs(self) -> list:
        return [self.input] if isinstance(self.input, str) else list(self.input)
