"""Score completion response types — chat chunks extended with consensus data.

Parity target: reference src/score/completions/response.rs (385 LoC).  Choices
carry per-candidate ``weight``/``confidence``, per-judge ``error``/``model``
(judge id)/``model_index``/``completion_metadata``; the delta additionally
carries the judge's ``vote`` vector and the chunk the ``weight_data`` evidence.
This shape IS the product contract (SURVEY §2.1).
"""

from __future__ import annotations

from decimal import Decimal
from typing import Optional

from .base import ResponseError
from .base import (
    Const,
    EXTEND,
    FIRST,
    KEEP,
    KEYED,
    List,
    NESTED,
    Struct,
    TaggedUnion,
    field,
)
from .chat_response import (
    Annotation,
    Audio,
    Delta as ChatDelta,
    FINISH_REASON,
    FINISH_REASON_DEFAULT,
    Image,
    Logprobs,
    Message as ChatMessage,
    SERVICE_TIER,
    StreamingToolCall,
    UnaryToolCall,
    Usage,
)
from .embeddings import CreateEmbeddingResponse


# ---------------------------------------------------------------------------
# Weight data evidence (reference src/score/completions/weight.rs:5-18)
# ---------------------------------------------------------------------------


class StaticData(Struct):
    pass


class TrainingTableData(Struct):
    embeddings_response: CreateEmbeddingResponse = field(CreateEmbeddingResponse)


WEIGHT_DATA = TaggedUnion(
    "type", {"static": StaticData, "training_table": TrainingTableData}
)


# ---------------------------------------------------------------------------
# Completion metadata (response.rs:326-385)
# ---------------------------------------------------------------------------


class CompletionMetadata(Struct):
    id: str = field(str, default="", merge=KEEP, skip_if_none=False)
    created: int = field(int, default=0, merge=KEEP, skip_if_none=False)
    model: str = field(str, default="", merge=KEEP, skip_if_none=False)
    service_tier: Optional[str] = field(SERVICE_TIER, default=None)
    system_fingerprint: Optional[str] = field(str, default=None)
    usage: Optional[Usage] = field(Usage, default=None, merge=NESTED)
    provider: Optional[str] = field(str, default=None)


# ---------------------------------------------------------------------------
# Streaming side
# ---------------------------------------------------------------------------


class Delta(Struct):
    """Chat delta flattened + the judge's ``vote`` vector (response.rs:184-199).

    The reference flattens the chat delta via serde ``#[serde(flatten)]``; we
    inline the same fields plus ``vote``.
    """

    content: Optional[str] = field(str, default=None, merge="concat")
    refusal: Optional[str] = field(str, default=None, merge="concat")
    role: Optional[str] = field(Const("assistant"), default=None)
    tool_calls: Optional[list] = field(
        List(StreamingToolCall),
        default=None,
        merge=KEYED,
        key="index",
    )
    reasoning: Optional[str] = field(str, default=None, merge="concat")
    images: Optional[list] = field(
        List(Image),
        default=None,
        merge=EXTEND,
    )
    vote: Optional[list] = field(List(Decimal), default=None)

    @classmethod
    def from_chat(cls, delta: ChatDelta, vote=None) -> "Delta":
        return cls(
            content=delta.content,
            refusal=delta.refusal,
            role=delta.role,
            tool_calls=delta.tool_calls,
            reasoning=delta.reasoning,
            images=delta.images,
            vote=vote,
        )

    def inner(self) -> ChatDelta:
        return ChatDelta(
            content=self.content,
            refusal=self.refusal,
            role=self.role,
            tool_calls=self.tool_calls,
            reasoning=self.reasoning,
            images=self.images,
        )

    def tool_as_content(self) -> None:
        if self.tool_calls is None:
            return
        tool_calls, self.tool_calls = self.tool_calls, None
        for tool_call in tool_calls:
            if tool_call.function is not None and tool_call.function.arguments is not None:
                if self.content is None:
                    self.content = tool_call.function.arguments
                else:
                    self.content += tool_call.function.arguments


class StreamingChoice(Struct):
    delta: Delta = field(Delta, default_factory=Delta, merge=NESTED)
    finish_reason: Optional[str] = field(FINISH_REASON, default=None, skip_if_none=False)
    index: int = field(int, default=0, merge=KEEP, skip_if_none=False)
    logprobs: Optional[Logprobs] = field(Logprobs, default=None, merge=NESTED)
    # custom fields
    weight: Optional[Decimal] = field(Decimal, default=None)
    confidence: Optional[Decimal] = field(Decimal, default=None)
    error: Optional[ResponseError] = field(ResponseError, default=None)
    model: Optional[str] = field(str, default=None)
    model_index: Optional[int] = field(int, default=None)
    completion_metadata: Optional[CompletionMetadata] = field(
        CompletionMetadata, default=None, merge=NESTED
    )

    def tool_as_content(self) -> None:
        if self.finish_reason == "tool_calls":
            self.finish_reason = "stop"
        self.delta.tool_as_content()

    def has_finish_reason_or_usage(self) -> bool:
        return self.finish_reason is not None or (
            self.completion_metadata is not None
            and self.completion_metadata.usage is not None
        )


class ChatCompletionChunk(Struct):
    id: str = field(str, merge=KEEP)
    choices: list = field(
        List(StreamingChoice), default_factory=list, merge=KEYED,
        skip_if_none=False, required=True
    )
    created: int = field(int, default=0, merge=KEEP, skip_if_none=False, required=True)
    model: str = field(str, default="", merge=KEEP, skip_if_none=False, required=True)
    object: str = field(
        Const("chat.completion.chunk"), default="chat.completion.chunk", merge=KEEP
    )
    usage: Optional[Usage] = field(Usage, default=None, merge=NESTED)
    # custom field
    weight_data: object = field(WEIGHT_DATA, default=None)
    # set (true) on the final aggregate frame when the consensus shipped
    # without the full panel — weight-quorum early exit or deadline expiry
    # with a partial panel; absent entirely from healthy responses
    degraded: Optional[bool] = field(bool, default=None, merge=KEEP)
    # set on the final aggregate frame when the request is traced: the key
    # into GET /v1/traces/{trace_id} for the consensus explain record.
    # Absent on untraced requests and on cache replays (the recording
    # strips the leader's id — see cache/store.py).  FIRST, not KEEP, so
    # fold_chunks carries it from the final frame into the unary fold.
    trace_id: Optional[str] = field(str, default=None, merge=FIRST)

    def tool_as_content(self) -> None:
        for choice in self.choices:
            choice.tool_as_content()

    def clone_without_choices(self) -> "ChatCompletionChunk":
        clone = self.clone()
        clone.choices = []
        return clone


# ---------------------------------------------------------------------------
# Unary side
# ---------------------------------------------------------------------------


class UnaryMessage(Struct):
    """Chat unary message flattened + ``vote`` (response.rs:301-320)."""

    content: Optional[str] = field(str, default=None, skip_if_none=False)
    refusal: Optional[str] = field(str, default=None, skip_if_none=False)
    role: str = field(Const("assistant"), default="assistant", skip_if_none=False)
    annotations: Optional[list] = field(
        List(Annotation),
        default=None,
    )
    audio: Optional[object] = field(
        Audio,
        default=None,
    )
    tool_calls: Optional[list] = field(
        List(UnaryToolCall),
        default=None,
    )
    reasoning: Optional[str] = field(str, default=None)
    images: Optional[list] = field(
        List(Image),
        default=None,
    )
    vote: Optional[list] = field(List(Decimal), default=None, skip_if_none=False)

    @classmethod
    def from_delta(cls, delta: Delta) -> "UnaryMessage":
        chat_msg = ChatMessage.from_delta(delta.inner())
        return cls(
            content=chat_msg.content,
            refusal=chat_msg.refusal,
            role=chat_msg.role,
            annotations=chat_msg.annotations,
            audio=chat_msg.audio,
            tool_calls=chat_msg.tool_calls,
            reasoning=chat_msg.reasoning,
            images=chat_msg.images,
            vote=delta.vote,
        )

    def inner(self) -> ChatMessage:
        return ChatMessage(
            content=self.content,
            refusal=self.refusal,
            role=self.role,
            annotations=self.annotations,
            audio=self.audio,
            tool_calls=self.tool_calls,
            reasoning=self.reasoning,
            images=self.images,
        )


class UnaryChoice(Struct):
    message: UnaryMessage = field(UnaryMessage)
    finish_reason: str = field(
        FINISH_REASON, default=FINISH_REASON_DEFAULT, skip_if_none=False
    )
    index: int = field(int, default=0, skip_if_none=False)
    logprobs: Optional[Logprobs] = field(Logprobs, default=None, skip_if_none=False)
    # custom fields
    weight: Optional[Decimal] = field(Decimal, default=None, skip_if_none=False)
    confidence: Optional[Decimal] = field(Decimal, default=None, skip_if_none=False)
    error: Optional[ResponseError] = field(ResponseError, default=None, skip_if_none=False)
    model: Optional[str] = field(str, default=None, skip_if_none=False)
    model_index: Optional[int] = field(int, default=None, skip_if_none=False)
    completion_metadata: Optional[CompletionMetadata] = field(
        CompletionMetadata, default=None, skip_if_none=False
    )

    @classmethod
    def from_streaming(cls, choice: StreamingChoice) -> "UnaryChoice":
        return cls(
            message=UnaryMessage.from_delta(choice.delta),
            finish_reason=choice.finish_reason or FINISH_REASON_DEFAULT,
            index=choice.index,
            logprobs=choice.logprobs,
            weight=choice.weight,
            confidence=choice.confidence,
            error=choice.error,
            model=choice.model,
            model_index=choice.model_index,
            completion_metadata=choice.completion_metadata,
        )


class ChatCompletion(Struct):
    id: str = field(str, default="")
    choices: list = field(List(UnaryChoice), default_factory=list, skip_if_none=False)
    created: int = field(int, default=0, skip_if_none=False)
    model: str = field(str, default="", skip_if_none=False)
    object: str = field(Const("chat.completion"), default="chat.completion")
    usage: Optional[Usage] = field(Usage, default=None)
    # custom field
    weight_data: object = field(WEIGHT_DATA, default=None, skip_if_none=False)
    degraded: Optional[bool] = field(bool, default=None)
    trace_id: Optional[str] = field(str, default=None)

    @classmethod
    def from_streaming(cls, chunk: ChatCompletionChunk) -> "ChatCompletion":
        return cls(
            id=chunk.id,
            choices=[UnaryChoice.from_streaming(c) for c in chunk.choices],
            created=chunk.created,
            model=chunk.model,
            object="chat.completion",
            usage=chunk.usage,
            weight_data=chunk.weight_data,
            degraded=chunk.degraded,
            trace_id=chunk.trace_id,
        )
