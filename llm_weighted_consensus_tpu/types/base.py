"""Declarative wire-type system with a generic streaming merge algebra.

The reference implements every response type as a serde struct with a hand
written ``push`` merge (reference: src/chat/completions/response.rs:23-302 and
the same pattern at score/multichat level).  The merge rules form a small
algebra:

* strings concatenate,
* numeric totals add,
* optionals are first-write-wins,
* keyed lists (choices by ``index``, tool calls by ``index``) merge per key,
* plain lists extend,
* nested structs recurse.

Instead of hand-writing ~30 ``push`` implementations we declare each struct's
fields once with a merge strategy and derive ``push``/``to_json_obj``/
``from_json_obj`` generically.  ``fold(push, chunks) == unary`` then holds by
construction and is property-tested in tests/test_merge_algebra.py.

This module is pure Python (no IO, no JAX) and is safe to import anywhere —
the analog of the reference's wasm-safe core (src/main.rs:242-243).
"""

from __future__ import annotations

import dataclasses
import sys
from decimal import Decimal
from typing import Any, Callable, Optional

from ..utils import jsonutil

MISSING = dataclasses.MISSING


class SchemaError(ValueError):
    """Raised when a JSON payload does not match the declared schema."""

    def __init__(self, path: str, message: str):
        self.path = path
        super().__init__(f"{path}: {message}" if path else message)


# ---------------------------------------------------------------------------
# Field specs
# ---------------------------------------------------------------------------

# Merge strategies (the `push` algebra):
FIRST = "first"      # first-write-wins (Option<T> semantics)
CONCAT = "concat"    # string concatenation
ADD = "add"          # numeric addition (int / Decimal)
EXTEND = "extend"    # list concatenation
KEYED = "keyed"      # list merged per-element by a key field (default "index")
NESTED = "nested"    # recurse into nested Struct.push
KEEP = "keep"        # never overwritten by pushes (id/created/model/object)


def field(
    spec,
    *,
    default=MISSING,
    default_factory=MISSING,
    merge: str = FIRST,
    skip_if_none: bool = True,
    key: str = "index",
    json_name: Optional[str] = None,
    required: bool = False,
):
    """Declare a struct field.

    ``spec`` describes the JSON codec for the value (see the spec mini-language
    below).  ``merge`` picks the push strategy.  ``skip_if_none`` mirrors
    serde's ``skip_serializing_if = "Option::is_none"``.  ``required=True``
    makes the field mandatory on parse even when a Python-side construction
    default exists (serde has no ``#[serde(default)]`` on it).
    """
    metadata = {
        "spec": spec,
        "merge": merge,
        "skip_if_none": skip_if_none,
        "key": key,
        "json_name": json_name,
        "required": required,
    }
    kwargs: dict[str, Any] = {"metadata": metadata}
    if default is not MISSING:
        kwargs["default"] = default
    if default_factory is not MISSING:
        kwargs["default_factory"] = default_factory
    return dataclasses.field(**kwargs)


# --- spec mini-language -----------------------------------------------------
#
# A spec is one of:
#   str / int / bool / float / Decimal  - scalar codecs
#   RAW                                 - passthrough JSON value
#   a Struct subclass                   - nested struct
#   List(spec)                          - homogeneous array
#   Map(spec)                           - string-keyed object (order-preserving)
#   Union(...)                          - untagged union, first parse wins
#   Enum(*values)                       - closed set of strings
#   Const(value)                        - fixed string (unit enum variants like
#                                         "chat.completion.chunk")

RAW = object()


class List:
    def __init__(self, spec):
        self.spec = spec


class Map:
    def __init__(self, spec):
        self.spec = spec


class Union:
    """Untagged union; parse attempts run in declaration order.

    Mirrors serde's ``#[serde(untagged)]``; order matters exactly the way
    variant order matters in the reference enums.
    """

    def __init__(self, *specs):
        self.specs = specs


class Enum:
    def __init__(self, *values: str):
        self.values = values


class Const:
    def __init__(self, value: str):
        self.value = value


class Lazy:
    """Spec resolved on first use — breaks import cycles (e.g. score request's
    ``model`` field referencing identity.ModelBase)."""

    def __init__(self, thunk: Callable):
        self.thunk = thunk
        self._spec = None

    def spec(self):
        if self._spec is None:
            self._spec = self.thunk()
        return self._spec


class TaggedUnion:
    """Internally tagged union (serde ``#[serde(tag = "...")]``).

    ``variants`` maps tag value -> Struct subclass.  The tag is injected /
    stripped during serialization.  Used for the ``Message`` role tree and
    rich content parts.
    """

    def __init__(self, tag: str, variants: dict):
        self.tag = tag
        self.variants = variants


def _decode(spec, obj, path: str):
    if isinstance(spec, Lazy):
        spec = spec.spec()
    if spec is RAW:
        return obj
    if spec is str:
        if not isinstance(obj, str):
            raise SchemaError(path, f"expected string, got {type(obj).__name__}")
        return obj
    if spec is bool:
        if not isinstance(obj, bool):
            raise SchemaError(path, f"expected bool, got {type(obj).__name__}")
        return obj
    if spec is int:
        if isinstance(obj, bool) or not isinstance(obj, int):
            raise SchemaError(path, f"expected integer, got {type(obj).__name__}")
        return obj
    if spec is float:
        if isinstance(obj, bool) or not isinstance(obj, (int, float, Decimal)):
            raise SchemaError(path, f"expected number, got {type(obj).__name__}")
        return float(obj)
    if spec is Decimal:
        if isinstance(obj, bool) or not isinstance(obj, (int, float, Decimal)):
            raise SchemaError(path, f"expected number, got {type(obj).__name__}")
        return obj if isinstance(obj, Decimal) else Decimal(str(obj))
    if isinstance(spec, Const):
        if obj != spec.value:
            raise SchemaError(path, f"expected {spec.value!r}, got {obj!r}")
        return obj
    if isinstance(spec, Enum):
        if obj not in spec.values:
            raise SchemaError(path, f"expected one of {spec.values}, got {obj!r}")
        return obj
    if isinstance(spec, List):
        if not isinstance(obj, list):
            raise SchemaError(path, f"expected array, got {type(obj).__name__}")
        return [_decode(spec.spec, v, f"{path}[{i}]") for i, v in enumerate(obj)]
    if isinstance(spec, Map):
        if not isinstance(obj, dict):
            raise SchemaError(path, f"expected object, got {type(obj).__name__}")
        return {k: _decode(spec.spec, v, f"{path}.{k}") for k, v in obj.items()}
    if isinstance(spec, Union):
        errors = []
        for sub in spec.specs:
            try:
                return _decode(sub, obj, path)
            except SchemaError as e:
                errors.append(str(e))
        raise SchemaError(path, "no union variant matched: " + "; ".join(errors))
    if isinstance(spec, TaggedUnion):
        if not isinstance(obj, dict):
            raise SchemaError(path, f"expected object, got {type(obj).__name__}")
        tag = obj.get(spec.tag)
        cls = spec.variants.get(tag)
        if cls is None:
            raise SchemaError(
                path, f"unknown {spec.tag} {tag!r} (expected one of {list(spec.variants)})"
            )
        rest = {k: v for k, v in obj.items() if k != spec.tag}
        return cls.from_json_obj(rest, path=path)
    if isinstance(spec, type) and issubclass(spec, Struct):
        return spec.from_json_obj(obj, path=path)
    raise TypeError(f"bad field spec {spec!r}")


def _encode(spec, value):
    if isinstance(spec, Lazy):
        spec = spec.spec()
    if value is None:
        return None
    if spec is RAW or spec in (str, bool, int, float, Decimal):
        return value
    if isinstance(spec, (Const, Enum)):
        return value
    if isinstance(spec, List):
        return [_encode(spec.spec, v) for v in value]
    if isinstance(spec, Map):
        return {k: _encode(spec.spec, v) for k, v in value.items()}
    if isinstance(spec, Union):
        # runtime type decides the encoding (first matching variant wins,
        # mirroring serde untagged serialization by variant type)
        for sub in spec.specs:
            if _spec_matches(sub, value):
                return _encode(sub, value)
        return _encode_dynamic(value)
    if isinstance(spec, TaggedUnion):
        for tag, cls in spec.variants.items():
            if type(value) is cls:
                obj = value.to_json_obj()
                return {spec.tag: tag, **obj}
        raise TypeError(f"value {type(value)!r} not a member of tagged union")
    if isinstance(spec, type) and issubclass(spec, Struct):
        return value.to_json_obj()
    raise TypeError(f"bad field spec {spec!r}")


def _spec_matches(spec, value) -> bool:
    """Best-effort runtime check that ``value`` belongs to ``spec``."""
    if isinstance(spec, Lazy):
        spec = spec.spec()
    if spec is RAW:
        return True
    if spec is str:
        return isinstance(value, str)
    if spec is bool:
        return isinstance(value, bool)
    if spec is int:
        return isinstance(value, int) and not isinstance(value, bool)
    if spec in (float, Decimal):
        return isinstance(value, (int, float, Decimal)) and not isinstance(value, bool)
    if isinstance(spec, Const):
        return value == spec.value
    if isinstance(spec, Enum):
        return value in spec.values
    if isinstance(spec, List):
        return isinstance(value, list)
    if isinstance(spec, Map):
        return isinstance(value, dict)
    if isinstance(spec, Union):
        return any(_spec_matches(sub, value) for sub in spec.specs)
    if isinstance(spec, TaggedUnion):
        return any(type(value) is cls for cls in spec.variants.values())
    if isinstance(spec, type) and issubclass(spec, Struct):
        return isinstance(value, spec)
    return False


def _encode_dynamic(value):
    if isinstance(value, Struct):
        return value.to_json_obj()
    if isinstance(value, list):
        return [_encode_dynamic(v) for v in value]
    if isinstance(value, dict):
        return {k: _encode_dynamic(v) for k, v in value.items()}
    return value


# ---------------------------------------------------------------------------
# Struct base
# ---------------------------------------------------------------------------


def _class_plan(cls, attr: str, build):
    """Per-class cache stored on the class itself (``cls.__dict__`` probe,
    NOT getattr: a subclass must not inherit its base's plan), so plans
    are garbage-collected with their class and cost one dict lookup per
    call.  Field specs are frozen at class-definition time (everything
    here re-derives what the hot methods used to pull from
    ``dataclasses.fields`` metadata on every call — mappingproxy lookups
    measured as a top host cost in a profiled scored request; push/clone/
    to_json_obj run per chunk per judge)."""
    plan = cls.__dict__.get(attr)
    if plan is None:
        plan = build(cls)
        setattr(cls, attr, plan)
    return plan


def _build_names(cls):
    return tuple(f.name for f in dataclasses.fields(cls))


def _build_push(cls):
    return tuple(
        (
            f.name,
            f.metadata.get("merge", FIRST),
            f.metadata.get("key", "index"),
        )
        for f in dataclasses.fields(cls)
    )


def _speccless_error(cls, name):
    return TypeError(
        f"{cls.__name__}.{name} was declared without the field() "
        "helper (no codec spec in metadata) — it can be pushed/cloned "
        "but not (de)serialized"
    )


def _build_encode(cls):
    # a spec-less field (declared without the field() helper — push/clone-
    # only state) stays in the plan with a None spec sentinel: encoding is
    # fine while its value is None (nothing to emit), and raises the
    # declaration error only when a real value would need a codec.
    # Raising at plan-build time instead would poison to_json_obj for the
    # WHOLE class the first time any instance serialized, even if the
    # spec-less field was never set.
    return tuple(
        (
            f.name,
            f.metadata.get("json_name") or f.name,
            f.metadata.get("skip_if_none", True),
            f.metadata.get("spec"),
        )
        for f in dataclasses.fields(cls)
    )


def _build_decode(cls):
    # spec-less fields are excluded outright: incoming JSON can't target
    # them (no json name contract), so they simply keep their default
    return tuple(
        (
            f.name,
            f.metadata.get("json_name") or f.name,
            f.metadata["spec"],
            bool(f.metadata.get("required"))
            or (
                f.default is dataclasses.MISSING
                and f.default_factory is dataclasses.MISSING
            ),
        )
        for f in dataclasses.fields(cls)
        if "spec" in f.metadata
    )


class Struct:
    """Base for all wire types; subclasses are auto-dataclassed."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        dataclasses.dataclass(cls)

    # -- serialization ------------------------------------------------------

    def to_json_obj(self) -> dict:
        out: dict[str, Any] = {}
        encode_plan = _class_plan(type(self), "_lwc_encode_plan", _build_encode)
        for attr, name, skip_if_none, spec in encode_plan:
            value = getattr(self, attr)
            if value is None and skip_if_none:
                continue
            if spec is None:
                # spec-less (push/clone-only) field holding a real value:
                # there is no codec to render it with — refuse loudly
                # instead of emitting something json.dumps will mangle
                raise _speccless_error(type(self), attr)
            out[name] = _encode(spec, value)
        return out

    def to_json(self, *, pretty: bool = False) -> str:
        return jsonutil.dumps(self.to_json_obj(), pretty=pretty)

    @classmethod
    def from_json_obj(cls, obj, *, path: str = ""):
        if not isinstance(obj, dict):
            raise SchemaError(path, f"expected object, got {type(obj).__name__}")
        kwargs = {}
        # unknown JSON fields are ignored, matching serde's default behavior
        decode_plan = _class_plan(cls, "_lwc_decode_plan", _build_decode)
        for attr, name, spec, required in decode_plan:
            if name in obj and obj[name] is not None:
                sub_path = f"{path}.{name}" if path else name
                kwargs[attr] = _decode(spec, obj[name], sub_path)
            elif required:
                sub_path = f"{path}.{name}" if path else name
                raise SchemaError(sub_path, "missing required field")
            # else: default applies
        return cls(**kwargs)

    @classmethod
    def from_json(cls, s: str):
        return cls.from_json_obj(jsonutil.loads(s))

    # -- merge algebra ------------------------------------------------------

    def push(self, other) -> None:
        """Merge ``other`` (a later streaming chunk) into ``self`` in place."""
        if type(other) is not type(self):
            raise TypeError(
                f"cannot push {type(other).__name__} into {type(self).__name__}"
            )
        push_plan = _class_plan(type(self), "_lwc_push_plan", _build_push)
        for name, strategy, key in push_plan:
            if strategy == KEEP:
                continue
            mine = getattr(self, name)
            theirs = getattr(other, name)
            if theirs is None:
                continue
            if mine is None:
                setattr(self, name, _clone(theirs))
                continue
            if strategy == FIRST:
                pass  # first write wins
            elif strategy == CONCAT:
                setattr(self, name, mine + theirs)
            elif strategy == ADD:
                setattr(self, name, mine + theirs)
            elif strategy == EXTEND:
                mine.extend(_clone(v) for v in theirs)
            elif strategy == NESTED:
                mine.push(theirs)
            elif strategy == KEYED:
                _push_keyed(mine, theirs, key)
            else:
                raise ValueError(f"unknown merge strategy {strategy!r}")

    def clone(self):
        return _clone(self)


def _push_keyed(mine: list, theirs: list, key: str) -> None:
    # Linear scan matches the reference exactly (choices lists are small);
    # reference: src/chat/completions/response.rs:56-78.
    for other in theirs:
        other_key = getattr(other, key)
        for item in mine:
            if getattr(item, key) == other_key:
                item.push(other)
                break
        else:
            mine.append(_clone(other))


def _clone(value):
    # exact-class checks first: the overwhelmingly common case is a leaf
    # (str/int/Decimal/None), which should fall through with two pointer
    # compares instead of three isinstance() calls (this function is the
    # top host cost of a profiled scored request — per-judge isolation
    # clones run per judge per chunk)
    cls = value.__class__
    if cls is list:
        return [_clone(v) for v in value]
    if cls is dict:
        return {k: _clone(v) for k, v in value.items()}
    if isinstance(value, Struct):
        return cls(
            **{
                name: _clone(getattr(value, name))
                for name in _class_plan(cls, "_lwc_field_names", _build_names)
            }
        )
    # subclasses of the containers (rare; exact classes took the fast path)
    if isinstance(value, list):
        return [_clone(v) for v in value]
    if isinstance(value, dict):
        return {k: _clone(v) for k, v in value.items()}
    return value


class ResponseError(Struct, Exception):
    """Wire-form ``{code, message}`` error (reference src/error.rs:8-13).

    Lives in the type core (rather than errors.py) because response types
    embed it as a field; errors.py re-exports it alongside the rich error
    taxonomy.
    """

    code: int = field(int)
    message: object = field(RAW, default=None, skip_if_none=False)

    def __post_init__(self):
        Exception.__init__(self, self.to_json())


# ---------------------------------------------------------------------------
# Splice serialization (the HOST_FASTPATH fast lane's codec)
# ---------------------------------------------------------------------------
#
# The slow path renders a streamed frame in two walks: ``to_json_obj``
# builds a dict tree, then ``jsonutil.dumps`` walks the tree into a
# string.  The splice plan compiled here precomputes, per struct class,
# a writer closure per field plus the static text around every splice
# point (``,"confidence":`` and the surrounding punctuation), so a frame
# is assembled in ONE walk straight into string segments joined and
# encoded once — and a per-stream ``SpliceEncoder`` additionally caches
# each nested value's rendered text, so a chunk whose choice metadata
# did not change since the previous chunk splices the cached segment
# back in and re-renders only the fields that changed (O(changed bytes),
# not O(frame)).
#
# Byte-identity contract: for every struct the splicer accepts,
# ``SpliceEncoder().encode(s) == jsonutil.dumps(s.to_json_obj())
# .encode("utf-8")``.  Leaves format through jsonutil's own scalar
# tokens, dynamic subtrees (unions, maps, RAW) are rendered by jsonutil
# itself on the encoded subtree, and anything the splicer cannot prove
# identical raises — callers (serve/frames.py) fall back to the slow
# path for that frame, never silently diverge.  Property-tested against
# the slow path in tests/test_host_fastpath.py.
#
# Aliasing contract: cache entries hold the encoded values BY REFERENCE
# (cloning every cached subtree costs more than the splice saves).
# That is safe here because the stream engine never mutates a chunk
# after yielding it — the aggregate is a *clone* of the initial chunk
# and ``push`` clones on insert — and the encoder is per-stream,
# dropped with the stream.  Mutating a struct after encoding it on the
# same encoder voids the byte-identity guarantee.


# Per-stream Decimal token memo, activated for the duration of one
# ``SpliceEncoder.encode`` call (encoding is synchronous and the encoder
# is single-stream by contract, so a module slot is safe on the serving
# event loop).  Streamed frames repeat a handful of Decimal OBJECTS —
# hard ballots share one zero per row, the tally memoizes repeated sums
# and shares — so formatting is keyed by object id; entries pin the
# value (the tuple holds the reference), which makes id reuse for a
# *different* live Decimal impossible.
_dec_memo: "dict | None" = None


def _splice_scalar(
    value,
    node,
    slot,
    out,
    _esc=jsonutil._escape_string,
    _fmt_dec=jsonutil._format_decimal,
    _int_repr=int.__repr__,
    _token=jsonutil.scalar_token,
):
    # exact-class fast paths for the tokens streamed frames are made of
    # (content strings, Decimal weights, integer indexes/timestamps);
    # everything else goes through the writer's scalar dispatch.  Note
    # ``cls is int`` cannot match bool — bool's class is bool, and the
    # dispatch fallback emits true/false for it.
    cls = value.__class__
    if cls is str:
        out.append(_esc(value))
    elif cls is Decimal:
        memo = _dec_memo
        if memo is None:
            out.append(_fmt_dec(value))
        else:
            hit = memo.get(id(value))
            if hit is None:
                memo[id(value)] = hit = (value, _fmt_dec(value))
            out.append(hit[1])
    elif cls is int:
        out.append(_int_repr(value))
    else:
        token = _token(value)
        if token is None:
            raise TypeError(f"cannot splice scalar {type(value).__name__}")
        out.append(token)


_MISS = object()


def _strict_eq(a, b):
    """Token-strict equality for splice-cache hit tests.  Plain ``==``
    is NOT sound here: ``Decimal("1") == Decimal("1.0")`` and
    ``True == 1`` hold while their JSON tokens differ, so a value-equal
    cache hit could replay stale bytes.  This compares the way the bytes
    would compare — identity first (the merge algebra shares objects
    across frames, so the hot path is one ``is``), then per-class rules
    that imply identical tokens.  Unknown classes return False: a
    re-render is always byte-safe, a false hit never is."""
    if a is b:
        return True
    cls = a.__class__
    if cls is not b.__class__:
        return False
    if cls is str or cls is int or cls is bool:
        return a == b
    if cls is Decimal:
        # equal value + equal exponent => same sign/digits => same token
        return a == b and a.as_tuple().exponent == b.as_tuple().exponent
    if cls is float:
        # repr IS the token; catches -0.0 == 0.0 and rejects nan
        return float.__repr__(a) == float.__repr__(b)
    if cls is list or cls is tuple:
        return len(a) == len(b) and all(map(_strict_eq, a, b))
    if cls is dict:
        if len(a) != len(b):
            return False
        for k, va in a.items():
            vb = b.get(k, _MISS)
            if vb is _MISS or not _strict_eq(va, vb):
                return False
        return True
    if isinstance(a, Struct):
        names = cls.__dict__.get("_lwc_field_names")
        if names is None:
            names = _class_plan(cls, "_lwc_field_names", _build_names)
        da, db = a.__dict__, b.__dict__
        for name in names:
            if not _strict_eq(da[name], db[name]):
                return False
        return True
    return False


def _splice_cached_struct(value, node, slot, out):
    """Nested struct behind a whole-value text cache: an unchanged value
    (token-strict compare, see _strict_eq) splices its previous
    rendering back in without re-walking.  A miss renders straight into
    ``out`` — only the cache copy pays a join."""
    if node is None:
        _splice_struct(value, None, out)
        return
    entry = node.get(slot)
    if entry is not None and _strict_eq(entry[0], value):
        out.append(entry[1])
        return
    child = entry[2] if entry is not None else {}
    start = len(out)
    _splice_struct(value, child, out)
    node[slot] = (value, "".join(out[start:]), child)


def _splice_value_writer(spec, merge, keyfield):
    """The writer closure for one field spec: ``write(value, node, slot,
    out)`` appends the value's JSON text segments to ``out``.  ``value``
    is never None — the field loop and the list writer handle null."""
    if isinstance(spec, Lazy):
        # resolved once at plan-build time (first encode of the class;
        # every lazily-referenced class exists by then)
        spec = spec.spec()
    if spec is RAW or isinstance(spec, (Union, TaggedUnion, Map)):

        def write_dynamic(value, node, slot, out, _spec=spec):
            # dynamic subtree: byte-identity by composition — jsonutil
            # renders the encoded subtree exactly as the slow path would
            out.append(jsonutil.dumps(_encode(_spec, value)))

        return write_dynamic
    if isinstance(spec, type) and issubclass(spec, Struct):
        return _splice_cached_struct
    if isinstance(spec, List):
        elem_spec = spec.spec
        if isinstance(elem_spec, Lazy):
            elem_spec = elem_spec.spec()
        if (
            merge == KEYED
            and isinstance(elem_spec, type)
            and issubclass(elem_spec, Struct)
        ):

            def write_keyed(value, node, slot, out, _key=keyfield):
                # per-element caches keyed the way push merges the list:
                # a choice whose fields did not change since the last
                # chunk is one equality compare + one cached segment
                if node is not None:
                    sub = node.get(slot)
                    if sub is None:
                        sub = node[slot] = {}
                else:
                    sub = None
                out.append("[")
                first = True
                for v in value:
                    if first:
                        first = False
                    else:
                        out.append(",")
                    _splice_cached_struct(v, sub, getattr(v, _key), out)
                out.append("]")

            return write_keyed
        elem_write = _splice_value_writer(elem_spec, FIRST, keyfield)
        if elem_write is _splice_scalar:

            def write_scalar_list(
                value,
                node,
                slot,
                out,
                _esc=jsonutil._escape_string,
                _fmt_dec=jsonutil._format_decimal,
                _int_repr=int.__repr__,
            ):
                # scalar elements (a judge's 64-Decimal vote vector is
                # the hot case): tokens into a local list, commas by one
                # C-level join — the generic path pays an append per
                # comma and a dispatch call per element
                if node is not None:
                    entry = node.get(slot)
                    if entry is not None and _strict_eq(entry[0], value):
                        out.append(entry[1])
                        return
                memo = _dec_memo
                parts = []
                ap = parts.append
                for v in value:
                    cls = v.__class__
                    if cls is Decimal:
                        if memo is None:
                            ap(_fmt_dec(v))
                        else:
                            hit = memo.get(id(v))
                            if hit is None:
                                memo[id(v)] = hit = (v, _fmt_dec(v))
                            ap(hit[1])
                    elif cls is str:
                        ap(_esc(v))
                    elif cls is int:
                        ap(_int_repr(v))
                    elif v is None:
                        # _encode maps None elements to None for every
                        # spec, so the slow path emits null here too
                        ap("null")
                    else:
                        sub: list = []
                        _splice_scalar(v, None, None, sub)
                        ap(sub[0])
                rendered = "[" + ",".join(parts) + "]"
                if node is not None:
                    node[slot] = (value, rendered)
                out.append(rendered)

            return write_scalar_list

        def write_list(value, node, slot, out, _elem=elem_write):
            # whole-value text cache, like nested structs: a judge's
            # vote vector rides along unchanged in every frame after its
            # final chunk, and the aggregate shares the list object, so
            # the hit test is usually one `is`
            if node is not None:
                entry = node.get(slot)
                if entry is not None and _strict_eq(entry[0], value):
                    out.append(entry[1])
                    return
            start = len(out)
            out.append("[")
            first = True
            for v in value:
                if first:
                    first = False
                else:
                    out.append(",")
                if v is None:
                    # _encode maps None elements to None for every spec,
                    # so the slow path emits null here too
                    out.append("null")
                else:
                    _elem(v, None, None, out)
            out.append("]")
            if node is not None:
                node[slot] = (value, "".join(out[start:]))

        return write_list
    # scalar specs (str/int/bool/float/Decimal/Enum/Const) format by
    # runtime type, exactly like the writer's scalar dispatch
    return _splice_scalar


_SCALAR_INLINE = """\
{i}cls_v = v.__class__
{i}if cls_v is str:
{i}    append(_esc(v))
{i}elif cls_v is Decimal:
{i}    _memo = _mod._dec_memo
{i}    if _memo is None:
{i}        append(_fmt_dec(v))
{i}    else:
{i}        _hit = _memo.get(id(v))
{i}        if _hit is None:
{i}            _memo[id(v)] = _hit = (v, _fmt_dec(v))
{i}        append(_hit[1])
{i}elif cls_v is int:
{i}    append(_int_repr(v))
{i}else:
{i}    _scalar(v, None, None, out)
"""


def _compile_splice(cls):
    """Compile the byte template for one struct class into a renderer
    function (``exec``-generated, the way dataclasses builds __init__).

    Everything knowable at class-definition time is baked into the
    code: the static text around every splice point (each field key with
    and without its leading comma, fused ``"key":null`` constants),
    first-comma tracking eliminated after the first always-emitted field
    (``skip_if_none=False`` fields are unconditionally present, so every
    later field statically knows a comma is needed), scalar dispatch
    inlined, and per-field writer closures bound as default args (local
    loads, not global lookups).  Only the values move at encode time —
    O(changed fields), with the surrounding bytes precompiled.

    Spec-less fields compile to a raise at the exact point the slow
    path raises."""
    binds = {
        "_esc": jsonutil._escape_string,
        "_fmt_dec": jsonutil._format_decimal,
        "_int_repr": int.__repr__,
        "_scalar": _splice_scalar,
        "_speccless_error": _speccless_error,
        "_mod": sys.modules[__name__],
        "_cls": cls,
        "Decimal": Decimal,
    }
    sig_extra = []
    lines = []
    state = "empty"  # -> "maybe" (runtime flag) -> "nonempty" (static)
    need_flag = False
    for f in dataclasses.fields(cls):
        name = f.metadata.get("json_name") or f.name
        spec = f.metadata.get("spec")
        skip_if_none = f.metadata.get("skip_if_none", True)
        key = jsonutil.scalar_token(name) + ":"
        if spec is None:
            write_kind = "error"
        else:
            writer = _splice_value_writer(
                spec,
                f.metadata.get("merge", FIRST),
                f.metadata.get("key", "index"),
            )
            if writer is _splice_scalar:
                write_kind = "scalar"
            else:
                write_kind = "call"
                binds[f"_w_{f.name}"] = writer
                sig_extra.append(f"_w_{f.name}")

        def write_code(indent):
            if write_kind == "scalar":
                return _SCALAR_INLINE.format(i=indent)
            if write_kind == "call":
                return f"{indent}_w_{f.name}(v, node, {f.name!r}, out)\n"
            return (
                f"{indent}raise _speccless_error(_cls, {f.name!r})\n"
            )

        lines.append(f"    v = values[{f.name!r}]\n")
        if skip_if_none:
            # absent when None: emission is conditional
            lines.append("    if v is not None:\n")
            if state == "empty":
                lines.append("        first = False\n")
                lines.append(f"        append({key!r})\n")
                need_flag = True
                state = "maybe"
            elif state == "maybe":
                lines.append("        if first:\n")
                lines.append("            first = False\n")
                lines.append(f"            append({key!r})\n")
                lines.append("        else:\n")
                lines.append(f"            append({',' + key!r})\n")
            else:
                lines.append(f"        append({',' + key!r})\n")
            lines.append(write_code("        "))
        else:
            # always emitted (null when None): later fields statically
            # know the object is non-empty
            if state == "empty":
                k = key
            elif state == "maybe":
                lines.append("    if first:\n")
                lines.append("        first = False\n")
                lines.append(f"        append({key!r})\n")
                lines.append("    else:\n")
                lines.append(f"        append({',' + key!r})\n")
            else:
                k = "," + key
            if state in ("empty", "nonempty"):
                lines.append("    if v is None:\n")
                lines.append(f"        append({k + 'null'!r})\n")
                lines.append("    else:\n")
                lines.append(f"        append({k!r})\n")
                lines.append(write_code("        "))
            else:
                lines.append("    if v is None:\n")
                lines.append("        append('null')\n")
                lines.append("    else:\n")
                lines.append(write_code("        "))
            state = "nonempty"
    sig = ", ".join(
        ["value", "node", "out"]
        + [f"{n}={n}" for n in binds if n in sig_extra]
        + [f"{n}={n}" for n in binds if n not in sig_extra]
    )
    src = [f"def _render({sig}):\n"]
    src.append("    values = value.__dict__\n")
    src.append("    append = out.append\n")
    if need_flag:
        src.append("    first = True\n")
    src.append("    append('{')\n")
    src.extend(lines)
    src.append("    append('}')\n")
    g = dict(binds)
    g["__builtins__"] = {"id": id, "str": str, "int": int}
    exec("".join(src), g)
    return g["_render"]


def _splice_struct(value, node, out):
    cls = value.__class__
    # __dict__ probe (not getattr): the compiled renderer is stored as a
    # plain function and must never be picked up as a bound method, nor
    # inherited by a subclass whose fields differ
    render = cls.__dict__.get("_lwc_splice_render")
    if render is None:
        render = _class_plan(cls, "_lwc_splice_render", _compile_splice)
    if node is not None and node.get("__cls__") is not cls:
        # a cache slot reused for a different class must never serve
        # stale text
        node.clear()
        node["__cls__"] = cls
    render(value, node, out)


class SpliceEncoder:
    """Per-stream splice serializer over the compiled templates.

    One instance serves one response stream: the cache tree maps nested
    struct fields and KEYED list elements (by their key field, the way
    ``push`` merges them) to their last rendered text, compared by value
    equality and stored by reference (see the aliasing contract above),
    and must not leak across requests."""

    __slots__ = ("_cache", "_decimals")

    def __init__(self):
        self._cache: dict = {}
        # per-stream Decimal token memo (see _dec_memo above): entries
        # pin their value object, so ids stay unambiguous for the
        # encoder's lifetime
        self._decimals: dict = {}

    def encode(self, struct) -> bytes:
        global _dec_memo
        if not isinstance(struct, Struct):
            raise TypeError(f"cannot splice {type(struct).__name__}")
        out: list[str] = []
        _dec_memo = self._decimals
        try:
            _splice_struct(struct, self._cache, out)
        finally:
            _dec_memo = None
        return "".join(out).encode("utf-8")


def fold_chunks(chunks):
    """Fold a chunk stream into the aggregate — ``unary = fold(push, stream)``.

    Mirrors the reference's create_unary loops (src/chat/completions/
    client.rs:170-191, src/score/completions/client.rs:71-91).
    """
    aggregate = None
    for chunk in chunks:
        if aggregate is None:
            aggregate = chunk.clone()
        else:
            aggregate.push(chunk)
    return aggregate
