"""Declarative wire-type system with a generic streaming merge algebra.

The reference implements every response type as a serde struct with a hand
written ``push`` merge (reference: src/chat/completions/response.rs:23-302 and
the same pattern at score/multichat level).  The merge rules form a small
algebra:

* strings concatenate,
* numeric totals add,
* optionals are first-write-wins,
* keyed lists (choices by ``index``, tool calls by ``index``) merge per key,
* plain lists extend,
* nested structs recurse.

Instead of hand-writing ~30 ``push`` implementations we declare each struct's
fields once with a merge strategy and derive ``push``/``to_json_obj``/
``from_json_obj`` generically.  ``fold(push, chunks) == unary`` then holds by
construction and is property-tested in tests/test_merge_algebra.py.

This module is pure Python (no IO, no JAX) and is safe to import anywhere —
the analog of the reference's wasm-safe core (src/main.rs:242-243).
"""

from __future__ import annotations

import dataclasses
from decimal import Decimal
from typing import Any, Callable, Optional

from ..utils import jsonutil

MISSING = dataclasses.MISSING


class SchemaError(ValueError):
    """Raised when a JSON payload does not match the declared schema."""

    def __init__(self, path: str, message: str):
        self.path = path
        super().__init__(f"{path}: {message}" if path else message)


# ---------------------------------------------------------------------------
# Field specs
# ---------------------------------------------------------------------------

# Merge strategies (the `push` algebra):
FIRST = "first"      # first-write-wins (Option<T> semantics)
CONCAT = "concat"    # string concatenation
ADD = "add"          # numeric addition (int / Decimal)
EXTEND = "extend"    # list concatenation
KEYED = "keyed"      # list merged per-element by a key field (default "index")
NESTED = "nested"    # recurse into nested Struct.push
KEEP = "keep"        # never overwritten by pushes (id/created/model/object)


def field(
    spec,
    *,
    default=MISSING,
    default_factory=MISSING,
    merge: str = FIRST,
    skip_if_none: bool = True,
    key: str = "index",
    json_name: Optional[str] = None,
    required: bool = False,
):
    """Declare a struct field.

    ``spec`` describes the JSON codec for the value (see the spec mini-language
    below).  ``merge`` picks the push strategy.  ``skip_if_none`` mirrors
    serde's ``skip_serializing_if = "Option::is_none"``.  ``required=True``
    makes the field mandatory on parse even when a Python-side construction
    default exists (serde has no ``#[serde(default)]`` on it).
    """
    metadata = {
        "spec": spec,
        "merge": merge,
        "skip_if_none": skip_if_none,
        "key": key,
        "json_name": json_name,
        "required": required,
    }
    kwargs: dict[str, Any] = {"metadata": metadata}
    if default is not MISSING:
        kwargs["default"] = default
    if default_factory is not MISSING:
        kwargs["default_factory"] = default_factory
    return dataclasses.field(**kwargs)


# --- spec mini-language -----------------------------------------------------
#
# A spec is one of:
#   str / int / bool / float / Decimal  - scalar codecs
#   RAW                                 - passthrough JSON value
#   a Struct subclass                   - nested struct
#   List(spec)                          - homogeneous array
#   Map(spec)                           - string-keyed object (order-preserving)
#   Union(...)                          - untagged union, first parse wins
#   Enum(*values)                       - closed set of strings
#   Const(value)                        - fixed string (unit enum variants like
#                                         "chat.completion.chunk")

RAW = object()


class List:
    def __init__(self, spec):
        self.spec = spec


class Map:
    def __init__(self, spec):
        self.spec = spec


class Union:
    """Untagged union; parse attempts run in declaration order.

    Mirrors serde's ``#[serde(untagged)]``; order matters exactly the way
    variant order matters in the reference enums.
    """

    def __init__(self, *specs):
        self.specs = specs


class Enum:
    def __init__(self, *values: str):
        self.values = values


class Const:
    def __init__(self, value: str):
        self.value = value


class Lazy:
    """Spec resolved on first use — breaks import cycles (e.g. score request's
    ``model`` field referencing identity.ModelBase)."""

    def __init__(self, thunk: Callable):
        self.thunk = thunk
        self._spec = None

    def spec(self):
        if self._spec is None:
            self._spec = self.thunk()
        return self._spec


class TaggedUnion:
    """Internally tagged union (serde ``#[serde(tag = "...")]``).

    ``variants`` maps tag value -> Struct subclass.  The tag is injected /
    stripped during serialization.  Used for the ``Message`` role tree and
    rich content parts.
    """

    def __init__(self, tag: str, variants: dict):
        self.tag = tag
        self.variants = variants


def _decode(spec, obj, path: str):
    if isinstance(spec, Lazy):
        spec = spec.spec()
    if spec is RAW:
        return obj
    if spec is str:
        if not isinstance(obj, str):
            raise SchemaError(path, f"expected string, got {type(obj).__name__}")
        return obj
    if spec is bool:
        if not isinstance(obj, bool):
            raise SchemaError(path, f"expected bool, got {type(obj).__name__}")
        return obj
    if spec is int:
        if isinstance(obj, bool) or not isinstance(obj, int):
            raise SchemaError(path, f"expected integer, got {type(obj).__name__}")
        return obj
    if spec is float:
        if isinstance(obj, bool) or not isinstance(obj, (int, float, Decimal)):
            raise SchemaError(path, f"expected number, got {type(obj).__name__}")
        return float(obj)
    if spec is Decimal:
        if isinstance(obj, bool) or not isinstance(obj, (int, float, Decimal)):
            raise SchemaError(path, f"expected number, got {type(obj).__name__}")
        return obj if isinstance(obj, Decimal) else Decimal(str(obj))
    if isinstance(spec, Const):
        if obj != spec.value:
            raise SchemaError(path, f"expected {spec.value!r}, got {obj!r}")
        return obj
    if isinstance(spec, Enum):
        if obj not in spec.values:
            raise SchemaError(path, f"expected one of {spec.values}, got {obj!r}")
        return obj
    if isinstance(spec, List):
        if not isinstance(obj, list):
            raise SchemaError(path, f"expected array, got {type(obj).__name__}")
        return [_decode(spec.spec, v, f"{path}[{i}]") for i, v in enumerate(obj)]
    if isinstance(spec, Map):
        if not isinstance(obj, dict):
            raise SchemaError(path, f"expected object, got {type(obj).__name__}")
        return {k: _decode(spec.spec, v, f"{path}.{k}") for k, v in obj.items()}
    if isinstance(spec, Union):
        errors = []
        for sub in spec.specs:
            try:
                return _decode(sub, obj, path)
            except SchemaError as e:
                errors.append(str(e))
        raise SchemaError(path, "no union variant matched: " + "; ".join(errors))
    if isinstance(spec, TaggedUnion):
        if not isinstance(obj, dict):
            raise SchemaError(path, f"expected object, got {type(obj).__name__}")
        tag = obj.get(spec.tag)
        cls = spec.variants.get(tag)
        if cls is None:
            raise SchemaError(
                path, f"unknown {spec.tag} {tag!r} (expected one of {list(spec.variants)})"
            )
        rest = {k: v for k, v in obj.items() if k != spec.tag}
        return cls.from_json_obj(rest, path=path)
    if isinstance(spec, type) and issubclass(spec, Struct):
        return spec.from_json_obj(obj, path=path)
    raise TypeError(f"bad field spec {spec!r}")


def _encode(spec, value):
    if isinstance(spec, Lazy):
        spec = spec.spec()
    if value is None:
        return None
    if spec is RAW or spec in (str, bool, int, float, Decimal):
        return value
    if isinstance(spec, (Const, Enum)):
        return value
    if isinstance(spec, List):
        return [_encode(spec.spec, v) for v in value]
    if isinstance(spec, Map):
        return {k: _encode(spec.spec, v) for k, v in value.items()}
    if isinstance(spec, Union):
        # runtime type decides the encoding (first matching variant wins,
        # mirroring serde untagged serialization by variant type)
        for sub in spec.specs:
            if _spec_matches(sub, value):
                return _encode(sub, value)
        return _encode_dynamic(value)
    if isinstance(spec, TaggedUnion):
        for tag, cls in spec.variants.items():
            if type(value) is cls:
                obj = value.to_json_obj()
                return {spec.tag: tag, **obj}
        raise TypeError(f"value {type(value)!r} not a member of tagged union")
    if isinstance(spec, type) and issubclass(spec, Struct):
        return value.to_json_obj()
    raise TypeError(f"bad field spec {spec!r}")


def _spec_matches(spec, value) -> bool:
    """Best-effort runtime check that ``value`` belongs to ``spec``."""
    if isinstance(spec, Lazy):
        spec = spec.spec()
    if spec is RAW:
        return True
    if spec is str:
        return isinstance(value, str)
    if spec is bool:
        return isinstance(value, bool)
    if spec is int:
        return isinstance(value, int) and not isinstance(value, bool)
    if spec in (float, Decimal):
        return isinstance(value, (int, float, Decimal)) and not isinstance(value, bool)
    if isinstance(spec, Const):
        return value == spec.value
    if isinstance(spec, Enum):
        return value in spec.values
    if isinstance(spec, List):
        return isinstance(value, list)
    if isinstance(spec, Map):
        return isinstance(value, dict)
    if isinstance(spec, Union):
        return any(_spec_matches(sub, value) for sub in spec.specs)
    if isinstance(spec, TaggedUnion):
        return any(type(value) is cls for cls in spec.variants.values())
    if isinstance(spec, type) and issubclass(spec, Struct):
        return isinstance(value, spec)
    return False


def _encode_dynamic(value):
    if isinstance(value, Struct):
        return value.to_json_obj()
    if isinstance(value, list):
        return [_encode_dynamic(v) for v in value]
    if isinstance(value, dict):
        return {k: _encode_dynamic(v) for k, v in value.items()}
    return value


# ---------------------------------------------------------------------------
# Struct base
# ---------------------------------------------------------------------------


def _class_plan(cls, attr: str, build):
    """Per-class cache stored on the class itself (``cls.__dict__`` probe,
    NOT getattr: a subclass must not inherit its base's plan), so plans
    are garbage-collected with their class and cost one dict lookup per
    call.  Field specs are frozen at class-definition time (everything
    here re-derives what the hot methods used to pull from
    ``dataclasses.fields`` metadata on every call — mappingproxy lookups
    measured as a top host cost in a profiled scored request; push/clone/
    to_json_obj run per chunk per judge)."""
    plan = cls.__dict__.get(attr)
    if plan is None:
        plan = build(cls)
        setattr(cls, attr, plan)
    return plan


def _build_names(cls):
    return tuple(f.name for f in dataclasses.fields(cls))


def _build_push(cls):
    return tuple(
        (
            f.name,
            f.metadata.get("merge", FIRST),
            f.metadata.get("key", "index"),
        )
        for f in dataclasses.fields(cls)
    )


def _speccless_error(cls, name):
    return TypeError(
        f"{cls.__name__}.{name} was declared without the field() "
        "helper (no codec spec in metadata) — it can be pushed/cloned "
        "but not (de)serialized"
    )


def _build_encode(cls):
    # a spec-less field (declared without the field() helper — push/clone-
    # only state) stays in the plan with a None spec sentinel: encoding is
    # fine while its value is None (nothing to emit), and raises the
    # declaration error only when a real value would need a codec.
    # Raising at plan-build time instead would poison to_json_obj for the
    # WHOLE class the first time any instance serialized, even if the
    # spec-less field was never set.
    return tuple(
        (
            f.name,
            f.metadata.get("json_name") or f.name,
            f.metadata.get("skip_if_none", True),
            f.metadata.get("spec"),
        )
        for f in dataclasses.fields(cls)
    )


def _build_decode(cls):
    # spec-less fields are excluded outright: incoming JSON can't target
    # them (no json name contract), so they simply keep their default
    return tuple(
        (
            f.name,
            f.metadata.get("json_name") or f.name,
            f.metadata["spec"],
            bool(f.metadata.get("required"))
            or (
                f.default is dataclasses.MISSING
                and f.default_factory is dataclasses.MISSING
            ),
        )
        for f in dataclasses.fields(cls)
        if "spec" in f.metadata
    )


class Struct:
    """Base for all wire types; subclasses are auto-dataclassed."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        dataclasses.dataclass(cls)

    # -- serialization ------------------------------------------------------

    def to_json_obj(self) -> dict:
        out: dict[str, Any] = {}
        encode_plan = _class_plan(type(self), "_lwc_encode_plan", _build_encode)
        for attr, name, skip_if_none, spec in encode_plan:
            value = getattr(self, attr)
            if value is None and skip_if_none:
                continue
            if spec is None:
                # spec-less (push/clone-only) field holding a real value:
                # there is no codec to render it with — refuse loudly
                # instead of emitting something json.dumps will mangle
                raise _speccless_error(type(self), attr)
            out[name] = _encode(spec, value)
        return out

    def to_json(self, *, pretty: bool = False) -> str:
        return jsonutil.dumps(self.to_json_obj(), pretty=pretty)

    @classmethod
    def from_json_obj(cls, obj, *, path: str = ""):
        if not isinstance(obj, dict):
            raise SchemaError(path, f"expected object, got {type(obj).__name__}")
        kwargs = {}
        # unknown JSON fields are ignored, matching serde's default behavior
        decode_plan = _class_plan(cls, "_lwc_decode_plan", _build_decode)
        for attr, name, spec, required in decode_plan:
            if name in obj and obj[name] is not None:
                sub_path = f"{path}.{name}" if path else name
                kwargs[attr] = _decode(spec, obj[name], sub_path)
            elif required:
                sub_path = f"{path}.{name}" if path else name
                raise SchemaError(sub_path, "missing required field")
            # else: default applies
        return cls(**kwargs)

    @classmethod
    def from_json(cls, s: str):
        return cls.from_json_obj(jsonutil.loads(s))

    # -- merge algebra ------------------------------------------------------

    def push(self, other) -> None:
        """Merge ``other`` (a later streaming chunk) into ``self`` in place."""
        if type(other) is not type(self):
            raise TypeError(
                f"cannot push {type(other).__name__} into {type(self).__name__}"
            )
        push_plan = _class_plan(type(self), "_lwc_push_plan", _build_push)
        for name, strategy, key in push_plan:
            if strategy == KEEP:
                continue
            mine = getattr(self, name)
            theirs = getattr(other, name)
            if theirs is None:
                continue
            if mine is None:
                setattr(self, name, _clone(theirs))
                continue
            if strategy == FIRST:
                pass  # first write wins
            elif strategy == CONCAT:
                setattr(self, name, mine + theirs)
            elif strategy == ADD:
                setattr(self, name, mine + theirs)
            elif strategy == EXTEND:
                mine.extend(_clone(v) for v in theirs)
            elif strategy == NESTED:
                mine.push(theirs)
            elif strategy == KEYED:
                _push_keyed(mine, theirs, key)
            else:
                raise ValueError(f"unknown merge strategy {strategy!r}")

    def clone(self):
        return _clone(self)


def _push_keyed(mine: list, theirs: list, key: str) -> None:
    # Linear scan matches the reference exactly (choices lists are small);
    # reference: src/chat/completions/response.rs:56-78.
    for other in theirs:
        other_key = getattr(other, key)
        for item in mine:
            if getattr(item, key) == other_key:
                item.push(other)
                break
        else:
            mine.append(_clone(other))


def _clone(value):
    # exact-class checks first: the overwhelmingly common case is a leaf
    # (str/int/Decimal/None), which should fall through with two pointer
    # compares instead of three isinstance() calls (this function is the
    # top host cost of a profiled scored request — per-judge isolation
    # clones run per judge per chunk)
    cls = value.__class__
    if cls is list:
        return [_clone(v) for v in value]
    if cls is dict:
        return {k: _clone(v) for k, v in value.items()}
    if isinstance(value, Struct):
        return cls(
            **{
                name: _clone(getattr(value, name))
                for name in _class_plan(cls, "_lwc_field_names", _build_names)
            }
        )
    # subclasses of the containers (rare; exact classes took the fast path)
    if isinstance(value, list):
        return [_clone(v) for v in value]
    if isinstance(value, dict):
        return {k: _clone(v) for k, v in value.items()}
    return value


class ResponseError(Struct, Exception):
    """Wire-form ``{code, message}`` error (reference src/error.rs:8-13).

    Lives in the type core (rather than errors.py) because response types
    embed it as a field; errors.py re-exports it alongside the rich error
    taxonomy.
    """

    code: int = field(int)
    message: object = field(RAW, default=None, skip_if_none=False)

    def __post_init__(self):
        Exception.__init__(self, self.to_json())


def fold_chunks(chunks):
    """Fold a chunk stream into the aggregate — ``unary = fold(push, stream)``.

    Mirrors the reference's create_unary loops (src/chat/completions/
    client.rs:170-191, src/score/completions/client.rs:71-91).
    """
    aggregate = None
    for chunk in chunks:
        if aggregate is None:
            aggregate = chunk.clone()
        else:
            aggregate.push(chunk)
    return aggregate
