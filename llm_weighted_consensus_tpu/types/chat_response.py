"""Chat completion response types — streaming chunks, unary, merge algebra.

Parity target: reference src/chat/completions/response.rs (872 LoC).  The
dual streaming/unary representation and the chunk-merge ``push`` algebra are
the load-bearing spec (SURVEY §2.3): strings concatenate, usage adds,
optionals first-write-win, choices/tool-calls merge keyed by ``index``, and
``unary == fold(push, chunks)``.
"""

from __future__ import annotations

from decimal import Decimal
from typing import Optional

from .base import (
    ADD,
    CONCAT,
    Const,
    EXTEND,
    Enum,
    KEEP,
    KEYED,
    List,
    NESTED,
    RAW,
    Struct,
    field,
)

SERVICE_TIER = Enum("auto", "default", "flex")

# FinishReason includes the custom `error` variant, which is also the default
# when a streaming choice never finished (response.rs:530-547).
FINISH_REASON = Enum("stop", "length", "tool_calls", "content_filter", "error")
FINISH_REASON_DEFAULT = "error"

ROLE = Enum("assistant")


# ---------------------------------------------------------------------------
# Usage & cost accounting (response.rs:549-734)
# ---------------------------------------------------------------------------


class CompletionTokensDetails(Struct):
    accepted_prediction_tokens: Optional[int] = field(int, default=None, merge=ADD)
    audio_tokens: Optional[int] = field(int, default=None, merge=ADD)
    reasoning_tokens: Optional[int] = field(int, default=None, merge=ADD)
    rejected_prediction_tokens: Optional[int] = field(int, default=None, merge=ADD)


class PromptTokensDetails(Struct):
    audio_tokens: Optional[int] = field(int, default=None, merge=ADD)
    cached_tokens: Optional[int] = field(int, default=None, merge=ADD)


class CostDetails(Struct):
    upstream_inference_cost: Optional[Decimal] = field(Decimal, default=None, merge=ADD)
    # custom field carried through nested archive completions
    upstream_upstream_inference_cost: Optional[Decimal] = field(
        Decimal, default=None, merge=ADD
    )

    def is_empty(self) -> bool:
        return (
            self.upstream_inference_cost is None
            and self.upstream_upstream_inference_cost is None
        )

    def total_cost(self) -> Decimal:
        total = Decimal(0)
        if self.upstream_inference_cost is not None:
            total += self.upstream_inference_cost
        if self.upstream_upstream_inference_cost is not None:
            total += self.upstream_upstream_inference_cost
        return total


class Usage(Struct):
    completion_tokens: int = field(int, default=0, merge=ADD, skip_if_none=False)
    prompt_tokens: int = field(int, default=0, merge=ADD, skip_if_none=False)
    total_tokens: int = field(int, default=0, merge=ADD, skip_if_none=False)
    completion_tokens_details: Optional[CompletionTokensDetails] = field(
        CompletionTokensDetails, default=None, merge=NESTED
    )
    prompt_tokens_details: Optional[PromptTokensDetails] = field(
        PromptTokensDetails, default=None, merge=NESTED
    )
    # openrouter fields
    cost: Optional[Decimal] = field(Decimal, default=None, merge=ADD)
    cost_details: Optional[CostDetails] = field(CostDetails, default=None, merge=NESTED)
    # custom field: derived total (cost + cost_details components)
    total_cost: Optional[Decimal] = field(Decimal, default=None, merge=ADD)

    def is_empty(self) -> bool:
        return (
            self.completion_tokens == 0
            and self.prompt_tokens == 0
            and self.total_tokens == 0
            and self.completion_tokens_details is None
            and self.prompt_tokens_details is None
        )

    def with_total_cost(self) -> None:
        """Derive ``total_cost`` once (response.rs:635-649)."""
        if self.total_cost is None and (
            self.cost is not None
            or (self.cost_details is not None and not self.cost_details.is_empty())
        ):
            total = Decimal(0)
            if self.cost is not None:
                total += self.cost
            if self.cost_details is not None:
                total += self.cost_details.total_cost()
            self.total_cost = total


# ---------------------------------------------------------------------------
# Logprobs (response.rs:736-778)
# ---------------------------------------------------------------------------


class TopLogprob(Struct):
    token: str = field(str)
    bytes: Optional[list] = field(List(int), default=None, skip_if_none=False)
    logprob: Optional[Decimal] = field(Decimal, default=None, skip_if_none=False)


class Logprob(Struct):
    token: str = field(str)
    bytes: Optional[list] = field(List(int), default=None, skip_if_none=False)
    logprob: Decimal = field(Decimal, default=None, skip_if_none=False)
    top_logprobs: list = field(List(TopLogprob), default_factory=list, skip_if_none=False)


class Logprobs(Struct):
    content: Optional[list] = field(List(Logprob), default=None, merge=EXTEND, skip_if_none=False)
    refusal: Optional[list] = field(List(Logprob), default=None, merge=EXTEND, skip_if_none=False)


# ---------------------------------------------------------------------------
# Generated images (openrouter; response.rs:794-810)
# ---------------------------------------------------------------------------


class ImageUrl(Struct):
    url: str = field(str)


class Image(Struct):
    type: str = field(Const("image_url"), default="image_url")
    image_url: ImageUrl = field(ImageUrl, default=None)


# ---------------------------------------------------------------------------
# Streaming side
# ---------------------------------------------------------------------------


class StreamingToolCallFunction(Struct):
    name: Optional[str] = field(str, default=None)
    arguments: Optional[str] = field(str, default=None, merge=CONCAT)


class StreamingToolCall(Struct):
    index: int = field(int, merge=KEEP)
    id: Optional[str] = field(str, default=None)
    function: Optional[StreamingToolCallFunction] = field(
        StreamingToolCallFunction, default=None, merge=NESTED
    )
    type: Optional[str] = field(Const("function"), default=None)


class Delta(Struct):
    content: Optional[str] = field(str, default=None, merge=CONCAT)
    refusal: Optional[str] = field(str, default=None, merge=CONCAT)
    role: Optional[str] = field(ROLE, default=None)
    tool_calls: Optional[list] = field(
        List(StreamingToolCall), default=None, merge=KEYED, key="index"
    )
    # openrouter fields
    reasoning: Optional[str] = field(str, default=None, merge=CONCAT)
    images: Optional[list] = field(List(Image), default=None, merge=EXTEND)

    def tool_as_content(self) -> None:
        """Fold tool-call argument deltas into content (response.rs:161-177)."""
        if self.tool_calls is None:
            return
        tool_calls, self.tool_calls = self.tool_calls, None
        for tool_call in tool_calls:
            if tool_call.function is not None and tool_call.function.arguments is not None:
                if self.content is None:
                    self.content = tool_call.function.arguments
                else:
                    self.content += tool_call.function.arguments


class StreamingChoice(Struct):
    delta: Delta = field(Delta, merge=NESTED)
    finish_reason: Optional[str] = field(FINISH_REASON, default=None, skip_if_none=False)
    index: int = field(int, default=0, merge=KEEP, skip_if_none=False)
    logprobs: Optional[Logprobs] = field(Logprobs, default=None, merge=NESTED)


class ChatCompletionChunk(Struct):
    id: str = field(str, merge=KEEP)
    choices: list = field(List(StreamingChoice), default_factory=list, merge=KEYED, skip_if_none=False, required=True)
    created: int = field(int, default=0, merge=KEEP, skip_if_none=False, required=True)
    model: str = field(str, default="", merge=KEEP, skip_if_none=False, required=True)
    object: str = field(Const("chat.completion.chunk"), default="chat.completion.chunk", merge=KEEP)
    service_tier: Optional[str] = field(SERVICE_TIER, default=None)
    system_fingerprint: Optional[str] = field(str, default=None)
    usage: Optional[Usage] = field(Usage, default=None, merge=NESTED)
    # openrouter fields
    provider: Optional[str] = field(str, default=None)

    def with_total_cost(self) -> None:
        if self.usage is not None:
            self.usage.with_total_cost()


# ---------------------------------------------------------------------------
# Unary side
# ---------------------------------------------------------------------------


class UnaryToolCallFunction(Struct):
    name: str = field(str, default="")
    arguments: str = field(str, default="")


class UnaryToolCall(Struct):
    id: str = field(str, default="")
    function: UnaryToolCallFunction = field(
        UnaryToolCallFunction, default_factory=UnaryToolCallFunction
    )
    type: str = field(Const("function"), default="function")

    @classmethod
    def from_streaming(cls, tc: StreamingToolCall) -> "UnaryToolCall":
        fn = tc.function
        return cls(
            id=tc.id or "",
            function=UnaryToolCallFunction(
                name=(fn.name if fn and fn.name else ""),
                arguments=(fn.arguments if fn and fn.arguments else ""),
            ),
            type="function",
        )


class AnnotationUrlCitation(Struct):
    end_index: int = field(int)
    start_index: int = field(int)
    title: str = field(str)
    url: str = field(str)


class Annotation(Struct):
    type: str = field(Const("url_citation"), default="url_citation")
    url_citation: AnnotationUrlCitation = field(AnnotationUrlCitation, default=None)


class Audio(Struct):
    id: str = field(str)
    data: str = field(str)
    expires_at: int = field(int)
    transcript: str = field(str)


class Message(Struct):
    content: Optional[str] = field(str, default=None, skip_if_none=False)
    refusal: Optional[str] = field(str, default=None, skip_if_none=False)
    role: str = field(ROLE, default="assistant", skip_if_none=False)
    annotations: Optional[list] = field(List(Annotation), default=None)
    audio: Optional[Audio] = field(Audio, default=None)
    tool_calls: Optional[list] = field(List(UnaryToolCall), default=None)
    # openrouter fields
    reasoning: Optional[str] = field(str, default=None)
    images: Optional[list] = field(List(Image), default=None)

    @classmethod
    def from_delta(cls, delta: Delta) -> "Message":
        return cls(
            content=delta.content,
            refusal=delta.refusal,
            role=delta.role or "assistant",
            annotations=None,
            audio=None,
            tool_calls=(
                [UnaryToolCall.from_streaming(tc) for tc in delta.tool_calls]
                if delta.tool_calls is not None
                else None
            ),
            reasoning=delta.reasoning,
            images=delta.images,
        )


class UnaryChoice(Struct):
    message: Message = field(Message)
    finish_reason: str = field(FINISH_REASON, default=FINISH_REASON_DEFAULT, skip_if_none=False)
    index: int = field(int, default=0, skip_if_none=False)
    logprobs: Optional[Logprobs] = field(Logprobs, default=None, skip_if_none=False)

    @classmethod
    def from_streaming(cls, choice: StreamingChoice) -> "UnaryChoice":
        return cls(
            message=Message.from_delta(choice.delta),
            finish_reason=choice.finish_reason or FINISH_REASON_DEFAULT,
            index=choice.index,
            logprobs=choice.logprobs,
        )


class ChatCompletion(Struct):
    id: str = field(str, default="")
    choices: list = field(List(UnaryChoice), default_factory=list, skip_if_none=False)
    created: int = field(int, default=0, skip_if_none=False)
    model: str = field(str, default="", skip_if_none=False)
    object: str = field(Const("chat.completion"), default="chat.completion")
    service_tier: Optional[str] = field(SERVICE_TIER, default=None)
    system_fingerprint: Optional[str] = field(str, default=None)
    usage: Optional[Usage] = field(Usage, default=None)
    # openrouter fields
    provider: Optional[str] = field(str, default=None)

    @classmethod
    def from_streaming(cls, chunk: ChatCompletionChunk) -> "ChatCompletion":
        """The unary-is-fold-of-streaming contract (response.rs:344-370)."""
        return cls(
            id=chunk.id,
            choices=[UnaryChoice.from_streaming(c) for c in chunk.choices],
            created=chunk.created,
            model=chunk.model,
            object="chat.completion",
            service_tier=chunk.service_tier,
            system_fingerprint=chunk.system_fingerprint,
            usage=chunk.usage,
            provider=chunk.provider,
        )
