"""Multichat request type (not present in the reference crate).

The reference ships only multichat *response* types and the
``multichat_id``/``multichat_index`` identity machinery (SURVEY §2.10); the
request side is defined here to complete the capability: one request fans
out to every generator slot of a score panel (judges deduplicated by
``multichat_id``; duplicate generators become extra samples, exactly the
slot semantics of model/mod.rs:153-178).
"""

from __future__ import annotations

from typing import Optional

from .base import List, Struct, field
from .chat_request import MESSAGE, SERVICE_TIER, StreamOptions, UsageInclude
from .score_request import MODEL


class ChatCompletionCreateParams(Struct):
    """POST /multichat/completions body: messages + a score panel whose
    judges define the generator slots."""

    messages: list = field(List(MESSAGE))
    model: object = field(MODEL)
    seed: Optional[int] = field(int, default=None)
    service_tier: Optional[str] = field(SERVICE_TIER, default=None)
    stream: Optional[bool] = field(bool, default=None)
    stream_options: Optional[StreamOptions] = field(StreamOptions, default=None)
    usage: Optional[UsageInclude] = field(UsageInclude, default=None)
    # extension (no reference analog): when true and the gateway has an
    # embedder, interleave live ``multichat.consensus`` frames as candidates
    # finish (BASELINE config 5 — streaming incremental consensus)
    consensus: Optional[bool] = field(bool, default=None)
