"""Record a streamed score response's chunk frames; replay them on a hit.

The cache value is the stream's *wire form*: the list of chunk JSON
objects exactly as the live stream yielded them.  Replaying that list
through the same SSE framing the gateway already uses makes a hit
byte-identical to the original streamed response, and the unary path
needs nothing extra — ``fold_chunks`` over replayed chunks produces the
same ``ChatCompletion`` the original unary call did.

Recording is conservative about what it considers a cacheable outcome:

* the stream must be consumed to natural completion — an abandoned
  stream (client disconnect, unary early-raise) records nothing;
* a trailing error item (``ScoreError``, e.g. AllVotesFailed) marks the
  whole stream uncacheable;
* any per-choice error inside a chunk (a judge that failed) marks it
  uncacheable too — a transient upstream failure must not be pinned for
  a full TTL;
* a ``degraded: true`` frame (weight-quorum early exit / deadline
  expiry with a partial panel — resilience/) marks it uncacheable: a
  degraded consensus is an emergency answer, never an authoritative one,
  and the next identical request should get a full-panel attempt.

Frames are snapshotted via ``to_json_obj()`` *before* they are yielded,
so no downstream consumer (unary fold, archiving tee) can mutate the
recorded copy; replay decodes fresh typed chunks per call for the same
reason.
"""

from __future__ import annotations

from typing import AsyncIterator, Callable, List, Optional


async def record_stream(
    stream: AsyncIterator, on_complete: Callable[[List[dict]], None]
) -> AsyncIterator:
    """Tee ``stream``, yielding every item unchanged; fire
    ``on_complete(chunk_objs)`` only after clean, error-free, complete
    consumption."""
    chunk_objs: List[dict] = []
    cacheable = True
    completed = False
    try:
        async for item in stream:
            if isinstance(item, BaseException):
                cacheable = False
            elif cacheable:
                if getattr(item, "degraded", None) or any(
                    c.error is not None for c in item.choices
                ):
                    cacheable = False
                    chunk_objs = []
                else:
                    chunk_objs.append(item.to_json_obj())
            yield item
        completed = True
    finally:
        aclose = getattr(stream, "aclose", None)
        if aclose is not None:
            await aclose()
    if completed and cacheable:
        on_complete(chunk_objs)


async def replay_stream(chunk_objs: List[dict]) -> AsyncIterator:
    """Yield typed chunks decoded from recorded frames.

    Decoding per replay (rather than storing typed chunks) costs a little
    CPU per hit but guarantees isolation: concurrent replays and the
    cached entry never share mutable state.
    """
    from ..types.score_response import ChatCompletionChunk

    for obj in chunk_objs:
        yield ChatCompletionChunk.from_json_obj(obj)


def chunks_from_record(chunk_objs: List[dict]) -> Optional[list]:
    """Decode all recorded frames at once (the unary hit path: callers
    fold these with ``fold_chunks``).  Returns None on a corrupt record
    (e.g. a hand-edited disk segment) so callers fall back to a miss."""
    from ..types.base import SchemaError
    from ..types.score_response import ChatCompletionChunk

    try:
        return [ChatCompletionChunk.from_json_obj(obj) for obj in chunk_objs]
    except (SchemaError, ValueError, TypeError, KeyError):
        return None
