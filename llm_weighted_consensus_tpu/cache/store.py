"""Two-tier cache store: in-memory LRU (TTL + byte budget) over an
optional append-only JSONL disk tier.

The memory tier is the hot path: an insertion-ordered dict used as an
LRU (hits reinsert at the tail), every entry carrying its byte size and
absolute expiry.  The byte budget is enforced on insert by evicting from
the head; TTL is enforced lazily on lookup (an expired entry counts as a
miss and is dropped).

The disk tier mirrors the XLA compile-cache pattern the service already
uses for jit specializations (serve/config.py COMPILE_CACHE_DIR): warm
restarts reload previously computed results instead of recomputing them.
Each store instance appends to its own JSONL segment (one JSON object per
line: ``{"k": fingerprint, "e": expiry, "v": value}``); on startup every
``seg-*.jsonl`` in the directory is replayed oldest-first, expired
entries skipped, and the surviving set is compacted into a fresh segment
when the old segments carry more dead weight than live data.  Eviction
never rewrites disk — the tier is append-only; compaction happens only at
load, where a full pass is already being paid.

Wall-clock time (not monotonic) keys expiry because the disk tier spans
process lifetimes.  The ``clock`` hook exists for tests.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional


class CacheStore:
    """In-memory LRU with TTL and byte-budget accounting, plus the
    optional JSONL disk tier.  ``ttl_sec<=0`` or ``max_bytes<=0`` disables
    the store entirely (``enabled`` False, every ``get`` a pass-through
    miss that touches no state) — the TTL=0 service configuration must
    preserve cacheless behavior exactly."""

    def __init__(
        self,
        ttl_sec: float,
        max_bytes: int,
        disk_dir: Optional[str] = None,
        *,
        clock: Callable[[], float] = time.time,
        name: str = "cache",
    ) -> None:
        self.ttl_sec = float(ttl_sec)
        self.max_bytes = int(max_bytes)
        self.disk_dir = disk_dir
        self.clock = clock
        self.name = name
        self._entries: dict = {}  # fp -> [value, size, expires_at]
        self._bytes = 0
        self._segment = None  # lazily opened append handle
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0
        self.puts = 0
        self.disk_loaded = 0
        self.disk_torn = 0
        self.flushes = 0
        if self.enabled and disk_dir:
            self._load_disk(disk_dir)

    @property
    def enabled(self) -> bool:
        return self.ttl_sec > 0 and self.max_bytes > 0

    # -- memory tier ---------------------------------------------------------

    def get(self, fp: str):
        """The cached value, or None.  Hits refresh LRU position (not
        TTL: an entry's lifetime is anchored to when it was computed, so
        a hot stale entry still refreshes eventually)."""
        if not self.enabled:
            return None
        entry = self._entries.get(fp)
        if entry is None:
            self.misses += 1
            return None
        value, size, expires_at = entry
        if self.clock() >= expires_at:
            del self._entries[fp]
            self._bytes -= size
            self.expirations += 1
            self.misses += 1
            return None
        # LRU refresh: reinsert at the insertion-order tail
        del self._entries[fp]
        self._entries[fp] = entry
        self.hits += 1
        return value

    def put(
        self, fp: str, value, size: int, ttl_sec: Optional[float] = None
    ) -> None:
        """Insert (or refresh) ``fp``; evicts least-recently-used entries
        until the byte budget holds.  A value larger than the whole
        budget is not stored (it would evict everything for one entry
        that can never be joined by another).  ``ttl_sec`` overrides the
        store TTL for this entry (clamped to it, never extended) — the
        fleet drain handoff uses it so a transferred entry expires
        exactly when the original would have."""
        if not self.enabled or size > self.max_bytes:
            return
        if ttl_sec is not None:
            ttl_sec = min(float(ttl_sec), self.ttl_sec)
            if ttl_sec <= 0:
                return
        expires_at = self.clock() + (
            self.ttl_sec if ttl_sec is None else ttl_sec
        )
        old = self._entries.pop(fp, None)
        if old is not None:
            self._bytes -= old[1]
        self._entries[fp] = [value, int(size), expires_at]
        self._bytes += int(size)
        while self._bytes > self.max_bytes and self._entries:
            victim, (_, vsize, _) = next(iter(self._entries.items()))
            if victim == fp:
                # cannot happen (size <= max_bytes guard) unless the
                # budget shrank; never evict the entry just inserted
                break
            del self._entries[victim]
            self._bytes -= vsize
            self.evictions += 1
        if old is None:
            self.puts += 1
            self._append_disk(fp, value, expires_at)

    def __len__(self) -> int:
        return len(self._entries)

    def hot_entries(self, limit: int) -> list:
        """The most-recently-used live entries, MRU first:
        ``[(fp, value, remaining_ttl_sec)]``.  The fleet drain handoff
        (fleet/coordinator.py) pushes these to their post-drain owners —
        MRU order so a bounded transfer carries the hottest keys."""
        if not self.enabled:
            return []
        now = self.clock()
        out = []
        for fp in reversed(list(self._entries)):
            value, _, expires_at = self._entries[fp]
            if expires_at <= now:
                continue
            out.append((fp, value, expires_at - now))
            if len(out) >= limit:
                break
        return out

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "bytes": self._bytes,
            "max_bytes": self.max_bytes,
            "ttl_sec": self.ttl_sec,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "expirations": self.expirations,
            "disk_loaded": self.disk_loaded,
            "disk_torn": self.disk_torn,
            "flushes": self.flushes,
        }

    def flush(self) -> None:
        """Drain hook (serve/lifecycle.py): push the open disk segment
        through to stable storage so a graceful shutdown loses nothing
        the final requests wrote.  Each append already ``flush()``es the
        userspace buffer; this adds the fsync the per-append path
        deliberately skips (an fsync per entry would serialize the hot
        path on disk latency).  Counted in ``flushes`` — the drain
        contract is 'flushed exactly once'.  No segment open (memory-only
        store, or the disk tier degraded away) = a counted no-op, same
        accelerator-not-a-dependency stance as ``_append_disk``."""
        self.flushes += 1
        if self._segment is None:
            return
        try:
            self._segment.flush()
            os.fsync(self._segment.fileno())
        except OSError:
            self._segment = None
            self.disk_dir = None

    # -- disk tier (value codec overridden by subclasses) ---------------------

    def encode_value(self, value):
        """value -> JSON-serializable object (None = not disk-cacheable)."""
        return value

    def decode_value(self, obj):
        """JSON object -> value (raise / return None to skip the entry)."""
        return obj

    def measure(self, obj) -> int:
        """Byte-size estimate of an encoded value (the budget unit)."""
        from ..utils import jsonutil

        return len(jsonutil.dumps(obj))

    def _append_disk(self, fp: str, value, expires_at: float) -> None:
        if not self.disk_dir:
            return
        obj = self.encode_value(value)
        if obj is None:
            return
        from ..utils import jsonutil

        try:
            if self._segment is None:
                os.makedirs(self.disk_dir, exist_ok=True)
                path = os.path.join(
                    self.disk_dir, f"seg-{os.getpid()}-{id(self):x}.jsonl"
                )
                self._segment = open(path, "a", encoding="utf-8")
            self._segment.write(
                jsonutil.dumps({"k": fp, "e": expires_at, "v": obj}) + "\n"
            )
            self._segment.flush()
        except OSError:
            # the disk tier is an accelerator, never a correctness
            # dependency: a full/readonly disk degrades to memory-only
            self._segment = None
            self.disk_dir = None

    def _load_disk(self, disk_dir: str) -> None:
        from ..utils import jsonutil

        if not os.path.isdir(disk_dir):
            return
        segments = sorted(
            os.path.join(disk_dir, f)
            for f in os.listdir(disk_dir)
            if f.startswith("seg-") and f.endswith(".jsonl")
        )
        if not segments:
            return
        now = self.clock()
        loaded: dict = {}  # fp -> (value, size, expires_at); later wins
        lines = 0
        for path in segments:
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        lines += 1
                        try:
                            rec = jsonutil.loads(line)
                            if now >= float(rec["e"]):
                                continue
                            value = self.decode_value(rec["v"])
                            if value is None:
                                continue
                            loaded[rec["k"]] = (
                                value,
                                self.measure(rec["v"]),
                                float(rec["e"]),
                            )
                        except (ValueError, KeyError, TypeError):
                            # torn tail write (kill -9 mid-append) or a
                            # foreign line: skipped and COUNTED — a torn
                            # record is expected crash debris, not a
                            # reason to fail the whole segment load
                            self.disk_torn += 1
                            continue
            except OSError:
                continue
        for fp, (value, size, expires_at) in loaded.items():
            if size > self.max_bytes:
                continue
            self._entries[fp] = [value, size, expires_at]
            self._bytes += size
            self.disk_loaded += 1
        while self._bytes > self.max_bytes and self._entries:
            victim, (_, vsize, _) = next(iter(self._entries.items()))
            del self._entries[victim]
            self._bytes -= vsize
        # compact when the segments hold more dead lines than live
        # entries: rewrite survivors into one fresh segment and drop the
        # old files (load already paid the full read)
        if lines > 2 * len(self._entries):
            try:
                compact = os.path.join(
                    disk_dir, f"seg-{os.getpid()}-{id(self):x}-c.jsonl"
                )
                with open(compact, "w", encoding="utf-8") as f:
                    for fp, (value, _, expires_at) in self._entries.items():
                        obj = self.encode_value(value)
                        if obj is None:
                            continue
                        f.write(
                            jsonutil.dumps(
                                {"k": fp, "e": expires_at, "v": obj}
                            )
                            + "\n"
                        )
                for path in segments:
                    os.unlink(path)
            except OSError:
                pass


class ScoreCache(CacheStore):
    """Fingerprint -> recorded score-stream chunk frames.

    The stored value is the *wire form*: the list of chunk JSON objects
    the stream yielded (cache/replay.py records and replays them), so a
    hit reproduces the exact frames of the original response — unary
    callers fold the same chunks the streaming path replays.  Values are
    plain JSON objects (typed chunks are decoded per replay, so no caller
    can mutate the cached copy), which makes the disk codec the identity.
    """

    def __init__(
        self,
        ttl_sec: float,
        max_bytes: int,
        disk_dir: Optional[str] = None,
        *,
        clock: Callable[[], float] = time.time,
    ) -> None:
        super().__init__(
            ttl_sec, max_bytes, disk_dir, clock=clock, name="score_cache"
        )

    def put_chunks(
        self, fp: str, chunk_objs: list, ttl_sec: Optional[float] = None
    ) -> None:
        # the recording leader's trace_id must not leak into replays: a
        # cache hit is a different request with (usually) no trace, and a
        # stale id pointing at the leader's span tree would mislead more
        # than it helps — cached responses simply carry no trace_id
        for obj in chunk_objs:
            obj.pop("trace_id", None)
        self.put(fp, chunk_objs, self.measure(chunk_objs), ttl_sec)

    def decode_value(self, obj):
        return obj if isinstance(obj, list) else None


class EmbeddingCache(CacheStore):
    """Row fingerprint -> ``(embedding vector, token count)``.

    Memory-only: vectors are recomputed cheaply relative to their JSONL
    footprint, and the batcher's win is collapsing *hot* rows before
    device dispatch, which the memory tier alone delivers."""

    def __init__(
        self,
        ttl_sec: float,
        max_bytes: int,
        *,
        clock: Callable[[], float] = time.time,
    ) -> None:
        super().__init__(
            ttl_sec, max_bytes, None, clock=clock, name="embed_cache"
        )

    def put_row(self, fp: str, vector, tokens: int) -> None:
        # vector is a host numpy row; nbytes + key/bookkeeping overhead
        self.put(fp, (vector, int(tokens)), int(vector.nbytes) + 64)

    def encode_value(self, value):
        return None  # never written to disk
