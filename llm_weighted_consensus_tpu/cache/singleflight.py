"""Single-flight deduplication: concurrent same-key requests collapse
onto one in-flight computation.

The classic shape is ``do(key, factory)`` — first caller (the leader)
runs the factory, everyone else awaits the leader's future.  The score
streaming path needs the primitives underneath instead: the leader must
stream *live* to its own client while recording, so it claims the key,
streams, and completes/fails the flight when the stream finishes;
followers that arrived mid-flight await the recorded chunks and replay
them.  Both shapes share one invariant: a key's future is removed from
the table by whoever resolves it, never left to dangle.

Cancellation safety:

* a *follower* being cancelled must not disturb the flight — its wait is
  wrapped in ``asyncio.shield`` so the leader's future never absorbs a
  bystander's cancellation;
* the *leader* being cancelled (client disconnect mid-stream) fails the
  flight with ``CancelledError``; followers observe a leader-abandonment
  and retry — one of them becomes the new leader rather than all of them
  inheriting the dead leader's fate.

Single event loop assumed (the serving process owns one loop); no locks
needed — all table mutations happen synchronously between awaits.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Dict, Optional


class _Flight:
    __slots__ = ("future",)

    def __init__(self) -> None:
        self.future: asyncio.Future = asyncio.get_event_loop().create_future()


class SingleFlight:
    """Per-key in-flight computation table with a collapse counter."""

    def __init__(self) -> None:
        self._flights: Dict[str, _Flight] = {}
        self.collapses = 0  # follower joins: requests that paid no upstream

    def __len__(self) -> int:
        return len(self._flights)

    # -- primitives (streaming path) -----------------------------------------

    def claim(self, key: str) -> Optional[asyncio.Future]:
        """Become the leader for ``key`` (returns None) or get the
        current leader's future to await (counts as a collapse)."""
        flight = self._flights.get(key)
        if flight is None:
            self._flights[key] = _Flight()
            return None
        self.collapses += 1
        return flight.future

    def complete(self, key: str, value) -> None:
        """Leader hand-off: resolve every follower with ``value``."""
        flight = self._flights.pop(key, None)
        if flight is not None and not flight.future.done():
            flight.future.set_result(value)

    def fail(self, key: str, exc: BaseException) -> None:
        """Leader hand-off on error; followers re-raise ``exc`` (or, for
        CancelledError, retry as leader — see ``wait``)."""
        flight = self._flights.pop(key, None)
        if flight is not None and not flight.future.done():
            flight.future.set_exception(exc)
            # mark retrieved so a flight no follower ever awaited doesn't
            # trip the loop's "exception was never retrieved" warning
            flight.future.exception()

    async def wait(self, future: asyncio.Future):
        """Follower-side await of a leader's future, shielded so this
        caller's cancellation cannot poison the shared flight.  Returns
        ``(ok, value)``: ``ok`` False means the leader was cancelled and
        the caller should retry ``claim`` (likely becoming the leader)."""
        from ..obs import span

        with span("singleflight:wait") as s:
            try:
                return True, await asyncio.shield(future)
            except asyncio.CancelledError:
                if future.cancelled() or (
                    future.done()
                    and isinstance(
                        future.exception(), asyncio.CancelledError
                    )
                ):
                    if s is not None:
                        s.annotate(leader_abandoned=True)
                    return False, None  # leader abandoned; caller retries
                raise  # caller itself was cancelled

    # -- classic interface ---------------------------------------------------

    async def do(self, key: str, factory: Callable[[], Awaitable]):
        """Run ``factory`` once per key: leaders execute, followers await
        the leader's result.  A cancelled leader promotes a follower."""
        while True:
            future = self.claim(key)
            if future is None:
                try:
                    value = await factory()
                except BaseException as exc:
                    self.fail(key, exc)
                    raise
                self.complete(key, value)
                return value
            ok, value = await self.wait(future)
            if ok:
                return value
