"""Content-addressed consensus cache with single-flight deduplication.

The identity layer already gives every judge-panel configuration a
content-addressed id (identity/model.py); this package extends that to
whole *requests*: a canonical fingerprint over (panel id, canonicalized
messages, candidate choice set, sampling params) keys a two-tier
result store, so two semantically identical score requests pay one judge
fan-out instead of two.  Modules:

* ``fingerprint``  — canonical request keys on ``IncrementalHasher``
  (JSON field order never changes the key);
* ``store``        — in-memory LRU with TTL + byte budget, optional
  append-only JSONL disk tier for warm restarts (the XLA compile-cache
  pattern, serve/config.py COMPILE_CACHE_DIR);
* ``singleflight`` — concurrent same-fingerprint requests collapse onto
  one in-flight computation (asyncio future per key);
* ``replay``       — record a streamed score response's chunk frames and
  replay them on a hit, so ``stream=true`` clients get byte-identical
  wire behavior on hit and miss.

Pure-core hygiene: nothing here imports jax or aiohttp at module scope
(tests/test_import_hygiene.py pins it).
"""

from .fingerprint import embed_fingerprint, score_fingerprint  # noqa: F401
from .singleflight import SingleFlight  # noqa: F401
from .store import CacheStore, ScoreCache, EmbeddingCache  # noqa: F401
from .replay import chunks_from_record, record_stream, replay_stream  # noqa: F401

__all__ = [
    "CacheStore",
    "EmbeddingCache",
    "ScoreCache",
    "SingleFlight",
    "chunks_from_record",
    "embed_fingerprint",
    "record_stream",
    "replay_stream",
    "score_fingerprint",
]
