"""Canonical request fingerprints, built on the identity layer's hasher.

A fingerprint is a 22-char base62 id (the same xxh3-128 pipeline as panel
ids, identity/__init__.py) over a canonical byte string, so two requests
that differ only in JSON field order, whitespace, or panel member
declaration order hash identically:

* score requests: ``(context, panel model id, canonicalized messages,
  candidate choice set, sampling params)`` — the model component is the
  panel's content-addressed ``id`` whenever the request carries an inline
  panel (member order and default-value noise already canonicalized by
  ``into_model_validate``), the 22-char id itself for registry
  references;
* embedding rows: ``(model id, truncation window, text)`` — one key per
  row, so the batcher can memoize per row before device dispatch.

Key-space versioning: every fingerprint is prefixed with a ``kind/v1``
tag.  If canonicalization ever changes, bump the tag — a stale disk tier
must miss, never serve a wrong-keyed entry.
"""

from __future__ import annotations

from typing import Optional

from ..identity import IncrementalHasher
from ..utils import jsonutil

SCORE_KEY_VERSION = "score/v1"
EMBED_KEY_VERSION = "embed/v1"

# request fields that must never reach the key: they select the wire
# framing (stream) or the cache policy itself (cache_bypass), not the
# computation
_NON_SEMANTIC_FIELDS = ("stream", "stream_options", "cache_bypass")


def _canonical_model_key(model_param) -> Optional[str]:
    """The content-addressed panel id for any of the four ``model`` request
    forms (clients/score.py fetch_or_validate_score_model), or None when
    the form cannot be resolved without IO surprises (the normal path will
    then raise its usual error — an unfingerprintable request is simply
    uncacheable, never an error here)."""
    from ..identity.model import ModelBase

    if isinstance(model_param, ModelBase):
        try:
            # clone first: callers' params must not observe prepare()'s
            # canonicalization as a side effect of a cache *lookup*
            return model_param.clone().into_model_validate().id
        except Exception:
            return None
    if not isinstance(model_param, str):
        return None
    if len(model_param) == 22:
        return model_param
    slug = model_param.split("/")[-1]
    if len(slug) == 22:
        return slug
    try:
        base = ModelBase.from_json_obj(jsonutil.loads(model_param))
        return base.into_model_validate().id
    except Exception:
        return None


def score_fingerprint(params, ctx: Optional[str] = None) -> Optional[str]:
    """Canonical key for a score request, or None when uncacheable.

    ``ctx`` is the caller's authorization context: results computed under
    one upstream credential are never served to another.
    """
    model_key = _canonical_model_key(params.model)
    if model_key is None:
        return None
    try:
        obj = params.to_json_obj()
    except Exception:
        return None
    for name in _NON_SEMANTIC_FIELDS:
        obj.pop(name, None)
    obj["model"] = model_key
    hasher = IncrementalHasher()
    hasher.write(SCORE_KEY_VERSION)
    hasher.write("\x00")
    hasher.write(ctx or "")
    hasher.write("\x00")
    # the parsed request streams straight into the hasher in bounded
    # chunks — the full canonical string (large message payloads, inline
    # panels) is never materialized; digest bytes are identical to the
    # dumps() form (pinned in tests/test_host_fastpath.py)
    jsonutil.dump_into(obj, hasher.write)
    return hasher.finish_id()


def embed_fingerprint(
    model_id: str, text: str, max_tokens: Optional[int] = None
) -> str:
    """Canonical key for one embedding row.

    The text is hashed byte-exact: tokenizers distinguish codepoint
    sequences that higher-level normalization would conflate, and a false
    hit is strictly worse than a miss.  ``max_tokens`` is part of the key
    because truncation changes the embedding.
    """
    hasher = IncrementalHasher()
    hasher.write(EMBED_KEY_VERSION)
    hasher.write("\x00")
    hasher.write(model_id)
    hasher.write("\x00")
    hasher.write("" if max_tokens is None else str(int(max_tokens)))
    hasher.write("\x00")
    hasher.write(text)
    return hasher.finish_id()
