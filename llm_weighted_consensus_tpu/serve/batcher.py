"""Dynamic micro-batching for the serving device path.

The reference serves concurrent work by fanning judge sub-requests out over
async streams (select_all, score client.rs:343); its "device" is an upstream
HTTP API, so concurrency composes for free.  Here the device is a TPU chip
behind one PJRT queue: K concurrent HTTP requests each dispatching their own
forward pay K host<->device round-trips for work the MXU could do in one
batch.  This module closes that gap (SURVEY §2.8 "DP over candidates" at the
serving edge): handlers submit device work items to a ``DeviceBatcher``,
which collects everything that arrives within a small window (or while a
previous dispatch holds the device) and dispatches each compatible group as
ONE batched device call.

Three work kinds are batched:

* ``embed``       — texts -> (embeddings, token count); R requests' texts are
                    tokenized together and run as one ``embed_tokens`` batch;
* ``consensus``   — N candidate texts -> confidence[N]; R same-shape requests
                    run as one ``consensus_confidence_tokens_many`` dispatch;
* ``stream``      — one streaming-consensus update (embed one candidate into a
                    device-resident buffer + masked revote); R concurrent
                    streams' updates run as one vmapped dispatch
                    (``stream_vote_update_many``).

A single dispatch thread serializes device calls, which is what makes the
window mostly free: while one batch is on device, new arrivals queue and are
dispatched together the moment it returns.  Utilization (queue depth, busy
fraction, items-per-dispatch) is exposed through the metrics provider hook
so the window/batch knobs are tunable from ``GET /metrics``.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np


class _Item:
    __slots__ = ("kind", "key", "payload", "future")

    def __init__(self, kind, key, payload, future):
        self.kind = kind
        self.key = key
        self.payload = payload
        self.future = future


class DeviceBatcher:
    """Collects concurrent device work and dispatches it in fused batches.

    ``window_ms`` bounds the extra latency a lone request pays waiting for
    company; ``max_batch`` bounds items per dispatch (oversized groups are
    chunked).  ``window_ms=0`` still batches whatever accumulates behind an
    in-flight dispatch — only the idle-arrival wait is removed.
    """

    def __init__(
        self,
        embedder,
        metrics=None,
        *,
        window_ms: float = 3.0,
        max_batch: int = 64,
    ) -> None:
        self.embedder = embedder
        self.metrics = metrics
        self.window_ms = float(window_ms)
        self.max_batch = int(max_batch)
        self._pending: list = []
        self._flusher: Optional[asyncio.Task] = None
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="lwc-device"
        )
        # recent device-dispatch intervals, for the busy-fraction gauge
        self._busy: deque = deque(maxlen=1024)
        self._inflight_since: Optional[float] = None
        self._started = time.perf_counter()
        self._dispatches = 0
        self._items = 0
        if metrics is not None:
            metrics.register_provider("device_batcher", self.utilization)

    # -- public async API ----------------------------------------------------

    async def embed(self, texts: list, max_tokens: Optional[int] = None):
        """texts -> (embeddings[N, H] f32, token_count).  Batches with every
        other embed request sharing the same ``max_tokens`` cap."""
        return await self._submit(
            "embed", ("embed", max_tokens), (list(texts), max_tokens)
        )

    async def consensus(self, texts: list, temperature: float = 0.05):
        """N candidate texts -> confidence[N] (embed + cosine consensus vote
        in one fused dispatch).  Batches with same-N same-temperature
        requests via ``consensus_confidence_tokens_many``."""
        return await self._submit(
            "consensus",
            ("consensus", len(texts), float(temperature)),
            (list(texts), temperature),
        )

    async def stream_update(
        self, text: str, buf, valid, position: int, temperature: float = 0.05
    ):
        """One streaming-consensus update -> (buf, valid, confidence[CAP]).
        Batches with updates from other live streams at the same capacity
        bucket (vmapped embed + scatter + masked revote)."""
        return await self._submit(
            "stream",
            ("stream", int(buf.shape[0]), float(temperature)),
            (text, buf, valid, position, temperature),
        )

    def close(self) -> None:
        self._executor.shutdown(wait=False)

    # -- observability (SURVEY §5 metrics row: "device util") -----------------

    def utilization(self, window_sec: float = 60.0) -> dict:
        now = time.perf_counter()
        lo = now - window_sec
        busy = sum(
            max(0.0, min(end, now) - max(start, lo))
            for start, end in self._busy
        )
        if self._inflight_since is not None:
            busy += now - max(self._inflight_since, lo)
        span = max(min(window_sec, now - self._started), 1e-9)
        return {
            "queue_depth": len(self._pending),
            "busy_fraction": round(min(busy / span, 1.0), 4),
            "dispatches": self._dispatches,
            "items": self._items,
            "items_per_dispatch": round(
                self._items / self._dispatches, 2
            )
            if self._dispatches
            else 0.0,
            "window_ms": self.window_ms,
            "max_batch": self.max_batch,
        }

    # -- internals -----------------------------------------------------------

    async def _submit(self, kind, key, payload):
        loop = asyncio.get_running_loop()
        future = loop.create_future()
        self._pending.append(_Item(kind, key, payload, future))
        if self._flusher is None or self._flusher.done():
            self._flusher = loop.create_task(self._drain())
        return await future

    async def _drain(self) -> None:
        loop = asyncio.get_running_loop()
        if self.window_ms > 0:
            # the accumulation window: lone arrivals wait this long for
            # company; arrivals during a dispatch skip it (they already
            # waited behind the device)
            await asyncio.sleep(self.window_ms / 1000.0)
        while self._pending:
            batch, self._pending = self._pending, []
            for group in self._group(batch):
                t0 = time.perf_counter()
                self._inflight_since = t0
                try:
                    results = await loop.run_in_executor(
                        self._executor, self._dispatch, group
                    )
                except Exception as e:
                    for item in group:
                        if not item.future.done():
                            item.future.set_exception(e)
                    self._observe(group, t0, error=True)
                else:
                    for item, result in zip(group, results):
                        if not item.future.done():
                            item.future.set_result(result)
                    self._observe(group, t0, error=False)

    def _observe(self, group, t0, *, error: bool) -> None:
        end = time.perf_counter()
        self._inflight_since = None
        self._busy.append((t0, end))
        self._dispatches += 1
        self._items += len(group)
        if self.metrics is not None:
            self.metrics.observe(
                f"device:batch:{group[0].kind}",
                (end - t0) * 1e3,
                error=error,
            )

    def _group(self, batch: list):
        """Compatible-work groups, arrival order preserved, each at most
        ``max_batch`` items."""
        groups: dict = {}
        order = []
        for item in batch:
            if item.key not in groups:
                groups[item.key] = []
                order.append(item.key)
            groups[item.key].append(item)
        for key in order:
            items = groups[key]
            for i in range(0, len(items), self.max_batch):
                yield items[i : i + self.max_batch]

    # -- dispatch implementations (device thread) ------------------------------

    def _dispatch(self, group: list) -> list:
        return getattr(self, "_dispatch_" + group[0].kind)(group)

    def _dispatch_embed(self, group: list) -> list:
        max_tokens = group[0].payload[1]
        texts: list = []
        counts = []
        for item in group:
            t, _ = item.payload
            texts.extend(t)
            counts.append(len(t))
        ids, mask = self.embedder.tokenize(texts, max_tokens)
        emb = self.embedder.embed_tokens(ids, mask)
        tokens = mask.sum(axis=1)
        out = []
        start = 0
        for count in counts:
            out.append(
                (
                    emb[start : start + count],
                    int(tokens[start : start + count].sum()),
                )
            )
            start += count
        return out

    def _dispatch_consensus(self, group: list) -> list:
        texts0, temperature = group[0].payload
        n = len(texts0)
        if len(group) == 1:
            return [
                np.asarray(
                    self.embedder.consensus_confidence(
                        texts0, temperature=temperature
                    )
                )
            ]
        all_texts = [t for item in group for t in item.payload[0]]
        ids, mask = self.embedder.tokenize(all_texts)
        r = len(group)
        conf = np.asarray(
            self.embedder.consensus_confidence_tokens_many(
                ids.reshape(r, n, -1), mask.reshape(r, n, -1), temperature
            )
        )
        return [conf[i] for i in range(r)]

    def _dispatch_stream(self, group: list) -> list:
        if len(group) == 1:
            text, buf, valid, position, temperature = group[0].payload
            return [
                self.embedder.stream_vote_update(
                    text, buf, valid, position, temperature
                )
            ]
        texts = [item.payload[0] for item in group]
        bufs = [item.payload[1] for item in group]
        valids = [item.payload[2] for item in group]
        positions = [item.payload[3] for item in group]
        temperature = group[0].payload[4]
        out_bufs, out_valids, confs = self.embedder.stream_vote_update_many(
            texts, bufs, valids, positions, temperature
        )
        return [
            (out_bufs[i], out_valids[i], confs[i]) for i in range(len(group))
        ]
