"""Dynamic micro-batching for the serving device path.

The reference serves concurrent work by fanning judge sub-requests out over
async streams (select_all, score client.rs:343); its "device" is an upstream
HTTP API, so concurrency composes for free.  Here the device is a TPU chip
behind one PJRT queue: K concurrent HTTP requests each dispatching their own
forward pay K host<->device round-trips for work the MXU could do in one
batch.  This module closes that gap (SURVEY §2.8 "DP over candidates" at the
serving edge): handlers submit device work items to a ``DeviceBatcher``,
which collects everything that arrives within a small window (or while a
previous dispatch holds the device) and dispatches each compatible group as
ONE batched device call.

Three work kinds are batched:

* ``embed``       — texts -> (embeddings, token count); R requests' texts are
                    tokenized together and run as one ``embed_tokens`` batch;
* ``consensus``   — N candidate texts -> confidence[N]; R same-shape requests
                    run as one ``consensus_confidence_tokens_many`` dispatch;
* ``stream``      — one streaming-consensus update (embed one candidate into a
                    device-resident buffer + masked revote); R concurrent
                    streams' updates run as one vmapped dispatch
                    (``stream_vote_update_many``).

Dispatches are PIPELINED to ``pipeline_depth`` in flight (default 2), and
the pipeline is asynchronous end to end (ISSUE 13):

* **submit time** — each item's tokenization (and packed pack-plan) runs
  in a small host worker pool (``HOST_TOKENIZER_WORKERS``) the moment it
  is submitted, so ``_dispatch_*`` only concatenates pre-built rows;
* **dispatch thread** — pads into reusable staging buffers, starts the
  ``device_put`` (baked batch sharding in mesh mode), and returns as
  soon as the PJRT call is ENQUEUED (models/dispatch_seam.py) — group
  k+1's staging genuinely overlaps group k's device execution, even
  with ``METRICS_DEVICE_TIMING=1``;
* **waiter thread** — blocks on the enqueued outputs, records the
  per-bucket device time + the ``overlap`` gauge interval, recycles the
  staging buffers, and materializes per-item results.  Device faults
  surface here and feed the same meshfault triage as dispatch-thread
  ones.

XLA orders the device work on its stream, so results are unaffected;
arrivals while every slot is busy queue and ride the next group.
Utilization (queue depth, busy fraction, items-per-dispatch) is exposed
through the metrics provider hook so the window/batch knobs are tunable
from ``GET /metrics``.
"""

from __future__ import annotations

import asyncio
import functools
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from ..models import dispatch_seam as _seam


class _Item:
    __slots__ = (
        "kind", "key", "payload", "future", "deadline", "span",
        "redispatches", "submitted", "prepared", "lane",
    )

    def __init__(
        self, kind, key, payload, future, deadline=None, span=None,
        lane="latency",
    ):
        self.kind = kind
        self.key = key
        self.payload = payload
        self.future = future
        # priority class (ISSUE 20): "latency" rides the request path,
        # "offline" (train/ feed work) dispatches only when the latency
        # lane has no ready group — the two queues never mix in a group
        self.lane = lane
        # enqueue timestamp: _run_group attributes (dispatch start -
        # submitted) to the ``batcher_queue`` phase per item, including
        # any fault re-queue wait (obs/phases.py)
        self.submitted = time.perf_counter()
        # the request's propagated deadline (resilience/deadline.py),
        # captured at submit so the pre-dispatch shed can drop work
        # that can no longer finish in time
        self.deadline = deadline
        # the request's batcher span (obs/), captured at submit like the
        # deadline: the flusher and _run_group run in long-lived tasks
        # whose ambient context is stale, so device timing children hang
        # off this explicit handle instead of contextvars
        self.span = span
        # times this item was re-queued after a classified device fault
        # (resilience/meshfault.py) — bounded so a fault loop can never
        # recycle one item forever
        self.redispatches = 0
        # submit-time tokenization (HOST_TOKENIZER_WORKERS): a future
        # resolving to this item's pre-built rows (padded kinds) or its
        # packed plan; None when the pool is off or the kind streams
        self.prepared = None


class _StagedGroup:
    """What the dispatch hop hands the waiter hop: the group's deferred-
    readiness sink (pending device dispatches + checked-out staging
    buffers) and the finalize closure that materializes per-item results
    after readiness."""

    __slots__ = ("sink", "finalize")

    def __init__(self, sink, finalize) -> None:
        self.sink = sink
        self.finalize = finalize


class DeviceBatcher:
    """Collects concurrent device work and dispatches it in fused batches.

    ``window_ms`` bounds the extra latency a lone request pays waiting for
    company; ``max_batch`` bounds items per dispatch (oversized groups are
    chunked).  ``window_ms=0`` still batches whatever accumulates behind an
    in-flight dispatch — only the idle-arrival wait is removed.
    """

    def __init__(
        self,
        embedder,
        metrics=None,
        *,
        window_ms: float = 3.0,
        max_batch: int = 64,
        pipeline_depth: int = 2,
        max_rows: int = 512,
        embed_cache=None,
        max_queue_depth: int = 0,
        watchdog=None,
        fallback_embedder=None,
        fallback_context=None,
        meshfault=None,
        packing: bool = False,
        packing_row_tokens: int = 512,
        packing_max_rows: int = 8,
        packing_max_segments: int = 64,
        prefix_dedup: bool = True,
        prefix_dedup_min_chars: int = 48,
        host_tokenizer_workers: int = 2,
        staging_buffers: int = 2,
    ) -> None:
        self.embedder = embedder
        self.metrics = metrics
        # continuous batching (PACKING_ENABLED): embed + consensus items
        # share ONE dispatch key and ride the ragged segment-id layout
        # (serve/packing.py) instead of the per-kind padded buckets;
        # opt-in — the padded path stays the default contract.  Works on
        # the single-device embedder AND the first-class mesh mode (its
        # packed dispatch dp-pads the row dim); only the legacy
        # hook-sharded embedders decline (supports_packing).
        self.packing = bool(packing) and bool(
            getattr(embedder, "supports_packing", lambda: False)()
        )
        self.packing_row_tokens = max(16, int(packing_row_tokens))
        self.packing_max_rows = max(1, int(packing_max_rows))
        self.packing_max_segments = max(1, int(packing_max_segments))
        # shared-prefix dedup (PREFIX_DEDUP, packed path only): a
        # consensus request's N candidates usually share the conversation
        # prefix; embed it ONCE as its own segment and compose
        # per-candidate embeddings from (prefix, suffix) part vectors
        self.prefix_dedup = bool(prefix_dedup)
        self.prefix_dedup_min_chars = max(1, int(prefix_dedup_min_chars))
        # packing efficiency accounting (satellite: /metrics): real vs
        # dispatched token slots per path, dedup hits, bucket occupancy.
        # The stats lock exists because these counters mutate on the
        # dispatch executor (pipeline_depth >= 2 workers) while
        # utilization() reads them on the event loop: += on a plain int
        # is read-modify-write, and two workers interleaving it drop
        # increments (registered in analysis/concurrency_model.py)
        self._stats_lock = threading.Lock()
        self._pack_real_tokens = 0
        self._pack_slot_tokens = 0
        self._pad_real_tokens = 0
        self._pad_slot_tokens = 0
        self.prefix_dedup_hits = 0
        self.prefix_dedup_tokens_saved = 0
        self.packed_fallback_items = 0
        self._packed_occupancy: dict = {}
        # bounded queue (ADMISSION_MAX_QUEUE_DEPTH): arrivals beyond
        # this many pending items fail fast with OverloadedError (503)
        # instead of growing the queue without limit; 0 = unbounded
        # (the pre-change behavior)
        self.max_queue_depth = max(0, int(max_queue_depth))
        # device watchdog (resilience/watchdog.py): every dispatch is
        # bracketed begin/end so a hung PJRT call is detected
        self.watchdog = watchdog
        # CPU fallback: while the watchdog holds the device unhealthy,
        # dispatches route to this embedder instead (built against host
        # params); fallback_context() supplies the jax.default_device
        # scope so its computations stay off the wedged device
        self.fallback_embedder = fallback_embedder
        self.fallback_context = fallback_context
        self._use_fallback = False
        # mesh fault domains (resilience/meshfault.py): classifies
        # dispatch failures, injects DEVICE_FAULT_PLAN faults at the
        # _dispatch seam, and downsizes the mesh on persistent loss —
        # the batcher re-queues the failed group's live items onto the
        # new shape instead of failing them
        self.meshfault = meshfault
        self.shed_queue_full = 0
        self.shed_deadline = 0
        self.shed_redispatch_limit = 0
        self.cancelled_items = 0
        self.fallback_dispatches = 0
        # per-kind EWMA of dispatch wall time: the deadline shed drops
        # an item whose remaining budget is below the expected cost
        # (CoDel-flavored: dead-on-arrival work never reaches the MXU)
        self._ewma_ms: dict = {}
        # optional per-row embedding memoization (cache/EmbeddingCache):
        # hot rows resolve before the dispatch path, and identical rows
        # in flight collapse onto one device computation
        self.embed_cache = embed_cache
        self._embed_inflight: dict = {}
        self._embed_collapses = 0
        self.window_ms = float(window_ms)
        self.max_batch = int(max_batch)
        self.pipeline_depth = max(1, int(pipeline_depth))
        # rows (encoder batch entries) per dispatch: a synchronized burst
        # of K requests otherwise forms ONE giant group per drain round,
        # which the pipeline cannot overlap (the next round's group only
        # forms after this one's responses restart the closed loop);
        # chunking by rows turns a burst into pipeline_depth-overlappable
        # sub-dispatches sized for good MXU utilization
        self.max_rows = max(1, int(max_rows))
        # full-mesh capacity, kept so rescale_capacity is idempotent in
        # the scale (downsize 8->4->2 then recovery back to 1.0 restores
        # the configured values exactly)
        self._base_max_rows = self.max_rows
        self._base_max_batch = self.max_batch
        self._pending: list = []
        # offline priority class (ISSUE 20): a separate queue the group
        # planner only draws from when the latency queue is empty.
        # Preemption happens at dispatch boundaries for free — groups
        # are planned one at a time after each pipeline-slot acquire,
        # so a latency arrival waits behind at most the offline
        # dispatches already in flight (<= 1 extra slot wait), never
        # behind queued offline work
        self._pending_offline: list = []
        self._flusher: Optional[asyncio.Task] = None
        self._sem: Optional[asyncio.Semaphore] = None
        # set by _submit so a parked _drain starts new work immediately
        # instead of waiting out an in-flight dispatch
        self._wake: Optional[asyncio.Event] = None
        self._executor = ThreadPoolExecutor(
            max_workers=self.pipeline_depth,
            thread_name_prefix="lwc-device",
        )
        # the readiness waiters (dispatch_seam.py): one hop per in-flight
        # group blocks on its enqueued outputs OFF the dispatch thread,
        # so sizing matches the pipeline depth exactly
        self._waiters = ThreadPoolExecutor(
            max_workers=self.pipeline_depth,
            thread_name_prefix="lwc-waiter",
        )
        # submit-time tokenization pool (HOST_TOKENIZER_WORKERS; 0 =
        # tokenize on the dispatch thread, the pre-ISSUE-13 behavior)
        self.host_tokenizer_workers = max(0, int(host_tokenizer_workers))
        self._tok_pool = (
            ThreadPoolExecutor(
                max_workers=self.host_tokenizer_workers,
                thread_name_prefix="lwc-hosttok",
            )
            if self.host_tokenizer_workers > 0
            else None
        )
        # size the embedder's staging-buffer pool (STAGING_BUFFERS; the
        # waiter recycles buffers through it at readiness)
        self.staging_buffers = max(0, int(staging_buffers))
        pool = getattr(embedder, "staging_pool", None)
        if pool is not None:
            pool.per_bucket = self.staging_buffers
        # recent device-dispatch intervals, for the busy-fraction gauge
        self._busy: deque = deque(maxlen=1024)
        # (start time, lane) of dispatches currently in flight
        self._inflight: dict = {}
        self._started = time.perf_counter()
        self._dispatches = 0
        self._items = 0
        # per-lane accounting (ISSUE 20): dispatches/items counters plus
        # a busy-interval ring per priority class, so /metrics exposes
        # per-class utilization/occupancy.  Event-loop-only like the
        # combined counters above — _observe is the sole writer — so no
        # lock (and no concurrency_model.py registry row) is needed
        self._lane_dispatches = {"latency": 0, "offline": 0}
        self._lane_items = {"latency": 0, "offline": 0}
        self._lane_busy = {
            "latency": deque(maxlen=1024),
            "offline": deque(maxlen=1024),
        }
        if metrics is not None:
            metrics.register_provider("device_batcher", self.utilization)
            if embed_cache is not None:
                metrics.register_provider(
                    "embed_cache", self._embed_cache_stats
                )

    def _embed_cache_stats(self) -> dict:
        stats = self.embed_cache.stats()
        stats["inflight_collapses"] = self._embed_collapses
        return stats

    # -- public async API ----------------------------------------------------

    async def embed(
        self,
        texts: list,
        max_tokens: Optional[int] = None,
        priority: str = "latency",
    ):
        """texts -> (embeddings[N, H] f32, token_count).  Batches with every
        other embed request sharing the same ``max_tokens`` cap.

        With an ``embed_cache`` attached, rows resolve individually
        BEFORE batching: cached rows skip the device entirely, rows
        already being computed by a concurrent request are joined rather
        than recomputed, and only genuinely new rows ride a dispatch.
        The public contract is unchanged either way.

        ``priority="offline"`` routes the item through the offline
        class: it dispatches only when the latency lane has no ready
        group (train/ feed work riding an otherwise-idle device)."""
        texts = list(texts)
        if await self._route_ring(texts, max_tokens):
            # over-length request on a sequence-parallel mesh: the ring
            # dispatch serves the FULL text where the dense path would
            # truncate at max_tokens.  Bypasses the embed cache — its
            # fingerprints assume dense truncation semantics, and a
            # full-length vector under the same (text, cap) key would
            # poison dense hits (and vice versa).
            emb, row_tokens = await self._submit(
                "ring_embed",
                ("ring_embed", max_tokens),
                (texts, max_tokens),
                priority=priority,
            )
            return emb, int(np.asarray(row_tokens).sum())
        key = self._embed_key(max_tokens)
        cache = self.embed_cache
        if cache is None or not cache.enabled or not texts:
            emb, row_tokens = await self._submit(
                "embed", key, (texts, max_tokens), priority=priority
            )
            return emb, int(np.asarray(row_tokens).sum())
        from ..cache.fingerprint import embed_fingerprint

        model_id = getattr(self.embedder, "model_name", "") or ""
        rows: list = [None] * len(texts)
        joins: list = []  # (row position, future) — ours or a peer's
        submit_fps: list = []
        submit_texts: list = []
        loop = asyncio.get_running_loop()
        for i, text in enumerate(texts):
            fp = embed_fingerprint(model_id, text, max_tokens)
            hit = cache.get(fp)
            if hit is not None:
                rows[i] = hit
                continue
            fut = self._embed_inflight.get(fp)
            if fut is not None:
                # identical row already being computed (by a concurrent
                # request, or earlier in THIS text list): join it
                self._embed_collapses += 1
                joins.append((i, fut))
                continue
            fut = loop.create_future()
            self._embed_inflight[fp] = fut
            submit_fps.append(fp)
            submit_texts.append(text)
            joins.append((i, fut))
        if submit_texts:
            try:
                emb, row_tokens = await self._submit(
                    "embed",
                    key,
                    (submit_texts, max_tokens),
                    priority=priority,
                )
            except BaseException as e:
                for fp in submit_fps:
                    fut = self._embed_inflight.pop(fp, None)
                    if fut is not None and not fut.done():
                        fut.set_exception(e)
                        fut.exception()  # joined peers re-raise; lone
                        # futures must not warn "never retrieved"
                raise
            row_tokens = np.asarray(row_tokens)
            for j, fp in enumerate(submit_fps):
                vec = np.asarray(emb[j])
                cache.put_row(fp, vec, int(row_tokens[j]))
                fut = self._embed_inflight.pop(fp, None)
                if fut is not None and not fut.done():
                    fut.set_result((vec, int(row_tokens[j])))
        retry: list = []
        for i, fut in joins:
            try:
                # shielded: this caller's cancellation must not poison a
                # future other requests are also joined on
                rows[i] = await asyncio.shield(fut)
            except BaseException:
                if (
                    fut.done()
                    and not fut.cancelled()
                    and fut.exception() is not None
                ):
                    retry.append(i)  # the peer's dispatch failed —
                    # recompute rather than inherit its fate
                else:
                    raise  # this caller itself was cancelled
        if retry:
            emb, row_tokens = await self._submit(
                "embed",
                key,
                ([texts[i] for i in retry], max_tokens),
                priority=priority,
            )
            row_tokens = np.asarray(row_tokens)
            for j, i in enumerate(retry):
                rows[i] = (np.asarray(emb[j]), int(row_tokens[j]))
        return (
            np.stack([r[0] for r in rows]).astype(np.float32, copy=False),
            int(sum(r[1] for r in rows)),
        )

    async def consensus(
        self,
        texts: list,
        temperature: float = 0.05,
        priority: str = "latency",
    ):
        """N candidate texts -> (confidence[N], token_count): embed +
        cosine consensus vote in one fused dispatch, with the prompt
        token count from the SAME tokenization (callers must not
        re-tokenize on the event loop for usage accounting).  Batches
        with same-N same-temperature requests via
        ``consensus_confidence_tokens_many`` — or, with packing enabled,
        with EVERY other packed-eligible item regardless of N and
        temperature (the packed dispatch votes per item on host).

        Over-length candidate sets on a sequence-parallel mesh route to
        the ring dispatch instead (full-length scoring, no truncation)
        — bypassing the packed key too: a packed row is capped at the
        dense window, so an over-length segment can never ride it."""
        texts = list(texts)
        if await self._route_ring(texts):
            return await self._submit(
                "ring_vote",
                ("ring_vote", len(texts), float(temperature)),
                (texts, temperature),
                priority=priority,
            )
        key = (
            ("packed",)
            if self.packing
            else ("consensus", len(texts), float(temperature))
        )
        return await self._submit(
            "consensus",
            key,
            (texts, temperature),
            priority=priority,
        )

    def _embed_key(self, max_tokens):
        """Grouping key for embed items: packed mode groups across
        max_tokens caps (each item tokenizes under its own cap on the
        device thread); the padded path tokenizes the whole group with
        one cap, so the cap stays in the key."""
        if self.packing:
            return ("packed",)
        return ("embed", max_tokens)

    async def _route_ring(
        self, texts: list, max_tokens: Optional[int] = None
    ) -> bool:
        """Whether this request should ride the long-context ring
        dispatch: the embedder serves a sequence-parallel mesh AND at
        least one text exceeds the dense token window.

        The gateway never sends a length cap, so routing keys off the
        ACTUAL text length.  Two tiers keep the common case free:
        ``len(text) + 2`` is an upper bound on the wordpiece token count
        (every token consumes >= 1 character, plus [CLS]/[SEP]), so any
        request under the window in characters is dense with zero extra
        work; only plausibly-long requests pay a precise tokenization,
        run OFF the event loop on the host tokenizer pool.  An explicit
        ``max_tokens`` at or under the dense window is an intentional
        truncation request and stays dense."""
        embedder = self.embedder
        if not texts or not getattr(
            embedder, "ring_available", lambda: False
        )():
            return False
        cap = embedder.max_tokens
        if max_tokens is not None and int(max_tokens) <= cap:
            return False
        if all(len(t) + 2 <= cap for t in texts):
            return False
        loop = asyncio.get_running_loop()

        def over_length() -> bool:
            _, mask = embedder.tokenize_ring(texts, max_tokens)
            return int(mask.sum(axis=1).max(initial=0)) > cap

        return await loop.run_in_executor(self._tok_pool, over_length)

    async def stream_update(
        self,
        text: str,
        buf,
        valid,
        position: int,
        temperature: float = 0.05,
        want_conf: bool = True,
    ):
        """One streaming-consensus update -> (buf, valid, confidence[CAP]).
        Batches with updates from other live streams at the same capacity
        bucket (vmapped embed + scatter + masked revote).

        ``want_conf=False`` skips the host confidence fetch (conf returns
        None): a stream folding K candidates in one burst reads only the
        LAST confidence, and K synchronous link round-trips for discarded
        intermediates would undo the batching win."""
        return await self._submit(
            "stream",
            ("stream", int(buf.shape[0]), float(temperature)),
            (text, buf, valid, position, temperature, want_conf),
        )

    def close(self) -> None:
        self._executor.shutdown(wait=False)
        self._waiters.shutdown(wait=False)
        if self._tok_pool is not None:
            self._tok_pool.shutdown(wait=False)

    # -- overload / lifecycle hooks -------------------------------------------

    def use_fallback(self, active: bool) -> None:
        """Route dispatches to the CPU fallback embedder (watchdog
        on_trip) or back to the device (on_recover).  A bare flag read
        by the dispatch path; no-op without a fallback embedder."""
        self._use_fallback = bool(active)

    def rescale_capacity(self, scale: float) -> None:
        """Scale per-dispatch capacity to the surviving chip fraction
        (a MeshFaultManager rescale hook): a half-size mesh gets half
        the encoder rows per group, so dispatch wall time — and the
        deadline-shed EWMA feeding on it — stays roughly flat through a
        downsize.  scale=1.0 restores the configured capacity exactly."""
        scale = max(0.0, float(scale))
        self.max_rows = max(1, int(self._base_max_rows * scale))
        self.max_batch = max(1, int(self._base_max_batch * scale))

    def idle(self) -> bool:
        """No pending items (either priority class) and no dispatch in
        flight."""
        return (
            not self._pending
            and not self._pending_offline
            and not self._inflight
            and (self._flusher is None or self._flusher.done())
        )

    async def drain(self, timeout_sec: float) -> bool:
        """Wait (bounded) for every queued item to dispatch and every
        dispatch to finish; True = the queue drained clean.  The drain
        path in serve/lifecycle.py calls this after admission stops —
        nothing new arrives, so the wait is monotone."""
        deadline = time.perf_counter() + max(0.0, float(timeout_sec))
        while not self.idle():
            if time.perf_counter() >= deadline:
                return self.idle()
            await asyncio.sleep(0.005)
        return True

    # -- observability (SURVEY §5 metrics row: "device util") -----------------

    def utilization(self, window_sec: float = 60.0) -> dict:
        now = time.perf_counter()
        lo = now - window_sec
        span = max(min(window_sec, now - self._started), 1e-9)

        def busy_fraction(intervals, inflight_lane=None):
            busy = sum(
                max(0.0, min(end, now) - max(start, lo))
                for start, end in intervals
            )
            for start, lane in self._inflight.values():
                if inflight_lane is None or lane == inflight_lane:
                    busy += now - max(start, lo)
            return round(min(busy / span, 1.0), 4)
        # consistent counter snapshot: the dispatch workers mutate these
        # under the same lock; the staging-pool stats() call below stays
        # OUTSIDE it (the pool has its own lock — no nesting, no edge)
        with self._stats_lock:
            pack_real = self._pack_real_tokens
            pack_slot = self._pack_slot_tokens
            pad_real = self._pad_real_tokens
            pad_slot = self._pad_slot_tokens
            dedup_hits = self.prefix_dedup_hits
            dedup_saved = self.prefix_dedup_tokens_saved
            pack_fallback = self.packed_fallback_items
            occupancy = dict(self._packed_occupancy)
            fallback_dispatches = self.fallback_dispatches
        return {
            "queue_depth": len(self._pending),
            "busy_fraction": busy_fraction(self._busy),
            # per-priority-class utilization (ISSUE 20): the offline
            # lane's occupancy is the acceptance gauge for the train/
            # feed drill (>= 90% on an otherwise-idle mesh)
            "lanes": {
                lane: {
                    "queue_depth": len(
                        self._pending
                        if lane == "latency"
                        else self._pending_offline
                    ),
                    "dispatches": self._lane_dispatches[lane],
                    "items": self._lane_items[lane],
                    "busy_fraction": busy_fraction(
                        self._lane_busy[lane], inflight_lane=lane
                    ),
                }
                for lane in ("latency", "offline")
            },
            "dispatches": self._dispatches,
            "items": self._items,
            "items_per_dispatch": round(
                self._items / self._dispatches, 2
            )
            if self._dispatches
            else 0.0,
            "window_ms": self.window_ms,
            "max_batch": self.max_batch,
            # host<->device overlap machinery (ISSUE 13): submit-time
            # tokenization pool size and the embedder's staging-buffer
            # reuse counters (None when the embedder has no pool)
            "host_tokenizer_workers": self.host_tokenizer_workers,
            "staging": (
                self.embedder.staging_pool.stats()
                if getattr(self.embedder, "staging_pool", None) is not None
                else None
            ),
            "max_queue_depth": self.max_queue_depth,
            "shed_queue_full": self.shed_queue_full,
            "shed_deadline": self.shed_deadline,
            "shed_redispatch_limit": self.shed_redispatch_limit,
            "cancelled_items": self.cancelled_items,
            "fallback_active": self._use_fallback,
            "fallback_dispatches": fallback_dispatches,
            # packing-efficiency counters (ISSUE 7): real tokens actually
            # embedded vs device slots dispatched, per path — the padding
            # waste the packed layout exists to reclaim
            "packing": {
                "enabled": self.packing,
                "real_tokens": pack_real,
                "slot_tokens": pack_slot,
                "padding_waste": round(1.0 - pack_real / pack_slot, 4)
                if pack_slot
                else 0.0,
                "prefix_dedup_hits": dedup_hits,
                "prefix_dedup_tokens_saved": dedup_saved,
                "fallback_items": pack_fallback,
                # packed row-bucket B -> device calls at that bucket
                "bucket_occupancy": {
                    str(b): c for b, c in sorted(occupancy.items())
                },
            },
            "padded": {
                "real_tokens": pad_real,
                "slot_tokens": pad_slot,
                "padding_waste": round(1.0 - pad_real / pad_slot, 4)
                if pad_slot
                else 0.0,
            },
        }

    def lane_occupancy(
        self, lane: str, since: float, until: Optional[float] = None
    ) -> float:
        """Fraction of ``[since, until]`` the device had ``lane`` work
        in flight, with overlapping pipelined intervals MERGED (unlike
        the clamped busy-fraction gauge, this is an honest coverage
        measure — the acceptance gauge for the offline-occupancy
        drill).  Event-loop read over event-loop-written state."""
        now = time.perf_counter() if until is None else until
        window = now - since
        if window <= 0:
            return 0.0
        intervals = [
            (max(start, since), min(end, now))
            for start, end in self._lane_busy.get(lane, ())
            if end > since and start < now
        ]
        intervals += [
            (max(start, since), now)
            for start, inflight_lane in self._inflight.values()
            if inflight_lane == lane and start < now
        ]
        if not intervals:
            return 0.0
        intervals.sort()
        covered = 0.0
        cur_lo, cur_hi = intervals[0]
        for lo, hi in intervals[1:]:
            if lo > cur_hi:
                covered += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        covered += cur_hi - cur_lo
        return round(min(covered / window, 1.0), 4)

    # -- internals -----------------------------------------------------------

    async def _submit(self, kind, key, payload, priority="latency"):
        from .. import obs

        offline = priority == "offline"
        # enqueue -> result wall time for THIS request's item; created
        # here (the submitting task still carries the request context)
        span = obs.child_span(
            f"batcher:{kind}",
            queue_depth=len(self._pending),
            **({"lane": "offline"} if offline else {}),
        )
        # the queue-depth shed guards the LATENCY lane only: offline
        # feeders self-limit by awaiting their futures, and shedding
        # background work with a 503 would just make the drill retry it
        if (
            not offline
            and self.max_queue_depth
            and len(self._pending) >= self.max_queue_depth
        ):
            # fail fast at the door: a queue this deep means every item
            # behind it would wait out its deadline anyway (satellite
            # fix for the unbounded deque growth under overload)
            self.shed_queue_full += 1
            if self.metrics is not None:
                self.metrics.observe(
                    "device:shed:queue_full", 0.0, error=True
                )
            if span is not None:
                span.annotate(shed="queue_full")
                span.finish("error")
            from ..errors import OverloadedError

            raise OverloadedError("batcher_queue_full")
        from ..resilience.deadline import current_deadline

        loop = asyncio.get_running_loop()
        future = loop.create_future()
        item = _Item(
            kind,
            key,
            payload,
            future,
            current_deadline(),
            span,
            lane="offline" if offline else "latency",
        )
        if self._tok_pool is not None and kind in (
            "embed", "consensus", "ring_embed", "ring_vote"
        ):
            # submit-time tokenization: the item's rows (or packed plan)
            # build on the host pool NOW, overlapping earlier groups'
            # device time; tokenizer errors park in the future and
            # re-raise on the dispatch thread, same path as before
            try:
                item.prepared = self._tok_pool.submit(
                    self._prepare_item, kind, key, payload
                )
            except RuntimeError:  # pool shut down mid-close
                item.prepared = None
        (self._pending_offline if offline else self._pending).append(item)
        if self._flusher is None or self._flusher.done():
            self._flusher = loop.create_task(self._drain())
        elif self._wake is not None:
            self._wake.set()  # unpark a flusher waiting on in-flight work
        try:
            result = await future
            if span is not None:
                span.finish()
            return result
        except BaseException:
            # the caller is gone (task cancellation, or a GeneratorExit
            # thrown into a streaming generator by the client
            # disconnecting): cancel the item's future so a not-yet-
            # dispatched item is dropped from its group instead of
            # burning device time on work nobody will read
            future.cancel()
            if span is not None:
                span.finish("error")
            raise

    async def _drain(self) -> None:
        loop = asyncio.get_running_loop()
        if self._sem is None:
            self._sem = asyncio.Semaphore(self.pipeline_depth)
            self._wake = asyncio.Event()
        if self.window_ms > 0:
            # the accumulation window: lone arrivals wait this long for
            # company; arrivals during a dispatch skip it (they already
            # waited behind the device)
            await asyncio.sleep(self.window_ms / 1000.0)
        inflight: set = set()
        while self._pending or self._pending_offline or inflight:
            if self._pending or self._pending_offline:
                # bounded pipelining: wait for a dispatch slot FIRST and
                # only then plan ONE group from whatever is pending —
                # continuous admission: items arriving while earlier
                # groups hold the device join the NEXT dispatch group
                # instead of waiting behind a plan made before they
                # existed (the old snapshot-everything drain)
                await self._sem.acquire()
                # the slot is owned here until _run_group takes it:
                # release on every non-handoff exit (shed-to-empty,
                # _shed_group raising) or the pipeline wedges one
                # depth shallower per leak
                handed_off = False
                try:
                    # shed AFTER the slot wait — that queueing delay
                    # is exactly where deadlines die under overload
                    group = self._shed_group(self._next_group())
                    if group:
                        task = loop.create_task(
                            self._run_group(loop, group)
                        )
                        inflight.add(task)
                        task.add_done_callback(inflight.discard)
                        handed_off = True
                finally:
                    if not handed_off:
                        self._sem.release()
            else:
                # park until a dispatch finishes OR a new item arrives
                # (_submit sets the wake event) — a free pipeline slot
                # must start staging new work immediately, not wait out
                # the in-flight device call
                self._wake.clear()
                waker = loop.create_task(self._wake.wait())
                try:
                    await asyncio.wait(
                        {waker, *inflight},
                        return_when=asyncio.FIRST_COMPLETED,
                    )
                finally:
                    waker.cancel()

    @staticmethod
    def _est_kind(item) -> str:
        """The EWMA/metrics series an item's dispatch runs under: packed
        groups mix embed and consensus kinds, so they estimate and report
        as one "packed" series."""
        return "packed" if item.key and item.key[0] == "packed" else item.kind

    def _next_group(self) -> list:
        """Plan ONE dispatch group from the live pending queue: the head
        item's key, joined by every same-key arrival (order preserved) up
        to ``max_batch`` items and the row budget; everything else stays
        pending for the next iteration.  Planning one group at a time —
        AFTER the pipeline-slot wait — is what makes the batcher
        continuous: work that arrives during an in-flight dispatch is in
        ``self._pending`` by the time this runs, so it rides the very
        next group instead of a pre-made plan.

        Consensus groups keep the pow2-chunk policy (``_pow2_chunks``):
        the first chunk dispatches now, the remainder returns to the
        FRONT of the queue (they are the oldest same-key items) and
        dispatches next iteration — same chunk sizes as the snapshot
        drain, one slot apart.

        Priority classes (ISSUE 20): the latency queue is ALWAYS
        planned first; the offline queue contributes a group only when
        no latency item is ready.  Because this selection re-runs after
        every pipeline-slot acquire, an offline backlog yields the very
        next slot to a latency arrival — the offline class can delay
        latency work by at most the dispatch already in flight."""
        from_latency = bool(self._pending)
        pending = self._pending if from_latency else self._pending_offline
        if not pending:
            return []
        key = pending[0].key
        # packed groups are bounded by estimated SEGMENTS (one packed
        # call's worth at a time — the dispatch may still split into
        # multiple bucket calls); padded groups by encoder rows
        row_budget = (
            self.packing_max_rows * self.packing_max_segments
            if key and key[0] == "packed"
            else self.max_rows
        )
        take: list = []
        rest: list = []
        rows = 0
        closed = False  # once one same-key item misses the budget, later
        # same-key items must not jump it (per-key FIFO is the contract)
        for item in pending:
            r = self._rows(item)
            if (
                item.key == key
                and not closed
                and len(take) < self.max_batch
                and (not take or rows + r <= row_budget)
            ):
                take.append(item)
                rows += r
            else:
                if item.key == key:
                    closed = True
                rest.append(item)
        if from_latency:
            self._pending = rest
        else:
            self._pending_offline = rest
        if take and take[0].kind == "consensus" and key[0] == "consensus":
            chunks = list(self._pow2_chunks(take))
            if len(chunks) > 1:
                remainder = [i for c in chunks[1:] for i in c]
                if from_latency:
                    self._pending = remainder + self._pending
                else:
                    self._pending_offline = (
                        remainder + self._pending_offline
                    )
                take = chunks[0]
        return take

    def _shed_group(self, group: list) -> list:
        """Items still worth dispatching: drops items whose caller
        already cancelled (client disconnect), and fails items whose
        propagated deadline is expired — or has less budget left than
        this kind's warm dispatch-time estimate — with 504 (CoDel-style:
        dead work is cheapest to drop the moment before it costs MXU
        time)."""
        live = []
        for item in group:
            if item.future.done():
                # cancelled by a departed caller (_submit's except path)
                self.cancelled_items += 1
                continue
            deadline = item.deadline
            if deadline is not None:
                estimate = self._ewma_ms.get(self._est_kind(item))
                doomed = deadline.expired() or (
                    estimate is not None
                    and deadline.remaining() * 1e3 < estimate
                )
                if doomed:
                    from ..errors import DeadlineExceededError

                    if item.span is not None:
                        # finished by _submit when the exception lands
                        item.span.annotate(shed="deadline")
                    item.future.set_exception(
                        DeadlineExceededError("shed before device dispatch")
                    )
                    self.shed_deadline += 1
                    if self.metrics is not None:
                        self.metrics.observe(
                            "device:shed:deadline", 0.0, error=True
                        )
                    continue
            live.append(item)
        return live

    async def _run_group(self, loop, group) -> None:
        t0 = time.perf_counter()
        token = object()
        self._inflight[token] = (t0, group[0].lane)
        from ..obs import phases as _phases

        for item in group:
            _phases.observe_phase(
                "batcher_queue", (t0 - item.submitted) * 1e3
            )
        # device wall-time children on each traced item's batcher span,
        # bracketing exactly what the watchdog brackets (the executor
        # hop + the PJRT call); the mesh epoch stamps which shape served
        # the dispatch, so a re-dispatched item's span tree shows one
        # child per epoch it touched — and a classified fault hands the
        # SAME stamp to downsize(), which skips the ladder step when the
        # epoch already advanced (two pipelined groups faulting on one
        # dead device must cost one rung, not two)
        epoch = self.meshfault.epoch if self.meshfault is not None else None
        extra = {"mesh_epoch": epoch} if epoch is not None else {}
        dspans = [
            item.span.child(
                "device:dispatch",
                kind=item.kind,
                batch_size=len(group),
                **extra,
            )
            for item in group
            if item.span is not None
        ]
        error = False
        wd_token = (
            self.watchdog.begin(self._est_kind(group[0]))
            if self.watchdog is not None
            else None
        )
        try:
            staged = await loop.run_in_executor(
                self._executor, self._dispatch, group
            )
            # readiness moved OFF the dispatch thread (ISSUE 13): the
            # hop above returns at enqueue, freeing its executor worker
            # to stage the next group; this waiter hop blocks on the
            # enqueued outputs, records device time + overlap intervals,
            # and materializes per-item results
            results = await loop.run_in_executor(
                self._waiters, self._finalize_group, staged
            )
        except Exception as e:
            error = True
            # device-fault triage (resilience/meshfault.py): a classified
            # fault re-queues the group's live items (after a downsize,
            # when the fault is persistent) instead of failing them;
            # ordinary application errors — and anything raised by the
            # CPU twin — keep the fail-the-group path byte-for-byte.
            # Faults now surface on EITHER hop — inject/staging errors on
            # the dispatch thread, device faults at the waiter where
            # readiness reports them — and both land here
            kind = (
                self.meshfault.classify(e)
                if self.meshfault is not None and not self._use_fallback
                else None
            )
            if kind is not None:
                await self._handle_device_fault(loop, kind, e, group, epoch)
            else:
                for item in group:
                    if not item.future.done():
                        item.future.set_exception(e)
            self._observe(group, t0, token, error=True)
        else:
            for item, result in zip(group, results):
                if not item.future.done():
                    item.future.set_result(result)
            self._observe(group, t0, token, error=False)
        finally:
            if wd_token is not None:
                self.watchdog.end(wd_token)
            for dspan in dspans:
                dspan.finish("error" if error else None)
            self._sem.release()

    # each item survives at most this many fault re-queues before it
    # inherits the device exception — a backstop above the natural bound
    # (ladder length x transient retries) so a pathological fault plan
    # can never recycle one item indefinitely
    REDISPATCH_LIMIT = 8

    async def _handle_device_fault(
        self, loop, kind, exc, group, epoch=None
    ) -> None:
        """React to a classified device fault: persistent faults walk
        the downsize ladder (off the event loop — the downsize blocks on
        the shape gate until in-flight dispatches drain, and holds the
        failed dispatch's launch epoch so concurrent faults from one
        dead device step the ladder exactly once); a spent ladder
        flips to the CPU twin — the last resort, per the
        DEVICE_WATCHDOG_CPU_FALLBACK x MESH_ENABLED precedence — and a
        spent ladder WITHOUT a twin fails the group.  Every surviving
        path re-queues the group's live items for re-dispatch on the
        new (or retried) shape."""
        if kind == "persistent":
            ok = await loop.run_in_executor(
                self._executor,
                functools.partial(
                    self.meshfault.downsize, observed_epoch=epoch
                ),
            )
            if not ok:
                if self.fallback_embedder is not None:
                    self.use_fallback(True)
                else:
                    for item in group:
                        if not item.future.done():
                            item.future.set_exception(exc)
                    return
        self._requeue(group, exc)

    def _requeue(self, group, exc) -> None:
        """Put a faulted group's items back at the FRONT of the pending
        queue (they are the oldest work), bounded by their propagated
        deadlines — an item past budget sheds 504 here exactly as the
        pre-dispatch shed does — and by REDISPATCH_LIMIT."""
        from ..errors import DeadlineExceededError

        live = []
        for item in group:
            if item.future.done():
                self.cancelled_items += 1
                continue
            if item.deadline is not None and item.deadline.expired():
                if item.span is not None:
                    item.span.annotate(shed="deadline")
                item.future.set_exception(
                    DeadlineExceededError("deadline expired during re-dispatch")
                )
                self.shed_deadline += 1
                if self.metrics is not None:
                    self.metrics.observe(
                        "device:shed:deadline", 0.0, error=True
                    )
                continue
            if item.redispatches >= self.REDISPATCH_LIMIT:
                # observable like the adjacent deadline shed: a fault
                # loop exhausting items must show up in /metrics, not
                # only as client-side errors
                if item.span is not None:
                    item.span.annotate(shed="redispatch_limit")
                item.future.set_exception(exc)
                self.shed_redispatch_limit += 1
                if self.metrics is not None:
                    self.metrics.observe(
                        "device:shed:redispatch", 0.0, error=True
                    )
                continue
            item.redispatches += 1
            live.append(item)
        if not live:
            return
        # items return to the FRONT of their own lane's queue: a faulted
        # offline group must not jump the latency class on re-dispatch
        offline = [i for i in live if i.lane == "offline"]
        latency = [i for i in live if i.lane != "offline"]
        if latency:
            self._pending[:0] = latency
        if offline:
            self._pending_offline[:0] = offline
        self.meshfault.note_redispatch(len(live))
        if self._wake is not None:
            self._wake.set()

    def _observe(self, group, t0, token, *, error: bool) -> None:
        end = time.perf_counter()
        self._inflight.pop(token, None)
        # overlapping pipelined intervals can double-count; the busy
        # fraction gauge clamps at 1.0, which is the honest reading of
        # "the device path has work in flight"
        self._busy.append((t0, end))
        self._dispatches += 1
        self._items += len(group)
        lane = group[0].lane
        self._lane_dispatches[lane] += 1
        self._lane_items[lane] += len(group)
        self._lane_busy[lane].append((t0, end))
        series = self._est_kind(group[0])
        if not error:
            # warm per-kind dispatch-time estimate for the deadline shed
            ms = (end - t0) * 1e3
            prev = self._ewma_ms.get(series)
            self._ewma_ms[series] = (
                ms if prev is None else 0.8 * prev + 0.2 * ms
            )
        if self.metrics is not None:
            # exemplar: the first traced item in the group links this
            # series to a concrete span tree (explicit handle — ambient
            # reads would see the flusher task's stale context)
            trace_id = next(
                (
                    item.span.trace.trace_id
                    for item in group
                    if item.span is not None
                ),
                None,
            )
            self.metrics.observe(
                f"device:batch:{series}",
                (end - t0) * 1e3,
                error=error,
                trace_id=trace_id,
            )

    @staticmethod
    def _rows(item) -> int:
        """Encoder rows one item contributes to its dispatch."""
        if item.kind in ("embed", "consensus", "ring_embed", "ring_vote"):
            return max(1, len(item.payload[0]))
        return 1  # stream: one new candidate per update

    def _group(self, batch: list):
        """Compatible-work groups, arrival order preserved, each at most
        ``max_batch`` items AND ``max_rows`` encoder rows (so one burst
        splits into pipeline-overlappable dispatches).

        Consensus groups whose pow2-bucket padding would waste more than
        a quarter of the device rows are additionally split into
        power-of-two chunks (9 -> 8+1): the consensus device path buckets
        the request dimension to the next power of two (a full-encoder
        jit specialization per bucket, so buckets must stay coarse), and
        e.g. a 9-request group padded to 16 would burn 44% of its rows
        embedding [PAD] slots.  Chunks reuse the already-compiled
        specializations and pipeline (``pipeline_depth``); mild padding
        (<=25%) is kept whole because an extra dispatch costs a pipeline
        slot (~a link round-trip on a tunnel) — not worth a few pad rows
        (r4 code-review finding)."""
        groups: dict = {}
        order = []
        for item in batch:
            if item.key not in groups:
                groups[item.key] = []
                order.append(item.key)
            groups[item.key].append(item)
        for key in order:
            items = groups[key]
            group: list = []
            rows = 0
            for item in items:
                r = self._rows(item)
                if group and (
                    len(group) >= self.max_batch
                    or rows + r > self.max_rows
                ):
                    yield from self._pow2_chunks(group)
                    group, rows = [], 0
                group.append(item)
                rows += r
            if group:
                yield from self._pow2_chunks(group)

    @staticmethod
    def _pow2_chunks(group: list):
        """Split a group into pow2-sized chunks wherever the padded
        single dispatch would waste >25% of its rows; otherwise pass it
        through whole (see _group docstring for the trade).  Only the
        consensus kind benefits: embed batches pad total ROWS, not
        items, and the stream path's R bucket has a minimum of 16, so
        chunking small stream groups would strictly ADD padding and
        dispatches."""
        if group[0].kind != "consensus":
            yield group
            return
        start = 0
        remaining = len(group)
        from ..utils import next_pow2

        while remaining:
            bucket = next_pow2(remaining)
            if (bucket - remaining) * 4 <= bucket:
                # <=25% padding: one dispatch beats extra round-trips
                yield group[start:]
                return
            size = bucket // 2  # largest pow2 below remaining
            yield group[start : start + size]
            start += size
            remaining -= size

    # -- dispatch implementations (device thread) ------------------------------

    def _dispatch(self, group: list):
        """Stage-and-enqueue hop: returns a plain result list on the
        fallback paths, or a ``_StagedGroup`` whose device work is
        ENQUEUED but not awaited — ``_finalize_group`` (waiter hop)
        finishes it."""
        if group[0].key and group[0].key[0] == "packed":
            fn = self._dispatch_packed
        else:
            fn = getattr(self, "_dispatch_" + group[0].kind)
        if self._use_fallback and self.fallback_embedder is not None:
            with self._stats_lock:
                self.fallback_dispatches += 1
            if self.fallback_context is not None:
                # jax.default_device scope: the fallback's computations
                # must stage on the CPU, never queue behind the wedged
                # device dispatch the watchdog tripped on.  No deferral:
                # the twin's results materialize inline, inside the scope
                with self.fallback_context():
                    return fn(group, self.fallback_embedder)()
            return fn(group, self.fallback_embedder)()
        sink = _seam.DispatchSink()
        if self.meshfault is not None:
            # shared side of the shape gate: this dispatch's embedder
            # reads (params, batch_multiple, shardings) are serialized
            # against downsize/try_recover re-shards (the executor has
            # pipeline_depth workers, so "run the re-shard on the
            # executor" alone would NOT serialize them).  The gate
            # releases at ENQUEUE: the PJRT call has captured its
            # buffers by then, so a re-shard swapping ``params`` cannot
            # tear in-flight device work — faults from that work surface
            # at the waiter and classify exactly like dispatch-thread
            # ones.  The DEVICE_FAULT_PLAN seam injects here, on the
            # dispatch thread where a real staging failure would raise;
            # the CPU-twin branch above never injects (the plan models
            # the device tier)
            with self.meshfault.dispatch_guard():
                self.meshfault.maybe_inject()
                with _seam.deferred_readiness(sink):
                    finalize = fn(group, self.embedder)
        else:
            with _seam.deferred_readiness(sink):
                finalize = fn(group, self.embedder)
        return _StagedGroup(sink, finalize)

    def _finalize_group(self, staged):
        """Waiter hop (lwc-waiter thread): block on the group's enqueued
        outputs, record per-bucket device time + the overlap gauge's
        (enqueue, ready) intervals, recycle staging buffers, then run
        the finalize closure (np conversions + per-item splits).  Device
        faults raise here and ride ``_run_group``'s triage."""
        if not isinstance(staged, _StagedGroup):
            return staged  # fallback path: already final
        from ..obs import phases as _phases

        pool = getattr(self.embedder, "staging_pool", None)
        _seam.drain_sink(
            staged.sink,
            observe_device=_phases.observe_device,
            observe_interval=_phases.observe_device_interval,
            release=pool.release if pool is not None else None,
        )
        results = staged.finalize()
        if self.meshfault is not None and not self._use_fallback:
            # the success note moves with readiness: a dispatch only
            # resets the transient-fault streak once its device work
            # actually completed, not merely enqueued
            self.meshfault.note_dispatch_ok()
        return results

    def _prepare_item(self, kind, key, payload):
        """Submit-time host work for one item (lwc-hosttok thread):
        pre-built padded rows for embed/consensus items, or the local-
        index packed plan for packed-key items.  Always runs against the
        PRIMARY embedder's tokenizer; the dispatch falls back to inline
        tokenization when it is serving the CPU twin."""
        if key and key[0] == "packed":
            return self._plan_packed_payload(kind, payload, self.embedder)
        if kind == "embed":
            texts, cap = payload
            return self.embedder.tokenize(texts, cap)
        if kind == "ring_embed":
            texts, cap = payload
            return self.embedder.tokenize_ring(texts, cap)
        if kind == "ring_vote":
            texts, _temperature = payload
            return self.embedder.tokenize_ring(texts)
        texts, _temperature = payload
        return self.embedder.tokenize(texts)

    def _prepared_rows(self, group: list, embedder):
        """Concatenate the group's submit-time tokenized rows into the
        batch group-level ``tokenize`` would have produced: each item's
        rows are padded from its own seq bucket out to the group's
        (fill = the tokenizer pad id, mask 0 — the exact background
        ``encode_batch`` writes), so the result is byte-identical to
        tokenizing the whole group at once.  None when any item lacks
        prepared rows (pool off, CPU twin, mid-close)."""
        if embedder is not self.embedder:
            return None
        rows = []
        for item in group:
            fut = item.prepared
            if fut is None:
                return None
            rows.append(fut.result())  # re-raises tokenizer errors
        width = max(ids.shape[1] for ids, _ in rows)
        if len(rows) == 1:
            return rows[0]
        pad_id = int(
            getattr(getattr(embedder, "tokenizer", None), "pad_id", 0) or 0
        )
        ids_parts, mask_parts = [], []
        for ids, mask in rows:
            gap = width - ids.shape[1]
            if gap:
                ids = np.pad(
                    ids, ((0, 0), (0, gap)), constant_values=pad_id
                )
                mask = np.pad(mask, ((0, 0), (0, gap)))
            ids_parts.append(ids)
            mask_parts.append(mask)
        return np.concatenate(ids_parts), np.concatenate(mask_parts)

    def _dispatch_embed(self, group: list, embedder):
        max_tokens = group[0].payload[1]
        counts = [len(item.payload[0]) for item in group]
        prepared = self._prepared_rows(group, embedder)
        if prepared is not None:
            ids, mask = prepared
        else:
            texts = [t for item in group for t in item.payload[0]]
            ids, mask = embedder.tokenize(texts, max_tokens)
        self._count_padded(embedder, ids, mask)
        emb = embedder.embed_tokens(ids, mask)
        tokens = mask.sum(axis=1)

        def finalize() -> list:
            # waiter hop: emb materializes AFTER readiness (under the
            # deferred scope embed_tokens handed back the device array)
            emb_np = np.asarray(emb)
            out = []
            start = 0
            for count in counts:
                # per-ROW token counts (not the summed total): embed()
                # needs row granularity for the per-row memoization path
                # and sums for the public (emb, total_tokens) contract
                out.append(
                    (
                        emb_np[start : start + count],
                        tokens[start : start + count],
                    )
                )
                start += count
            return out

        return finalize

    def _dispatch_consensus(self, group: list, embedder):
        texts0, temperature = group[0].payload
        n = len(texts0)
        prepared = self._prepared_rows(group, embedder)
        if len(group) == 1:
            if prepared is not None:
                ids, mask = prepared
            else:
                ids, mask = embedder.tokenize(texts0)
            with self._stats_lock:
                self._pad_real_tokens += int(mask.sum())
                self._pad_slot_tokens += int(ids.size)
            conf = embedder.consensus_confidence_tokens(
                ids, mask, temperature
            )
            tok = int(mask.sum())

            def finalize_one() -> list:
                return [(np.asarray(conf), tok)]

            return finalize_one
        if prepared is not None:
            ids, mask = prepared
        else:
            all_texts = [t for item in group for t in item.payload[0]]
            ids, mask = embedder.tokenize(all_texts)
        r = len(group)
        from ..utils import next_pow2

        # the grouped dispatch pads the request dim to its pow2 bucket
        with self._stats_lock:
            self._pad_real_tokens += int(mask.sum())
            self._pad_slot_tokens += int(next_pow2(r) * n * ids.shape[1])
        conf = embedder.consensus_confidence_tokens_many(
            ids.reshape(r, n, -1), mask.reshape(r, n, -1), temperature
        )
        tokens = mask.reshape(r, n, -1).sum(axis=(1, 2))

        def finalize() -> list:
            conf_np = np.asarray(conf)
            return [(conf_np[i], int(tokens[i])) for i in range(r)]

        return finalize

    # -- long-context ring dispatch -------------------------------------------

    def _dispatch_ring_embed(self, group: list, embedder):
        """Over-length embed items -> full-length embeddings via the
        sequence-parallel ring dispatch (``embed_tokens_ring``).  Only
        the primary embedder carries the sp mesh; on the CPU twin the
        group falls back to the dense (truncating) dispatch — degraded
        but serving, the same contract every other kind has there."""
        if not getattr(embedder, "ring_available", lambda: False)():
            return self._dispatch_embed(group, embedder)
        max_tokens = group[0].payload[1]
        counts = [len(item.payload[0]) for item in group]
        prepared = self._prepared_rows(group, embedder)
        if prepared is not None:
            ids, mask = prepared
        else:
            texts = [t for item in group for t in item.payload[0]]
            ids, mask = embedder.tokenize_ring(texts, max_tokens)
        self._count_padded(embedder, ids, mask)
        emb = embedder.embed_tokens_ring(ids, mask)
        tokens = mask.sum(axis=1)

        def finalize() -> list:
            emb_np = np.asarray(emb)
            out = []
            start = 0
            for count in counts:
                out.append(
                    (
                        emb_np[start : start + count],
                        tokens[start : start + count],
                    )
                )
                start += count
            return out

        return finalize

    def _dispatch_ring_vote(self, group: list, embedder):
        """Over-length consensus items -> full-length scoring via the
        fused ring embed + vote (``consensus_confidence_tokens_ring``).
        One device dispatch PER item — there is no grouped ring vote
        (long-context groups are rare and row-heavy; the per-item
        dispatches still pipeline through the shared readiness sink) —
        with the dense (truncating) fallback on the CPU twin."""
        if not getattr(embedder, "ring_available", lambda: False)():
            return self._dispatch_consensus(group, embedder)
        staged = []
        for item in group:
            texts, temperature = item.payload
            fut = item.prepared
            if embedder is self.embedder and fut is not None:
                ids, mask = fut.result()  # re-raises tokenizer errors
            else:
                ids, mask = embedder.tokenize_ring(texts)
            with self._stats_lock:
                self._pad_real_tokens += int(mask.sum())
                self._pad_slot_tokens += int(ids.size)
            conf = embedder.consensus_confidence_tokens_ring(
                ids, mask, temperature
            )
            staged.append((conf, int(mask.sum())))

        def finalize() -> list:
            return [(np.asarray(conf), tok) for conf, tok in staged]

        return finalize

    def _count_padded(self, embedder, ids, mask) -> None:
        """Padded-path efficiency accounting for an embed dispatch: real
        tokens vs the row-bucketed slot count ``embed_tokens`` pads to."""
        try:
            from ..models.embedder import _bucket

            pad_b = _bucket(
                ids.shape[0], getattr(embedder, "MAX_DEVICE_BATCH", 4096)
            )
            # mesh/dp embedders pad the bucket up to the dp multiple too
            pad_b += (-pad_b) % getattr(embedder, "batch_multiple", 1)
        except Exception:
            pad_b = ids.shape[0]
        with self._stats_lock:
            self._pad_real_tokens += int(mask.sum())
            self._pad_slot_tokens += int(pad_b * ids.shape[1])

    # -- packed (continuous-batching) dispatch --------------------------------

    def _dispatch_packed(self, group: list, embedder):
        """One mixed group (embed + consensus items, any N, any cap) ->
        per-item results through the ragged segment-id layout.

        Stage (dispatch thread): collect each item's pack plan — built at
        submit time on the host pool when possible — first-fit pack every
        segment in the group into ("packed", B, L, K) bucket calls, and
        ENQUEUE ``embedder.embed_packed`` per call.  Finalize (waiter
        thread, after readiness): materialize segment vectors, then
        reassemble — embed items gather their per-text vectors; consensus
        items compose candidate vectors (prefix-weighted when deduped)
        and vote ON HOST (``packing.consensus_vote_np`` — numerics-
        matched to the device vote) so mixed-N requests share a dispatch
        without per-N jit specializations.  Items whose sequences exceed
        the packed row fall back to their padded dispatch, staged inside
        this same group."""
        from . import packing as _packing

        if not (
            getattr(embedder, "embed_packed", None) is not None
            and getattr(embedder, "supports_packing", lambda: False)()
        ):
            # e.g. the CPU-fallback or a legacy hook-sharded embedder
            # mid-swap: serve every item through its padded path, one by
            # one (first-class mesh embedders pack fine and never land
            # here)
            staged = [
                self._packed_item_fallback(item, embedder)
                for item in group
            ]
            return lambda: [(np.asarray(a), t) for a, t in staged]
        from ..obs import phases as _phases

        row_tokens = self.packing_row_tokens
        segments: list = []  # ragged int32 token rows, group-global
        # pack_plan phase: ragged tokenization + first-fit packing (the
        # host work BEFORE any device call); submit-time plans make the
        # per-item loop a rebase, inline planning covers the rest.  Runs
        # on the executor thread, so it reports to the lock-guarded
        # global aggregator and stamps each item's batcher span
        # (annotate is a plain dict update — no span creation off the
        # event loop)
        t_plan = time.perf_counter()
        plans = [
            self._plan_packed_item(item, embedder, segments)
            for item in group
        ]
        plan_ms = (time.perf_counter() - t_plan) * 1e3
        # oversized items dispatch their padded path NOW, on the same
        # thread and inside the same guard/deferred scope as the packed
        # calls; their host fetches ride finalize with everything else
        fallback_staged: dict = {}
        for i, plan in enumerate(plans):
            if plan[0] == "fallback":
                with self._stats_lock:
                    self.packed_fallback_items += 1
                fallback_staged[i] = self._packed_item_fallback(
                    group[i], embedder
                )
        seg_vecs: list = [None] * len(segments)
        call_outs: list = []  # (call, enqueued device out) pairs
        if segments:
            t_plan = time.perf_counter()
            calls = _packing.build_calls(
                segments,
                row_tokens,
                self.packing_max_rows,
                self.packing_max_segments,
            )
            plan_ms += (time.perf_counter() - t_plan) * 1e3
            for call in calls:
                out = embedder.embed_packed(
                    call.ids, call.segment_ids, call.positions,
                    call.seg_starts,
                )
                b = call.ids.shape[0]
                with self._stats_lock:
                    self._pack_real_tokens += call.real_tokens
                    self._pack_slot_tokens += call.slot_tokens
                    self._packed_occupancy[b] = (
                        self._packed_occupancy.get(b, 0) + 1
                    )
                call_outs.append((call, out))
        _phases.observe_phase("pack_plan", plan_ms)
        share_plan = plan_ms / len(group)
        for item in group:
            if item.span is not None:
                item.span.annotate(pack_plan_ms=round(share_plan, 3))

        def finalize() -> list:
            for call, out in call_outs:
                out_np = np.asarray(out, np.float32)
                for si, (r, slot) in call.slots.items():
                    seg_vecs[si] = out_np[r, slot]
            # host_tally phase: per-item reassembly + the host-side vote
            # (packing.consensus_vote_np) — waiter-thread work that
            # overlaps the NEXT group's staging and device time
            t_tally = time.perf_counter()
            results: list = [None] * len(group)
            for i, (item, plan) in enumerate(zip(group, plans)):
                if plan[0] == "fallback":
                    a, t = fallback_staged[i]
                    results[i] = (np.asarray(a), t)
                else:
                    results[i] = self._assemble_packed_item(
                        item, plan, segments, seg_vecs, embedder
                    )
            tally_ms = (time.perf_counter() - t_tally) * 1e3
            _phases.observe_phase("host_tally", tally_ms)
            share_tally = tally_ms / len(group)
            for item in group:
                if item.span is not None:
                    item.span.annotate(host_tally_ms=round(share_tally, 3))
            return results

        return finalize

    def _plan_packed_item(self, item, embedder, segments: list):
        """One item's group-global assembly plan: consume the submit-time
        plan when it was built against THIS embedder (the CPU twin's
        tokenizer may differ), else plan inline; extend the group
        segments and apply the dedup counters the pure planner deferred."""
        if embedder is self.embedder and item.prepared is not None:
            plan, rows, stats = item.prepared.result()
        else:
            plan, rows, stats = self._plan_packed_payload(
                item.kind, item.payload, embedder
            )
        base = len(segments)
        segments.extend(rows)
        if stats is not None:
            _, hits, saved = stats
            with self._stats_lock:
                self.prefix_dedup_hits += hits
                self.prefix_dedup_tokens_saved += saved
        return self._rebase_plan(plan, base)

    def _plan_packed_payload(self, kind, payload, embedder):
        """Pure pack planning for one item's payload -> (local plan,
        ragged rows, dedup-stats delta).  Plan segment indices are
        0-based relative to ``rows`` so the plan can build at SUBMIT time
        (host pool), before the item's position in any dispatch group is
        known; ``_plan_packed_item`` rebases it.  Counters are applied
        only when the plan is consumed, so a shed item's speculative plan
        costs nothing observable.  Oversized items plan as
        ("fallback",)."""
        from . import packing as _packing

        row_tokens = self.packing_row_tokens
        seg_cap = min(row_tokens, embedder.max_tokens)
        if kind == "embed":
            texts, cap = payload
            rows = embedder.tokenize_ragged(
                texts, min(cap, seg_cap) if cap else seg_cap
            )
            if any(not 0 < len(r) <= row_tokens for r in rows):
                return (("fallback",), [], None)
            return (("embed", list(range(len(rows)))), rows, None)
        texts, temperature = payload
        prefix = (
            _packing.shared_prefix(texts, self.prefix_dedup_min_chars)
            if self.prefix_dedup
            else None
        )
        if prefix is not None:
            parts = [prefix] + [t[len(prefix) :] for t in texts]
            # empty suffixes (candidate == prefix) embed nothing: their
            # candidate vector IS the prefix vector
            part_texts = [parts[0]] + [s for s in parts[1:] if s]
            rows = embedder.tokenize_ragged(part_texts, seg_cap)
            # a prefix this short is all [CLS]/[SEP] overhead — or the
            # pieces no longer fit the packed row: vote on full texts
            if len(rows[0]) >= 4 and all(
                0 < len(r) <= row_tokens for r in rows
            ):
                seg_iter = iter(range(1, len(rows)))
                suffix_segs = [
                    next(seg_iter) if s else None for s in parts[1:]
                ]
                stats = (
                    "dedup",
                    len(texts) - 1,
                    (len(texts) - 1) * len(rows[0]),
                )
                return (
                    ("consensus_dedup", 0, suffix_segs, temperature),
                    rows,
                    stats,
                )
        rows = embedder.tokenize_ragged(texts, seg_cap)
        if any(not 0 < len(r) <= row_tokens for r in rows):
            return (("fallback",), [], None)
        return (
            ("consensus", list(range(len(rows))), temperature),
            rows,
            None,
        )

    @staticmethod
    def _rebase_plan(plan, base: int):
        """Shift a local-index pack plan's segment indices by ``base``
        (the group-global offset its rows landed at)."""
        if plan[0] == "embed":
            return ("embed", [base + i for i in plan[1]])
        if plan[0] == "consensus_dedup":
            _, prefix_idx, suffix_segs, temperature = plan
            return (
                "consensus_dedup",
                base + prefix_idx,
                [
                    base + si if si is not None else None
                    for si in suffix_segs
                ],
                temperature,
            )
        if plan[0] == "consensus":
            return ("consensus", [base + i for i in plan[1]], plan[2])
        return plan  # ("fallback",)

    def _assemble_packed_item(
        self, item, plan, segments: list, seg_vecs: list, embedder
    ):
        from . import packing as _packing

        if plan[0] == "embed":
            idxs = plan[1]
            emb = np.stack([seg_vecs[i] for i in idxs]).astype(
                np.float32, copy=False
            )
            tokens = np.asarray([len(segments[i]) for i in idxs])
            return (emb, tokens)
        if plan[0] == "consensus_dedup":
            _, prefix_idx, suffix_segs, temperature = plan
            p_vec = seg_vecs[prefix_idx]
            p_tok = len(segments[prefix_idx])
            cand = np.stack(
                [
                    _packing.compose_prefix_suffix(
                        p_vec,
                        p_tok,
                        seg_vecs[si] if si is not None else None,
                        len(segments[si]) if si is not None else 0,
                    )
                    for si in suffix_segs
                ]
            )
            conf = _packing.consensus_vote_np(cand, temperature)
            tokens = p_tok + sum(
                len(segments[si]) for si in suffix_segs if si is not None
            )
            return (conf, int(tokens))
        _, idxs, temperature = plan
        cand = np.stack([seg_vecs[i] for i in idxs])
        conf = _packing.consensus_vote_np(cand, temperature)
        return (conf, int(sum(len(segments[i]) for i in idxs)))

    def _packed_item_fallback(self, item, embedder):
        """Stage one packed-key item through its padded dispatch (the
        packed row cannot hold it, or the embedder cannot pack).  The
        returned (handle, tokens) pair is host-materialized by the
        caller's finalize closure, after readiness."""
        if item.kind == "embed":
            texts, cap = item.payload
            ids, mask = embedder.tokenize(texts, cap)
            self._count_padded(embedder, ids, mask)
            emb = embedder.embed_tokens(ids, mask)
            return (emb, mask.sum(axis=1))
        texts, temperature = item.payload
        ids, mask = embedder.tokenize(texts)
        with self._stats_lock:
            self._pad_real_tokens += int(mask.sum())
            self._pad_slot_tokens += int(ids.size)
        conf = embedder.consensus_confidence_tokens(ids, mask, temperature)
        return (conf, int(mask.sum()))

    def _dispatch_stream(self, group: list, embedder):
        if len(group) == 1:
            text, buf, valid, position, temperature, want = group[0].payload
            out_buf, out_valid, conf = embedder.stream_vote_update(
                text, buf, valid, position, temperature
            )

            def finalize_one() -> list:
                # fetch here, on the waiter thread — a device-resident
                # conf would make the caller's np.asarray stall the
                # event loop for a link round-trip per update
                return [
                    (out_buf, out_valid, np.asarray(conf) if want else None)
                ]

            return finalize_one
        texts = [item.payload[0] for item in group]
        bufs = [item.payload[1] for item in group]
        valids = [item.payload[2] for item in group]
        positions = [item.payload[3] for item in group]
        temperature = group[0].payload[4]
        wants = [item.payload[5] for item in group]
        out_bufs, out_valids, confs = embedder.stream_vote_update_many(
            texts, bufs, valids, positions, temperature
        )

        def finalize() -> list:
            # fetch ALL wanted confidences in ONE transfer here, on the
            # waiter thread: every stream np.asarray's its own
            # confidence right after this returns, and R separate slice
            # fetches would re-serialize the round-trips the batching
            # just fused (R x link RTT per dispatch).  bufs / valids
            # stay device-resident — nobody reads them on host.
            confs_host = np.asarray(confs) if any(wants) else None
            return [
                (
                    out_bufs[i],
                    out_valids[i],
                    confs_host[i] if wants[i] else None,
                )
                for i in range(len(group))
            ]

        return finalize
