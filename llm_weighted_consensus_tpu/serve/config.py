"""Env-first service configuration.

Parity: the reference's 16 env vars (main.rs:3-37) with identical names and
defaults, plus TPU-framework additions (encoder + mesh flags).  ``.env``
loading mirrors dotenv: simple KEY=VALUE lines, environment wins.

TPU additions:

* ``EMBEDDER_MODEL``  — encoder preset: ``bge-{small,base,large}-en`` (CLS
  pooling), ``e5-{small,base,large}-v2`` / ``gte-{small,base,large}``
  (masked-mean pooling — family default applied automatically).  Unset =
  no device side (static weights only).
* ``EMBEDDER_WEIGHTS`` — local checkpoint for the encoder: an HF snapshot
  dir (model.safetensors / pytorch_model.bin), a single weights file, or
  an orbax dir (models/loading.py).  Unset = random init (demo mode).
* ``EMBEDDER_VOCAB``  — path to a WordPiece ``vocab.txt``; defaults to
  the vocab.txt beside EMBEDDER_WEIGHTS when present, else hash-tokenizer
  fallback.
* ``EMBEDDER_QUANTIZE`` — ``int8`` serves the encoder W8A8 on the MXU's
  int8 path (2x bf16 peak; opt-in, accuracy pinned in tests/test_quant.py)
  via the fused Pallas quantized-matmul kernel (activation quant + int8
  matmul + dequant/bias/GELU epilogue in one kernel — ops/kernels.py).
  ``int8-pallas`` / ``int8-xla`` pin the kernel vs the XLA dot_general
  fallback (debugging).  Default ``none``.
* ``EMBEDDER_MAX_TOKENS`` — truncation window.  Default: the model's full
  position table under ``MESH_SP`` (long-context serving must not silently
  truncate), else 512.
* ``MESH_DP`` / ``MESH_TP`` — serve the embedder over a (dp, tp) device
  mesh: batches shard over ``dp``, encoder params Megatron-split over
  ``tp`` (parallel/sharding.py).  Unset = single device.  ``MESH_DP``
  empty + ``MESH_TP=n`` uses every device not consumed by tp for dp.
* ``MESH_SP`` — sequence parallelism: embedding forwards run as ring
  attention over an sp-way mesh (parallel/ring.py), enabling long-context
  inputs (e.g. ``EMBEDDER_MODEL=bert-long-8k``).  Combines with
  ``MESH_DP`` (batch x sequence grid); mutually exclusive with
  ``MESH_TP``.
* ``MESH_ENABLED`` — first-class mesh serving: embed and consensus
  dispatches run on a (dp, tp) ICI mesh with params placed once by the
  partition-rule tables, real input shardings on every dispatch, and
  per-(mesh-shape, bucket) AOT executables — AOT warmup and packing stay
  available, unlike the legacy ``MESH_DP``/``MESH_TP`` hook path, which
  this mode supersedes (mutually exclusive with it and with ``MESH_SP``).
  Off by default: unset leaves the single-device path untouched.
* ``MESH_SHAPE`` — the mesh layout for ``MESH_ENABLED`` as ``DPxTP``
  (e.g. ``4x2`` = batches split 4-way, encoder params 2-way) or
  ``DPxTPxSP`` (e.g. ``2x2x2`` adds a 2-way sequence-parallel axis:
  over-length score/embed requests dispatch as ring attention over
  ``sp`` instead of truncating, parallel/ring.py).  Without the sp
  axis the serving path is byte-identical to the 2-axis form.  Unset
  with ``MESH_ENABLED=1`` uses every local device on ``dp`` (tp=1);
  setting it without ``MESH_ENABLED`` is an error.
* ``LONG_CONTEXT_WARMUP`` — ring AOT buckets as ``NxS`` specs (e.g.
  ``4x4096,1x8192``): with an sp-bearing ``MESH_SHAPE`` these
  long-context consensus/embed shapes compile at startup, so the first
  over-length request pays no trace.  N=1 warms the plain embed path.
  Requires ``MESH_SHAPE=DPxTPxSP``; empty = ring shapes compile lazily.
* ``MULTIHOST`` — set to 1 on each host of a multi-host slice to call
  ``jax.distributed.initialize`` before mesh construction (parallel/dist.py).
* ``COMPILE_CACHE_DIR`` — persistent XLA compilation cache: jit
  specializations compiled on previous runs load from disk, cutting
  cold-start latency (first-request compiles take tens of seconds for
  large encoders).  Unset = in-memory cache only.
* ``PROFILE_DIR`` — arms ``POST /profile/start`` / ``POST /profile/stop``
  and the one-shot ``POST /v1/profile`` (bounded ``duration_ms`` capture
  window, admission-exempt so an overload can be profiled while the gate
  sheds): JAX profiler traces (xprof format, viewable in
  TensorBoard/xprof) are written under this directory.  Unset =
  start/stop disabled (404) and ``/v1/profile`` answers 403.
* ``RM_MODEL`` / ``RM_WEIGHTS`` / ``RM_VOCAB`` / ``RM_MAX_TOKENS`` /
  ``RM_QUANTIZE`` (``int8`` = W8A8 RM serving, default ``none``) — a
  DeBERTa reward model serving ``POST /consensus {"scorer": "rm"}``
  (BASELINE config 3 as a service): candidates re-rank by
  softmax(reward).  Same synthetic-params gate as the embedder; real
  checkpoints load from HF DeBERTa-v2/v3 snapshots or orbax dirs.
* ``ARCHIVE_PATH`` — JSON snapshot for the completions archive
  (checkpoint/resume): loaded at startup when the file exists, saved on
  graceful shutdown.  Unset = in-memory only.
* ``ARCHIVE_WRITE`` — archive every UNARY completion the gateway serves
  (with per-judge ballots and the originating score request, enabling
  logprob re-extraction and training-table learning), making its id
  referenceable in later requests.  Defaults on when ``ARCHIVE_PATH`` is
  set; ``ARCHIVE_WRITE=0`` disables.  ``POST /archive/rescore`` re-tallies
  archived completions on device (weight overrides, optional logprob
  revote, optional write-back).
* ``ARCHIVE_STREAMING`` — with ``ARCHIVE_WRITE``, also archive STREAMED
  completions: the gateway tees each chunk stream into the merge-algebra
  fold and archives the unary form at stream end (``unary =
  fold(chunks)`` — types/base.py).  Off by default: real traffic is
  mostly streaming, so this retains every served response.
* ``ARCHIVE_MAX_COMPLETIONS`` — FIFO cap per archive table (chat / score
  / multichat), bounding a long-running service's memory; evicting a
  score completion drops its ballots + request record.  ``0`` =
  unbounded.  Default 65536.
* ``TABLES_PATH`` — .npz snapshot for the judge training tables: loaded
  at startup when present, saved on graceful shutdown.  With an embedder
  configured, ``POST /weights/learn`` builds rows from the archive into
  the live tables (weights/learning.py).
* ``BATCH_WINDOW_MS`` — the micro-batching accumulation window
  (serve/batcher.py): concurrent requests' device work arriving within
  this window (or behind an in-flight dispatch) is fused into one batched
  device call.  ``0`` disables the idle wait but still batches behind
  in-flight dispatches.  Default 3.
* ``BATCH_MAX`` — max items per fused device dispatch (oversized groups
  chunk).  Default 64.
* ``BATCH_PIPELINE`` — device dispatches allowed in flight concurrently
  (the host side of batch k+1 overlaps batch k's device execution).
  The overlap holds with device timing on too: the dispatch thread
  returns at PJRT enqueue and a waiter thread records readiness
  (models/dispatch_seam.py).  Default 2; 1 = fully serialized.
* ``HOST_TOKENIZER_WORKERS`` — host threads tokenizing (and pack-
  planning) each item at SUBMIT time, so ``_dispatch_*`` only
  concatenates pre-built rows and group k+1's tokenization never rides
  the dispatch thread behind group k.  ``0`` tokenizes on the dispatch
  thread (the pre-overlap behavior).  Default 2.
* ``HOST_FASTPATH`` — host fast lane for the streaming consensus path:
  per-chunk SSE frames are assembled by splicing changed fields into
  precompiled byte templates (serve/frames.py) instead of a full
  ``to_json_obj`` + ``dumps`` per chunk, and the per-push weighted
  tally runs on scaled-int64 numpy vectors (clients/tally.py) with the
  Decimal fold retained as the final-frame authority.  Both lanes fall
  back loudly to the slow path whenever exactness cannot be proven, so
  output bytes are identical either way.  Default ``0`` (off).
* ``STAGING_BUFFERS`` — reusable host staging buffers kept per
  (shape, dtype) bucket for the padded dispatch paths; the batcher's
  waiter recycles each buffer once its transfer is ready instead of
  allocating fresh ``np.pad`` copies per dispatch.  ``0`` disables
  reuse.  Default 2.
* ``WARMUP`` — consensus shapes to pre-compile at startup, e.g.
  ``64x112,64x128`` (``NxS`` pairs): the first request at a shape
  otherwise pays a multi-second jit compile (each (N, seq-bucket) is
  its own XLA specialization); pair with ``COMPILE_CACHE_DIR`` to make
  later restarts near-instant.  Invalid specs fail startup loudly.
* ``WARMUP_R`` — concurrency buckets to ALSO pre-compile for each
  ``WARMUP`` shape through the batcher's grouped path, e.g. ``2,4``:
  the grouped dispatch (``consensus_confidence_tokens_many``) is a
  DISTINCT XLA specialization per power-of-two R bucket, so a warmed
  ``64x112`` alone still pays a multi-second compile on the first
  *concurrent* burst at that shape.  Values snap to the next power of
  two (the runtime bucketing) and dedup.  Default empty: only the
  single-request (R=1) path is warmed.
* ``WARMUP_AOT`` — ``1`` (default): warm via AOT ``.lower().compile()``
  — every warmed bucket's executable is compiled WITHOUT a device
  dispatch and cached on the embedder, and post-warmup traffic at those
  buckets calls the executables directly (zero jit specializations
  after startup; the ``jit`` section of ``/metrics`` shows the counts).
  ``0`` falls back to dispatch-based warmup (also what mesh-sharded
  embedders use: AOT lowering doesn't carry their shardings).
* ``BATCH_MAX_ROWS`` — encoder rows per fused dispatch; a synchronized
  burst of requests chunks into this many rows per dispatch so the
  pipeline has pieces to overlap.  Default 512.
* ``PACKING_ENABLED`` — continuous batching (serve/packing.py): embed
  and consensus device work rides a ragged segment-id layout — many
  variable-length sequences packed end-to-end per dense row — instead
  of one padded row each, and requests with DIFFERENT candidate counts,
  temperatures, and truncation caps share a dispatch.  Off by default
  (the padded (R, N, S)-bucketed dispatch is the legacy-exact path);
  requires a single-device embedder (mesh-sharded setups fall back to
  padded automatically).
* ``PACKING_ROW_TOKENS`` — token capacity L of one packed row; also the
  per-sequence ceiling on the packed path (longer sequences fall back
  to the padded dispatch per item).  Default 512.
* ``PACKING_MAX_ROWS`` — max rows B per packed device call; the row dim
  buckets to powers of two up to this, giving the small fixed
  ("packed", B, L, K) executable set that replaces the (R, N, S)
  lattice.  Default 8.
* ``PACKING_MAX_SEGMENTS`` — max sequences K per packed row (the slot
  dim of the pooled [B, K, H] output).  Default 64.
* ``PREFIX_DEDUP`` — with packing: a consensus request's N candidates
  sharing a long common prefix (the conversation) tokenize + embed that
  prefix ONCE; candidate vectors compose as the token-count-weighted
  normalized sum of prefix and suffix vectors (a defined approximation
  contract — DESIGN.md "Continuous batching").  Default on (packed
  mode only).
* ``PREFIX_DEDUP_MIN_CHARS`` — minimum shared-prefix length (chars,
  after cutting back to a whitespace boundary) worth deduping.
  Default 48.
* ``SCORE_CACHE_TTL`` — seconds a cached consensus result stays
  servable.  ``0`` (the default) disables the result cache entirely:
  the service behaves exactly as before the cache existed.  When >0,
  score requests are fingerprinted (cache/fingerprint.py: panel id +
  canonicalized messages + choices + sampling params, JSON field order
  irrelevant) and identical requests within the TTL replay the recorded
  chunk stream instead of re-running the judge fan-out; identical
  *concurrent* requests collapse onto one in-flight fan-out
  (single-flight).  Per-request opt-out: ``"cache_bypass": true``.
* ``SCORE_CACHE_MAX_BYTES`` — byte budget for the in-memory score result
  LRU.  Default 67108864 (64 MiB).
* ``SCORE_CACHE_DIR`` — append-only JSONL disk tier for the score cache
  (the COMPILE_CACHE_DIR pattern applied to results): entries persist
  across restarts and reload at startup, expired ones skipped.  Unset =
  memory only.
* ``SCORE_CACHE_EMBED`` — also memoize embedding rows per
  (model, truncation window, text) in the micro-batcher, so hot rows
  skip device dispatch.  Defaults on whenever ``SCORE_CACHE_TTL`` > 0;
  ``SCORE_CACHE_EMBED=0`` disables.
* ``SCORE_CACHE_EMBED_MAX_BYTES`` — byte budget for the embedding row
  cache.  Default 33554432 (32 MiB).

Cache counters (hits/misses/evictions/in-flight collapses) surface as
the ``score_cache`` / ``embed_cache`` sections of ``GET /metrics``.

Fleet tier (fleet/): N gateway replicas with ``FLEET_*`` set serve as
ONE tier — consistent-hash ownership of cache fingerprints, peer-to-peer
result fetch before going upstream, cross-replica single-flight leases
(a fleet-wide hot key hits the upstream judges exactly once), and
drain-time hot-set handoff.  Everything unset = single-replica behavior
untouched; a dead or unreachable peer degrades to exactly that:

* ``FLEET_SELF`` — this replica's own base URL as peers reach it
  (e.g. ``http://10.0.0.3:5000``).  Required to enable the fleet;
  requires ``SCORE_CACHE_TTL`` > 0 (the fleet shares score-cache
  entries) and a roster via one of the next two knobs.
* ``FLEET_PEERS`` — static comma-separated roster of replica base URLs,
  ``FLEET_SELF`` included.
* ``FLEET_PEERS_FILE`` — file-watched roster instead (one URL per
  line, ``#`` comments allowed), re-read within ~1 s of an mtime
  change so replicas join/leave without restarts.  Mutually exclusive
  with ``FLEET_PEERS``.
* ``FLEET_VNODES`` — virtual nodes per replica on the ownership ring
  (higher = smoother key balance, larger ring).  Default 64.
* ``FLEET_LEASE_MILLIS`` — cross-replica single-flight lease TTL: how
  long the owner waits for a lease holder's publish before waiters
  fall back to local compute (a dead holder costs one duplicate
  fan-out, never a stuck request).  Default 10000.
* ``FLEET_FETCH_TIMEOUT_MILLIS`` — per-peer-call timeout, always
  additionally clamped to HALF the remaining request deadline so the
  local-compute fallback keeps enough budget to run.  Default 2000.
* ``AOT_CACHE_DIR`` — fleet-shared serialized-executable store
  (models/aot_store.py): the first replica to AOT-compile a warmup
  bucket serializes the executable here, and every later replica (or
  restart) deserializes in milliseconds instead of compiling —
  seconds-fast warm cold start, zero jit compilations on the first
  request.  Keyed by an environment digest (jax version, backend,
  device kind/count, model config), so incompatible artifacts are
  never even opened.  Useful fleet or single-replica; independent of
  the ``FLEET_*`` knobs.

Resilience (all opt-in; everything unset = pre-resilience behavior,
byte for byte):

* ``CONNECT_TIMEOUT_MILLIS`` — TCP connect timeout for the upstream
  HTTP transport (previously hard-coded 30 s).  Default 30000.
* ``RESILIENCE_BREAKER_THRESHOLD`` — failure rate in (0, 1] that opens
  a per-upstream circuit breaker (keyed api_base+model).  ``0`` (the
  default) disables breakers entirely.
* ``RESILIENCE_BREAKER_WINDOW`` / ``RESILIENCE_BREAKER_MIN_SAMPLES`` /
  ``RESILIENCE_BREAKER_COOLDOWN_MILLIS`` — sliding-window size, the
  volume threshold before the rate is meaningful, and how long an open
  breaker refuses before half-open probing.  Defaults 20 / 5 / 5000.
* ``RESILIENCE_RETRY_BUDGET`` — retries one score request's judge
  fan-out may spend collectively (token bucket; anti-retry-storm).
  ``0`` = unlimited (no budget).
* ``RESILIENCE_HEDGE_MILLIS`` — static hedge delay: an attempt with no
  first chunk after this long races a backup against the next endpoint
  (the loser is cancelled).  ``0`` = no hedging.
* ``RESILIENCE_HEDGE_QUANTILE`` — hedge at an observed first-chunk
  latency quantile (e.g. ``0.95``) once enough samples exist, falling
  back to ``RESILIENCE_HEDGE_MILLIS`` before that.  ``0`` = static only.
* ``RESILIENCE_DEADLINE_MILLIS`` — default per-request deadline the
  gateway stamps on score/chat requests (clients override per request
  via the ``x-deadline-ms`` header); flows through the fan-out so
  timeouts, backoff sleeps and hedges respect the remaining budget.
  ``0`` = none.
* ``RESILIENCE_QUORUM`` — fraction of total panel weight that must
  settle before the quorum early-exit may cancel stragglers whose votes
  cannot flip the argmax; the final frame ships with ``degraded: true``
  (and is never cached).  ``0`` = always wait for the full panel.
* ``FAULT_PLAN`` — chaos-run fault injection at the transport seam,
  e.g. ``seed=42,connect=0.1,5xx=0.1,stall_first=0.1,stall_ms=200``
  (resilience/faults.py); the hostile-ingest kinds (``giant_line``,
  ``newline_less_flood``, ``oversized_unary``, ``binary_garbage``)
  size their payloads with ``flood_bytes`` (default 8 MiB).  Never set
  in production.

Hostile input & memory pressure (clients/sse.py byte budgets,
resilience/memguard.py — on by default, 0 disables each cap):

* ``JUDGE_STREAM_MAX_BYTES`` — cumulative byte budget for one judge's
  SSE stream leg; also caps the body read on a non-200 upstream
  response.  A trip surfaces as a per-judge ``ingest_cap`` error entry
  in a degraded (never-cached) final frame, counts against that
  upstream's breaker, and is hedgeable like any first-chunk failure.
  Default 33554432 (32 MiB); ``0`` = uncapped.
* ``SSE_MAX_EVENT_BYTES`` — byte cap on one SSE event's accumulated
  ``data:`` payload AND on the parser's newline-less buffered residue
  (one knob bounds both, Python and native parsers identically).
  Default 4194304 (4 MiB); ``0`` = uncapped.
* ``MAX_BODY_BYTES`` — gateway request-body cap (aiohttp
  ``client_max_size``, /fleet/v1 included); oversized requests get a
  structured ``413 {"kind": "payload_too_large"}`` envelope.  Default
  1048576 (1 MiB); ``0`` = aiohttp's own default cap.
* ``MEMGUARD`` — ``1`` (default) runs the host memory governor: RSS
  sampled each ``MEMGUARD_INTERVAL_MILLIS`` against soft/hard
  watermarks.  Soft pressure shrinks the cache byte budgets, trace
  ring and AIMD admission limit (restored on recovery); hard pressure
  sheds new non-exempt work (``503 shed_reason: memory``) and flags
  ``degraded_mem`` on /readyz (still 200).  Recovery is hysteretic.
  ``0`` disables.
* ``MEM_SOFT_BYTES`` / ``MEM_HARD_BYTES`` — the watermarks; ``0``
  (default) = auto at 80% / 90% of /proc/meminfo MemTotal (the
  governor disables itself when MemTotal is unreadable).
* ``MEMGUARD_INTERVAL_MILLIS`` — governor sampling period.
  Default 1000.

Resilience counters + breaker states surface as the ``resilience``
section of ``GET /metrics``.

Overload & lifecycle (resilience/admission.py, resilience/watchdog.py,
serve/lifecycle.py; all opt-in except graceful drain, which only changes
shutdown):

* ``ADMISSION_MAX_INFLIGHT`` — hard cap on concurrently admitted
  requests; excess work is shed at the gateway door with
  ``503 + Retry-After`` and a ``shed_reason`` body instead of queueing.
  ``0`` (the default) disables shedding — the admission gate then only
  tracks in-flight work (the gauge the drain path uses).
* ``ADMISSION_MAX_QUEUE_DEPTH`` — bound on the device batcher's pending
  queue: arrivals beyond it fail fast with 503
  (``shed_reason: batcher_queue_full``).  ``0`` = unbounded.
* ``ADMISSION_ADAPTIVE`` — ``1`` enables the AIMD/gradient concurrency
  limit under the hard cap (Netflix concurrency-limits style): observed
  latency beyond ``ADMISSION_LATENCY_FACTOR`` x a drifting baseline
  decays the limit multiplicatively; a full-but-healthy pipe recovers
  it additively.  Requires ``ADMISSION_MAX_INFLIGHT`` > 0.
* ``ADMISSION_MIN_LIMIT`` / ``ADMISSION_LATENCY_FACTOR`` /
  ``ADMISSION_RETRY_AFTER_MILLIS`` — adaptive floor, the congestion
  threshold multiplier (> 1), and the Retry-After hint on sheds.
  Defaults 2 / 2.0 / 1000.
* ``DRAIN_TIMEOUT_MILLIS`` — SIGTERM/SIGINT graceful-drain budget:
  ``/readyz`` flips to 503, new work sheds (``shed_reason: draining``),
  in-flight streams finish to their ``[DONE]`` and the batcher queue
  empties, the cache disk tier is flushed exactly once, then exit 0.
  Default 10000.
* ``DEVICE_WATCHDOG_MILLIS`` — a device dispatch exceeding this marks
  the device unhealthy (hung PJRT / wedged tunnel): ``/readyz`` flips
  and admission sheds device-dependent endpoints
  (``shed_reason: device_unhealthy``) until the dispatch completes.
  ``0`` (the default) disables the watchdog.
* ``DEVICE_WATCHDOG_INTERVAL_MILLIS`` — monitor-thread check period;
  ``0`` = auto (a quarter of the timeout).
* ``DEVICE_WATCHDOG_CPU_FALLBACK`` — ``1`` builds a CPU twin of the
  embedder at startup and routes embed/consensus dispatches to it while
  the device is unhealthy (degraded but alive beats shedding).
  Requires ``DEVICE_WATCHDOG_MILLIS`` > 0.  Precedence under
  ``MESH_ENABLED``: the twin is single-device, so collapsing a live
  dp×tp mesh onto it is an outage with extra steps — in mesh mode this
  flag therefore ALSO requires ``MESH_FAULT_ENABLED``, and the twin
  only serves after the downsize ladder is exhausted (a watchdog trip
  marks the next classified fault persistent instead of flipping the
  fallback directly).

Mesh fault domains (resilience/meshfault.py; requires ``MESH_ENABLED``,
all opt-in — unset keeps the PR 9 mesh path byte-for-byte):

* ``MESH_FAULT_ENABLED`` — ``1`` arms the mesh fault-domain subsystem:
  dispatch failures classify transient/persistent at the
  embedder/batcher seam, a persistent fault downsizes the mesh one
  rung along the dp-halving ladder (params re-shard onto the surviving
  submesh, dispatch swaps to that rung's AOT executables — every rung
  is warmed at startup), in-flight items re-dispatch on the new shape
  bounded by their deadlines, admission/batcher capacity rescale to
  the surviving chips, and ``/readyz`` stays 200 with a
  ``degraded_mesh`` flag.  Counters ride the ``meshfault`` /metrics
  section.
* ``MESH_FAULT_TRANSIENT_RETRIES`` — consecutive transient dispatch
  faults tolerated (each re-queues and retries on the SAME shape)
  before the streak escalates to persistent and walks the ladder.
  Default 2.
* ``MESH_FAULT_PROBE_MILLIS`` — recovery-prober period: while
  degraded, every interval the full mesh is re-validated with a real
  probe dispatch (a failed probe rolls the upsize back and backs the
  interval off exponentially) and, when healthy, the mesh upsizes back
  to the full shape (capacity restored, ``degraded_mesh`` clears).
  ``0`` (the default) disables automatic recovery.
* ``DEVICE_FAULT_PLAN`` — deterministic device-fault injection at the
  dispatch seam (the ``FAULT_PLAN`` contract at the embedder boundary),
  e.g. ``seed=42,persistent=0.05`` or
  ``script=ok|transient|persistent|ok,hang_ms=50`` with kinds
  ``transient`` / ``persistent`` / ``hang``.  Chaos runs and tier-1
  drills only; never set in production.

Shed/drain/watchdog counters and the inflight/queue-depth gauges
surface as the ``admission`` / ``device_watchdog`` / ``lifecycle`` /
``device_batcher`` sections of ``GET /metrics``.  ``/healthz`` remains
as a deprecated alias of the ``/livez`` + ``/readyz`` split.

Tracing (obs/; all opt-in — with every ``TRACE_*`` knob unset no root
span is ever created and the hot path pays one contextvar read):

* ``TRACE_SAMPLE_RATE`` — head-based sampling probability in [0, 1]:
  the gateway flips this coin once per request at the door.  Degraded,
  shed and errored requests are ALWAYS captured once tracing is
  enabled, regardless of the rate.  ``> 0`` enables tracing.
* ``TRACE_ENABLED`` — ``1`` enables tracing even at rate 0 (capture
  only the degraded/shed/error traces — the cheapest useful setting).
* ``TRACE_RING`` — completed traces kept in memory for
  ``GET /v1/traces`` (index) and ``GET /v1/traces/{trace_id}`` (full
  span tree); oldest evicted first.  Default 256.
* ``TRACE_DIR`` — optional JSONL disk tier: one JSON line per kept
  trace appended to ``traces-<pid>.jsonl`` under this directory
  (setting it also enables tracing).

Performance observability (obs/phases.py, obs/histogram.py,
analysis/roofline.py — DESIGN.md "Performance observability"):

* ``METRICS_DEVICE_TIMING`` — per-bucket device-time measurement at the
  embedder seam: every dispatch is timed enqueue-to-ready and lands in
  the ``phases`` / ``roofline`` sections of ``GET /metrics`` keyed by
  its (mesh-shape, bucket) label, plus the ``overlap`` gauge (device-
  busy union-interval over wall time across recent dispatches).  Under
  the batcher the readiness wait runs on a waiter thread
  (models/dispatch_seam.py), so timing does NOT serialize the dispatch
  pipeline; direct embedder callers pay an inline bracket.  Default on;
  ``0`` skips the recording (device rows, roofline attainment and the
  overlap gauge go dark, the other phases keep reporting).
  ``GET /metrics?format=prometheus`` renders the same data as
  OpenMetrics text with trace-id exemplars on the hot series.

Consensus-quality observability (obs/quality.py, obs/ledger.py —
DESIGN.md "Consensus quality"; the scorecard/SLI aggregates are always
on like the phase histograms, these knobs tune or extend them):

* ``QUALITY_WINDOW`` — ballots in each judge's sliding drift window;
  a judge is compared against its pre-window baseline and flagged only
  once BOTH hold a full window (cold judges never flag on noise).
  Default 64.
* ``QUALITY_DRIFT_THRESHOLD`` — how far a judge's windowed agreement
  rate or vote-mass-on-winner may fall below its baseline before the
  drift detector flags it, as an absolute rate drop in (0, 1].
  Default 0.25.
* ``LEDGER_RING`` — consensus-outcome records kept in memory (one per
  scored request: panel id, per-judge votes + weights, confidence
  vector, degraded/quorum verdict, trace id — the training substrate
  for weight learning and archive re-scoring).  ``0`` (the default)
  disables the ledger unless ``LEDGER_DIR`` is set (which implies a
  ring of 256).
* ``LEDGER_DIR`` — append-only JSONL disk tier for the ledger:
  one self-describing line per record in ``ledger-<pid>.jsonl``
  (setting it also enables the ledger).
* ``LEDGER_ROTATE_BYTES`` — rotate the active ledger file to a sealed
  timestamped shard (``ledger-<pid>-<ts>-<seq>.jsonl``) once it
  reaches this size; sealed shards still match the read glob, so
  ``load_ledger_records`` and the train/ shard feed see every
  generation.  ``0`` (the default) keeps one ever-growing file.

Offline lane & weight learning (train/, weights/live.py — DESIGN.md
"Offline lane & weight learning"):

* ``WEIGHTS_ENABLED`` — arm the versioned live weight store and the
  ``GET/PUT /v1/weights`` hot-swap endpoints; per-judge overrides
  apply to every tally, the applied version is stamped on each
  ``consensus:tally`` span and ledger record, and shadow-table
  counters feed the quality scorecards.  Default off.
* ``WEIGHTS_PATH`` — persist the live weight tables as JSON
  (``lwc.weights.v1``) so a hot-swapped table survives a restart;
  setting it implies ``WEIGHTS_ENABLED``.
* ``OFFLINE_ENABLED`` — expose ``POST /v1/train/rescore``: an
  admin-only drive of the batcher's offline priority class (archive
  or synthetic candidate groups re-scored whenever the latency lane
  has no ready group).  Default off; the offline class itself always
  exists in the batcher.
* ``OFFLINE_INFLIGHT`` — candidate groups the offline feeder keeps in
  flight (its only backpressure; >= 2 sustains device occupancy on an
  idle mesh).  Default 4.
* ``JUDGE_BIAS_PLAN`` — deterministic per-judge vote perturbation at
  the extraction seam (the ``FAULT_PLAN`` contract applied to a judge's
  ballot), e.g. ``judge=2,after=16,flip=1.0,seed=7`` with kinds
  ``flip`` / ``uniform`` / ``invert`` (resilience/faults.py
  JudgeBiasPlan).  Consensus-quality drills and tier-1 tests only;
  never set in production.

Scorecards ride ``GET /v1/judges`` (+ ``/v1/judges/{id}``) and the
``quality`` section of ``GET /metrics``; the ledger's counters ride
the ``ledger`` section.

Incoming ``traceparent`` headers (W3C) are honored — the caller's
trace id is adopted and its sampled flag forces capture — and every
upstream judge call carries a ``traceparent`` naming the attempt span
as parent.  Kept/dropped/forced counters surface as the ``traces``
section of ``GET /metrics``; per-series ``trace_id`` exemplars ride
the existing latency sections.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from ..utils import env_truthy, jsonutil


def enable_compile_cache(path: str) -> None:
    """Persistent XLA compilation cache: warm restarts (and repeat bench
    runs) skip the first-request compile (SURVEY §7 'cold-start/compile
    caching').  Must run before the first jit compilation.  Lives here —
    not serve/__main__ — so bench.py can use it without importing the
    aiohttp entry-point chain."""
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # cache every specialization, not only slow ones — the serving loop
    # has a handful of bucketed shapes and all of them matter cold
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax latches the cache-disabled decision at the process's FIRST
    # compile; enabling the dir afterwards is a silent no-op unless the
    # latch is reset.  Internal API, so fail open: worst case is the
    # pre-reset behavior (no persistent cache) rather than no serving.
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:
        pass


def _parse_warmup(raw) -> list:
    """"64x112,64x128" -> [(64, 112), (64, 128)].  Raises on malformed
    specs: a silently dropped warmup defeats its purpose."""
    if not raw:
        return []
    out = []
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        from .gateway import MAX_CONSENSUS_CANDIDATES

        try:
            n_s = part.split("x")
            n, s = int(n_s[0]), int(n_s[1])
            if (
                len(n_s) != 2
                or not 2 <= n <= MAX_CONSENSUS_CANDIDATES
                or s < 1
            ):
                raise ValueError
        except (ValueError, IndexError):
            raise ValueError(
                f"WARMUP spec {part!r}: expected NxS with 2 <= N <= "
                f"{MAX_CONSENSUS_CANDIDATES} candidates (the /consensus "
                "request ceiling — warming an unreachable shape burns "
                "startup time for nothing) and S >= 1 tokens (e.g. 64x112)"
            ) from None
        out.append((n, s))
    return out


def _parse_warmup_r(raw) -> list:
    """"2,4" -> [2, 4], snapped to the runtime's power-of-two R buckets
    and deduped ("3" warms the same specialization as "4").  Raises on
    malformed or non-positive values, same loud-failure contract as
    ``_parse_warmup``."""
    if not raw:
        return []
    buckets = []
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            r = int(part)
            if r < 1:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"WARMUP_R value {part!r}: expected a positive integer "
                "concurrency bucket (e.g. 2)"
            ) from None
        from ..utils import next_pow2

        bucket = next_pow2(r)
        if bucket not in buckets:
            buckets.append(bucket)
    return buckets


def _parse_long_context_warmup(raw) -> list:
    """"4x4096,1x8192" -> [(4, 4096), (1, 8192)]: ring AOT buckets for
    ``MESH_SHAPE=DPxTPxSP`` serving (N candidates x S tokens; N=1 warms
    the plain long-document embed path, so the floor is 1 where
    ``WARMUP``'s is 2).  Same loud-failure contract as
    ``_parse_warmup``."""
    if not raw:
        return []
    out = []
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            n_s = part.split("x")
            n, s = int(n_s[0]), int(n_s[1])
            if len(n_s) != 2 or n < 1 or s < 1:
                raise ValueError
        except (ValueError, IndexError):
            raise ValueError(
                f"LONG_CONTEXT_WARMUP spec {part!r}: expected NxS with "
                "N >= 1 candidates and S >= 1 tokens (e.g. 4x4096)"
            ) from None
        out.append((n, s))
    return out


def _parse_mesh_shape(raw) -> Optional[tuple]:
    """"4x2" -> (4, 2); "2x2x2" -> (2, 2, 2).  The optional third axis
    is sequence parallelism (ring attention, parallel/ring.py) — the
    2-form stays the exact pre-sp serving path.  Raises on malformed
    values, same loud-failure contract as ``_parse_warmup``: a silently
    dropped mesh shape would serve single-device while claiming a
    mesh."""
    if not raw:
        return None
    try:
        parts = [int(p) for p in str(raw).strip().split("x")]
        if len(parts) not in (2, 3) or any(p < 1 for p in parts):
            raise ValueError
    except (ValueError, IndexError):
        raise ValueError(
            f"MESH_SHAPE {raw!r}: expected DPxTP or DPxTPxSP with "
            "positive axes (e.g. 4x2 = batches split 4-way, encoder "
            "params 2-way; 2x2x2 adds 2-way sequence parallelism for "
            "long-context serving)"
        ) from None
    if len(parts) == 3 and parts[2] == 1:
        # sp=1 is exactly the 2-axis mesh; normalize so downstream code
        # (and the byte-identical no-sp contract) sees one canonical form
        parts = parts[:2]
    return tuple(parts)


def _parse_peer_list(raw) -> list:
    """"http://a:5000, http://b:5000" -> normalized URL list (trailing
    slashes stripped, empties dropped)."""
    if not raw:
        return []
    return [p.strip().rstrip("/") for p in str(raw).split(",") if p.strip()]


def _non_negative_int(env: dict, name: str, default: int) -> int:
    value = int(env.get(name, default))
    if value < 0:
        raise ValueError(
            f"{name}={value} must be >= 0 (0 = unbounded)"
        )
    return value


def load_dotenv(path: str = ".env") -> None:
    if not os.path.exists(path):
        return
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            if line.startswith("export "):
                line = line[len("export "):]
            key, _, value = line.partition("=")
            value = value.strip()
            # dotenv-style quoted values; unquoted values drop trailing
            # inline comments
            if len(value) >= 2 and value[0] == value[-1] and value[0] in "'\"":
                value = value[1:-1]
            elif " #" in value:
                value = value.split(" #", 1)[0].rstrip()
            os.environ.setdefault(key.strip(), value)


@dataclass
class Config:
    # backoff (main.rs:5-16)
    backoff_initial_interval_millis: float = 100.0
    backoff_randomization_factor: float = 0.5
    backoff_multiplier: float = 1.5
    backoff_max_interval_millis: float = 1000.0
    backoff_max_elapsed_time_millis: float = 40000.0
    # stream timeouts (main.rs:17-20)
    first_chunk_timeout_millis: float = 10000.0
    other_chunk_timeout_millis: float = 60000.0
    # TCP connect timeout (was hard-coded sock_connect=30)
    connect_timeout_millis: float = 30000.0
    # upstream endpoints (main.rs:21-33)
    openai_apis: list = field(default_factory=list)  # [{api_base, api_key}]
    openai_user_agent: Optional[str] = None
    openai_x_title: Optional[str] = None
    openai_referer: Optional[str] = None
    # bind (main.rs:34-37)
    address: str = "0.0.0.0"
    port: int = 5000
    # TPU-framework additions
    embedder_model: Optional[str] = None  # e.g. "bge-small-en"
    embedder_weights: Optional[str] = None  # local checkpoint path
    embedder_vocab: Optional[str] = None  # path to vocab.txt
    embedder_max_tokens: Optional[int] = None  # None = context-aware default
    embedder_quantize: str = "none"  # "int8" = W8A8 serving (models/quant.py)
    # reward-model re-ranking service (POST /consensus {"scorer": "rm"})
    rm_model: Optional[str] = None  # e.g. "deberta-v3-base"
    rm_weights: Optional[str] = None  # local HF/orbax checkpoint
    rm_vocab: Optional[str] = None  # spm.model / vocab.txt
    rm_max_tokens: int = 512
    rm_quantize: str = "none"  # "int8" = W8A8 RM serving (models/quant.py)
    mesh_dp: Optional[int] = None
    mesh_tp: int = 1
    mesh_sp: Optional[int] = None
    # first-class mesh serving (parallel/sharding.py shard_embedder_mesh):
    # off by default = the single-device path bit-for-bit
    mesh_enabled: bool = False
    mesh_shape: Optional[tuple] = None  # (dp, tp[, sp]) from "DPxTP[xSP]"
    # ring AOT buckets (NxS) warmed when MESH_SHAPE carries an sp axis
    long_context_warmup: list = field(default_factory=list)
    compile_cache_dir: Optional[str] = None
    profile_dir: Optional[str] = None
    archive_path: Optional[str] = None
    archive_write: bool = False
    # also archive STREAMED completions by teeing the chunk stream into
    # the fold (unary = fold(chunks)) at stream end; off by default —
    # folding retains every streamed response in memory
    archive_streaming: bool = False
    # FIFO cap per completion table; 0 = unbounded
    archive_max_completions: int = 65536
    tables_path: Optional[str] = None
    batch_window_ms: float = 3.0
    batch_max: int = 64
    # concurrent device dispatches in flight (host staging of batch k+1
    # overlaps device compute of batch k)
    batch_pipeline: int = 2
    # encoder rows per dispatch (bursts chunk into overlappable pieces)
    batch_max_rows: int = 512
    # submit-time tokenization pool (0 = tokenize on dispatch thread)
    host_tokenizer_workers: int = 2
    # host fast lane for the streaming consensus path (serve/frames.py
    # splice templates + clients/tally.py fixed-point tally); off = the
    # byte-identical slow path everywhere
    host_fastpath: bool = False
    # reusable host staging buffers per (shape, dtype); 0 = no reuse
    staging_buffers: int = 2
    # continuous batching (serve/packing.py): ragged segment-id packing
    # on the embed/consensus device path; off = legacy padded dispatch
    packing_enabled: bool = False
    packing_row_tokens: int = 512  # packed row capacity L (and per-seq cap)
    packing_max_rows: int = 8  # rows B per packed call (pow2-bucketed)
    packing_max_segments: int = 64  # sequences K per packed row
    # shared-prefix dedup across a consensus request's N candidates
    # (packed mode only; composition contract in DESIGN.md)
    prefix_dedup: bool = True
    prefix_dedup_min_chars: int = 48
    # [(n_candidates, seq), ...] consensus shapes to pre-compile at
    # startup (WARMUP env, e.g. "64x112,64x128"); [] = lazy compiles
    warmup: list = field(default_factory=list)
    # power-of-two concurrency buckets to pre-compile the grouped
    # (consensus_confidence_tokens_many) path for, per WARMUP shape
    # (WARMUP_R env, e.g. "2,4"); [] = single-request path only
    warmup_r: list = field(default_factory=list)
    # AOT-compile warmed buckets (.lower().compile(), no device
    # dispatch) and serve them from the embedder's executable table;
    # False = dispatch-based warmup (WARMUP_AOT env)
    warmup_aot: bool = True
    # consensus result cache (cache/): TTL seconds, 0 = disabled (exact
    # pre-cache behavior); byte budget for the in-memory LRU; optional
    # JSONL disk tier for warm restarts
    score_cache_ttl_sec: float = 0.0
    score_cache_max_bytes: int = 64 * 1024 * 1024
    score_cache_dir: Optional[str] = None
    # per-row embedding memoization in the micro-batcher; defaults on
    # whenever the score cache is on
    score_cache_embed: bool = False
    score_cache_embed_max_bytes: int = 32 * 1024 * 1024
    # resilience subsystem (resilience/): every knob defaults to "off";
    # resilience_policy() returns None when nothing is enabled so the
    # clients run their pre-resilience code paths untouched
    resilience_breaker_threshold: float = 0.0  # 0 = breakers disabled
    resilience_breaker_window: int = 20
    resilience_breaker_min_samples: int = 5
    resilience_breaker_cooldown_millis: float = 5000.0
    resilience_retry_budget: int = 0  # 0 = unlimited
    resilience_hedge_millis: float = 0.0  # 0 = no hedging
    resilience_hedge_quantile: float = 0.0  # 0 = static delay only
    resilience_deadline_millis: float = 0.0  # 0 = no default deadline
    resilience_quorum: float = 0.0  # 0 = wait for the full panel
    # chaos-run fault injection spec (resilience/faults.py); None = off
    fault_plan: Optional[str] = None
    # ingest byte budgets (clients/sse.py, clients/chat.py): per-judge
    # cumulative stream budget (doubles as the non-200 body-read cap)
    # and the SSE event/residue cap.  Library defaults are 0/off; the
    # SERVING layer turns them on here — 0 disables a cap explicitly
    judge_stream_max_bytes: int = 32 * 1024 * 1024
    sse_max_event_bytes: int = 4 * 1024 * 1024
    # gateway request-body cap -> aiohttp client_max_size (413 with a
    # structured payload_too_large envelope); 0 = aiohttp's default
    max_body_bytes: int = 1024 * 1024
    # host memory governor (resilience/memguard.py): soft/hard RSS
    # watermarks (0 = auto from MemTotal), sampling period, on/off
    memguard_enabled: bool = True
    mem_soft_bytes: int = 0
    mem_hard_bytes: int = 0
    memguard_interval_millis: float = 1000.0
    # overload protection (resilience/admission.py): hard in-flight cap
    # (0 = no shedding, gauge only), batcher queue bound (0 = unbounded),
    # and the AIMD/gradient adaptive limit under the cap
    admission_max_inflight: int = 0
    admission_max_queue_depth: int = 0
    admission_adaptive: bool = False
    admission_min_limit: int = 2
    admission_latency_factor: float = 2.0
    admission_retry_after_millis: float = 1000.0
    # graceful-drain budget on SIGTERM/SIGINT (serve/lifecycle.py)
    drain_timeout_millis: float = 10000.0
    # device dispatch watchdog (resilience/watchdog.py); 0 = off
    device_watchdog_millis: float = 0.0
    device_watchdog_interval_millis: float = 0.0  # 0 = auto (timeout/4)
    device_watchdog_cpu_fallback: bool = False
    # mesh fault domains (resilience/meshfault.py): classification +
    # downsize ladder + re-dispatch; requires mesh_enabled, off = the
    # PR 9 mesh path untouched
    mesh_fault_enabled: bool = False
    # consecutive transient faults tolerated before escalating to a
    # persistent (ladder-walking) fault
    mesh_fault_transient_retries: int = 2
    # recovery-prober period; 0 = no automatic upsize
    mesh_fault_probe_millis: float = 0.0
    # deterministic device-fault injection spec (DeviceFaultPlan.parse);
    # None = off (chaos runs and tier-1 drills only)
    device_fault_plan: Optional[str] = None
    # runtime lockdep (analysis/witness.py): wrap the registered
    # threading primitives and validate real acquisition order against
    # the declared DAG; off by default — intended for chaos/soak drills
    # (~1 dict update per lock acquisition when on)
    lock_witness: bool = False
    # request tracing (obs/): head-sample rate, forced-on flag (capture
    # only degraded/shed/error at rate 0), ring capacity, JSONL dir.
    # trace_sink() returns None when nothing enables tracing, keeping
    # the untraced hot path at one contextvar read per helper call.
    trace_sample_rate: float = 0.0
    trace_enabled: bool = False
    trace_ring: int = 256
    trace_dir: Optional[str] = None
    # per-bucket device timing (enqueue-to-ready at the embedder seam;
    # waiter-thread readiness under the batcher, inline bracket for
    # direct callers) feeding the phases/roofline metrics sections;
    # METRICS_DEVICE_TIMING=0 skips the recording entirely
    metrics_device_timing: bool = True
    # consensus-quality observability (obs/quality.py): drift-window
    # size and the agreement/calibration drop that flags a judge
    quality_window: int = 64
    quality_drift_threshold: float = 0.25
    # consensus-outcome ledger (obs/ledger.py): ring capacity (0 = off
    # unless ledger_dir is set), the optional JSONL disk tier, and the
    # size at which the active file seals into a timestamped shard
    ledger_ring: int = 0
    ledger_dir: Optional[str] = None
    ledger_rotate_bytes: int = 0
    # versioned live weight tables (weights/live.py): hot-swap via
    # GET/PUT /v1/weights; weights_path persists them across restarts
    # (and implies enabled)
    weights_enabled: bool = False
    weights_path: Optional[str] = None
    # offline lane driver (train/feed.py): POST /v1/train/rescore gate
    # and the feeder's in-flight group bound
    offline_enabled: bool = False
    offline_inflight: int = 4
    # deterministic judge-vote perturbation spec (JudgeBiasPlan.parse);
    # None = off (consensus-quality drills and tier-1 tests only)
    judge_bias_plan: Optional[str] = None
    # fleet tier (fleet/): replicated score cache with consistent-hash
    # ownership and cross-replica single-flight leases.  fleet_self
    # unset = everything off; fleet_config() returns None
    fleet_self: Optional[str] = None
    fleet_peers: list = field(default_factory=list)
    fleet_peers_file: Optional[str] = None
    fleet_vnodes: int = 64
    fleet_lease_millis: float = 10000.0
    fleet_fetch_timeout_millis: float = 2000.0
    # deterministic peer fault injection spec (fleet/faults.py
    # FleetFaultPlan.parse); None = the seam is a single is-None check
    fleet_fault_plan: Optional[str] = None
    # consecutive peer transport failures before quarantine (0 = never
    # quarantine), and how often a quarantined peer is probed for
    # re-admission
    fleet_quarantine_failures: int = 3
    fleet_probe_millis: float = 1000.0
    # fleet-shared serialized-executable store (models/aot_store.py);
    # None = compile every AOT bucket locally as before
    aot_cache_dir: Optional[str] = None

    @classmethod
    def from_env(cls, env: Optional[dict] = None) -> "Config":
        env = dict(os.environ if env is None else env)

        def get_f(name, default):
            return float(env.get(name, default))

        apis_json = env.get("OPENAI_APIS")
        if apis_json:
            apis = jsonutil.loads(apis_json)
        else:
            base, key = env.get("OPENAI_API_BASE"), env.get("OPENAI_API_KEY")
            if base and key:
                apis = [{"api_base": base, "api_key": key}]
            else:
                apis = []
        config = cls(
            backoff_initial_interval_millis=get_f(
                "BACKOFF_INITIAL_INTERVAL_MILLIS", 100
            ),
            backoff_randomization_factor=get_f(
                "BACKOFF_RANDOMIZATION_FACTOR", 0.5
            ),
            backoff_multiplier=get_f("BACKOFF_MULTIPLIER", 1.5),
            backoff_max_interval_millis=get_f(
                "BACKOFF_MAX_INTERVAL_MILLIS", 1000
            ),
            backoff_max_elapsed_time_millis=get_f(
                "BACKOFF_MAX_ELAPSED_TIME_MILLIS", 40000
            ),
            first_chunk_timeout_millis=get_f(
                "FIRST_CHUNK_TIMEOUT_MILLIS", 10000
            ),
            other_chunk_timeout_millis=get_f(
                "OTHER_CHUNK_TIMEOUT_MILLIS", 60000
            ),
            connect_timeout_millis=get_f("CONNECT_TIMEOUT_MILLIS", 30000),
            openai_apis=apis,
            openai_user_agent=env.get("OPENAI_USER_AGENT"),
            openai_x_title=env.get("OPENAI_X_TITLE"),
            openai_referer=env.get("OPENAI_REFERER"),
            address=env.get("ADDRESS", "0.0.0.0"),
            port=int(env.get("PORT", 5000)),
            embedder_model=env.get("EMBEDDER_MODEL"),
            embedder_weights=env.get("EMBEDDER_WEIGHTS"),
            embedder_vocab=env.get("EMBEDDER_VOCAB"),
            embedder_max_tokens=(
                int(env["EMBEDDER_MAX_TOKENS"])
                if env.get("EMBEDDER_MAX_TOKENS")
                else None
            ),
            embedder_quantize=env.get("EMBEDDER_QUANTIZE") or "none",
            rm_model=env.get("RM_MODEL"),
            rm_weights=env.get("RM_WEIGHTS"),
            rm_vocab=env.get("RM_VOCAB"),
            rm_max_tokens=int(env.get("RM_MAX_TOKENS", 512)),
            rm_quantize=env.get("RM_QUANTIZE") or "none",
            mesh_dp=int(env["MESH_DP"]) if env.get("MESH_DP") else None,
            mesh_tp=int(env.get("MESH_TP", 1)),
            mesh_sp=int(env["MESH_SP"]) if env.get("MESH_SP") else None,
            mesh_enabled=env_truthy(env.get("MESH_ENABLED", "0")),
            mesh_shape=_parse_mesh_shape(env.get("MESH_SHAPE")),
            long_context_warmup=_parse_long_context_warmup(
                env.get("LONG_CONTEXT_WARMUP")
            ),
            compile_cache_dir=env.get("COMPILE_CACHE_DIR"),
            profile_dir=env.get("PROFILE_DIR"),
            archive_path=env.get("ARCHIVE_PATH"),
            archive_write=env_truthy(
                env.get("ARCHIVE_WRITE", "1" if env.get("ARCHIVE_PATH") else "0")
            ),
            archive_streaming=env_truthy(env.get("ARCHIVE_STREAMING", "0")),
            archive_max_completions=_non_negative_int(
                env, "ARCHIVE_MAX_COMPLETIONS", 65536
            ),
            tables_path=env.get("TABLES_PATH"),
            batch_window_ms=get_f("BATCH_WINDOW_MS", 3.0),
            batch_max=int(env.get("BATCH_MAX", 64)),
            batch_pipeline=max(1, int(env.get("BATCH_PIPELINE", 2))),
            batch_max_rows=max(1, int(env.get("BATCH_MAX_ROWS", 512))),
            host_tokenizer_workers=_non_negative_int(
                env, "HOST_TOKENIZER_WORKERS", 2
            ),
            host_fastpath=env_truthy(env.get("HOST_FASTPATH", "0")),
            staging_buffers=_non_negative_int(env, "STAGING_BUFFERS", 2),
            packing_enabled=env_truthy(env.get("PACKING_ENABLED", "0")),
            packing_row_tokens=max(
                16, int(env.get("PACKING_ROW_TOKENS", 512))
            ),
            packing_max_rows=max(1, int(env.get("PACKING_MAX_ROWS", 8))),
            packing_max_segments=max(
                1, int(env.get("PACKING_MAX_SEGMENTS", 64))
            ),
            prefix_dedup=env_truthy(env.get("PREFIX_DEDUP", "1")),
            prefix_dedup_min_chars=max(
                1, int(env.get("PREFIX_DEDUP_MIN_CHARS", 48))
            ),
            warmup=_parse_warmup(env.get("WARMUP")),
            warmup_r=_parse_warmup_r(env.get("WARMUP_R")),
            warmup_aot=env_truthy(env.get("WARMUP_AOT", "1")),
            score_cache_ttl_sec=max(0.0, get_f("SCORE_CACHE_TTL", 0)),
            score_cache_max_bytes=_non_negative_int(
                env, "SCORE_CACHE_MAX_BYTES", 64 * 1024 * 1024
            ),
            score_cache_dir=env.get("SCORE_CACHE_DIR"),
            score_cache_embed=env_truthy(
                env.get(
                    "SCORE_CACHE_EMBED",
                    "1" if float(env.get("SCORE_CACHE_TTL", 0) or 0) > 0 else "0",
                )
            ),
            score_cache_embed_max_bytes=_non_negative_int(
                env, "SCORE_CACHE_EMBED_MAX_BYTES", 32 * 1024 * 1024
            ),
            resilience_breaker_threshold=get_f(
                "RESILIENCE_BREAKER_THRESHOLD", 0
            ),
            resilience_breaker_window=max(
                1, int(env.get("RESILIENCE_BREAKER_WINDOW", 20))
            ),
            resilience_breaker_min_samples=max(
                1, int(env.get("RESILIENCE_BREAKER_MIN_SAMPLES", 5))
            ),
            resilience_breaker_cooldown_millis=get_f(
                "RESILIENCE_BREAKER_COOLDOWN_MILLIS", 5000
            ),
            resilience_retry_budget=_non_negative_int(
                env, "RESILIENCE_RETRY_BUDGET", 0
            ),
            resilience_hedge_millis=get_f("RESILIENCE_HEDGE_MILLIS", 0),
            resilience_hedge_quantile=get_f("RESILIENCE_HEDGE_QUANTILE", 0),
            resilience_deadline_millis=get_f("RESILIENCE_DEADLINE_MILLIS", 0),
            resilience_quorum=get_f("RESILIENCE_QUORUM", 0),
            fault_plan=env.get("FAULT_PLAN"),
            judge_stream_max_bytes=_non_negative_int(
                env, "JUDGE_STREAM_MAX_BYTES", 32 * 1024 * 1024
            ),
            sse_max_event_bytes=_non_negative_int(
                env, "SSE_MAX_EVENT_BYTES", 4 * 1024 * 1024
            ),
            max_body_bytes=_non_negative_int(
                env, "MAX_BODY_BYTES", 1024 * 1024
            ),
            memguard_enabled=env_truthy(env.get("MEMGUARD", "1")),
            mem_soft_bytes=_non_negative_int(env, "MEM_SOFT_BYTES", 0),
            mem_hard_bytes=_non_negative_int(env, "MEM_HARD_BYTES", 0),
            memguard_interval_millis=get_f("MEMGUARD_INTERVAL_MILLIS", 1000),
            admission_max_inflight=_non_negative_int(
                env, "ADMISSION_MAX_INFLIGHT", 0
            ),
            admission_max_queue_depth=_non_negative_int(
                env, "ADMISSION_MAX_QUEUE_DEPTH", 0
            ),
            admission_adaptive=env_truthy(env.get("ADMISSION_ADAPTIVE", "0")),
            admission_min_limit=max(
                1, int(env.get("ADMISSION_MIN_LIMIT", 2))
            ),
            admission_latency_factor=get_f("ADMISSION_LATENCY_FACTOR", 2.0),
            admission_retry_after_millis=get_f(
                "ADMISSION_RETRY_AFTER_MILLIS", 1000
            ),
            drain_timeout_millis=get_f("DRAIN_TIMEOUT_MILLIS", 10000),
            device_watchdog_millis=get_f("DEVICE_WATCHDOG_MILLIS", 0),
            device_watchdog_interval_millis=get_f(
                "DEVICE_WATCHDOG_INTERVAL_MILLIS", 0
            ),
            device_watchdog_cpu_fallback=env_truthy(
                env.get("DEVICE_WATCHDOG_CPU_FALLBACK", "0")
            ),
            mesh_fault_enabled=env_truthy(
                env.get("MESH_FAULT_ENABLED", "0")
            ),
            mesh_fault_transient_retries=_non_negative_int(
                env, "MESH_FAULT_TRANSIENT_RETRIES", 2
            ),
            mesh_fault_probe_millis=get_f("MESH_FAULT_PROBE_MILLIS", 0),
            device_fault_plan=env.get("DEVICE_FAULT_PLAN"),
            lock_witness=env_truthy(env.get("LOCK_WITNESS", "0")),
            trace_sample_rate=get_f("TRACE_SAMPLE_RATE", 0),
            trace_enabled=env_truthy(env.get("TRACE_ENABLED", "0")),
            trace_ring=max(1, int(env.get("TRACE_RING", 256))),
            trace_dir=env.get("TRACE_DIR"),
            metrics_device_timing=env_truthy(
                env.get("METRICS_DEVICE_TIMING", "1")
            ),
            quality_window=int(env.get("QUALITY_WINDOW", 64)),
            quality_drift_threshold=get_f("QUALITY_DRIFT_THRESHOLD", 0.25),
            ledger_ring=_non_negative_int(env, "LEDGER_RING", 0),
            ledger_dir=env.get("LEDGER_DIR"),
            ledger_rotate_bytes=_non_negative_int(
                env, "LEDGER_ROTATE_BYTES", 0
            ),
            weights_enabled=env_truthy(env.get("WEIGHTS_ENABLED", "0")),
            weights_path=env.get("WEIGHTS_PATH"),
            offline_enabled=env_truthy(env.get("OFFLINE_ENABLED", "0")),
            offline_inflight=_non_negative_int(env, "OFFLINE_INFLIGHT", 4),
            judge_bias_plan=env.get("JUDGE_BIAS_PLAN"),
            fleet_self=env.get("FLEET_SELF"),
            fleet_peers=_parse_peer_list(env.get("FLEET_PEERS")),
            fleet_peers_file=env.get("FLEET_PEERS_FILE"),
            fleet_vnodes=max(1, int(env.get("FLEET_VNODES", 64))),
            fleet_lease_millis=get_f("FLEET_LEASE_MILLIS", 10000),
            fleet_fetch_timeout_millis=get_f(
                "FLEET_FETCH_TIMEOUT_MILLIS", 2000
            ),
            fleet_fault_plan=env.get("FLEET_FAULT_PLAN"),
            fleet_quarantine_failures=_non_negative_int(
                env, "FLEET_QUARANTINE_FAILURES", 3
            ),
            fleet_probe_millis=get_f("FLEET_PROBE_MILLIS", 1000),
            aot_cache_dir=env.get("AOT_CACHE_DIR"),
        )
        if config.quality_window < 1:
            raise ValueError(
                f"QUALITY_WINDOW={config.quality_window} must be >= 1 "
                "(ballots per judge in the sliding drift window)"
            )
        if not 0 < config.quality_drift_threshold <= 1:
            raise ValueError(
                f"QUALITY_DRIFT_THRESHOLD={config.quality_drift_threshold} "
                "must be an absolute rate drop in (0, 1]"
            )
        if not 0 <= config.resilience_quorum <= 1:
            raise ValueError(
                f"RESILIENCE_QUORUM={config.resilience_quorum} must be a "
                "weight fraction in [0, 1]"
            )
        if not 0 <= config.resilience_hedge_quantile < 1:
            raise ValueError(
                f"RESILIENCE_HEDGE_QUANTILE={config.resilience_hedge_quantile}"
                " must be a quantile in [0, 1)"
            )
        if config.admission_adaptive and config.admission_max_inflight <= 0:
            raise ValueError(
                "ADMISSION_ADAPTIVE=1 needs ADMISSION_MAX_INFLIGHT > 0: "
                "the adaptive limit operates UNDER the hard cap (set e.g. "
                "ADMISSION_MAX_INFLIGHT=64 ADMISSION_ADAPTIVE=1)"
            )
        if config.admission_latency_factor <= 1.0:
            raise ValueError(
                f"ADMISSION_LATENCY_FACTOR={config.admission_latency_factor} "
                "must be > 1 (it multiplies the latency baseline to form "
                "the congestion threshold)"
            )
        if (
            config.mem_soft_bytes > 0
            and config.mem_hard_bytes > 0
            and config.mem_hard_bytes < config.mem_soft_bytes
        ):
            raise ValueError(
                f"MEM_HARD_BYTES={config.mem_hard_bytes} must be >= "
                f"MEM_SOFT_BYTES={config.mem_soft_bytes}: the hard "
                "watermark sheds work the soft watermark only degrades"
            )
        if config.memguard_interval_millis <= 0:
            raise ValueError(
                f"MEMGUARD_INTERVAL_MILLIS={config.memguard_interval_millis}"
                " must be > 0 (the governor's RSS sampling period)"
            )
        if config.drain_timeout_millis < 0:
            raise ValueError(
                f"DRAIN_TIMEOUT_MILLIS={config.drain_timeout_millis} "
                "must be >= 0 (0 = shed immediately, no drain wait)"
            )
        if config.device_watchdog_millis < 0:
            raise ValueError(
                f"DEVICE_WATCHDOG_MILLIS={config.device_watchdog_millis} "
                "must be >= 0 (0 = watchdog disabled)"
            )
        if (
            config.device_watchdog_cpu_fallback
            and config.device_watchdog_millis <= 0
        ):
            raise ValueError(
                "DEVICE_WATCHDOG_CPU_FALLBACK=1 needs "
                "DEVICE_WATCHDOG_MILLIS > 0: without the watchdog nothing "
                "ever routes work to the fallback"
            )
        if not 0 <= config.trace_sample_rate <= 1:
            raise ValueError(
                f"TRACE_SAMPLE_RATE={config.trace_sample_rate} must be a "
                "probability in [0, 1]"
            )
        if config.mesh_shape is not None and not config.mesh_enabled:
            raise ValueError(
                "MESH_SHAPE is set but MESH_ENABLED is not: the shape only "
                "configures the first-class mesh mode (set MESH_ENABLED=1 "
                "MESH_SHAPE=4x2)"
            )
        if config.mesh_enabled and (
            config.mesh_dp is not None
            or config.mesh_tp > 1
            or config.mesh_sp is not None
        ):
            raise ValueError(
                "MESH_ENABLED is mutually exclusive with the legacy "
                "MESH_DP/MESH_TP/MESH_SP hooks: the first-class mesh mode "
                "supersedes them (use MESH_SHAPE=DPxTP)"
            )
        if config.long_context_warmup and (
            config.mesh_shape is None or len(config.mesh_shape) != 3
        ):
            raise ValueError(
                "LONG_CONTEXT_WARMUP is set but MESH_SHAPE carries no sp "
                "axis: ring buckets only compile on a sequence-parallel "
                "mesh (set MESH_SHAPE=DPxTPxSP, e.g. 2x2x2, or unset "
                "LONG_CONTEXT_WARMUP)"
            )
        if config.mesh_fault_enabled and not config.mesh_enabled:
            raise ValueError(
                "MESH_FAULT_ENABLED=1 needs MESH_ENABLED=1: fault domains, "
                "the downsize ladder and re-dispatch all operate on the "
                "first-class serving mesh (set MESH_ENABLED=1, optionally "
                "MESH_SHAPE=DPxTP)"
            )
        if config.device_fault_plan and not config.mesh_fault_enabled:
            raise ValueError(
                "DEVICE_FAULT_PLAN is set but MESH_FAULT_ENABLED is not: "
                "the injection seam lives in the mesh fault-domain "
                "subsystem, so the plan would silently never fire (set "
                "MESH_FAULT_ENABLED=1, or unset DEVICE_FAULT_PLAN)"
            )
        if config.mesh_fault_probe_millis < 0:
            raise ValueError(
                f"MESH_FAULT_PROBE_MILLIS={config.mesh_fault_probe_millis} "
                "must be >= 0 (0 = no automatic recovery upsize)"
            )
        if (
            config.mesh_enabled
            and config.device_watchdog_cpu_fallback
            and not config.mesh_fault_enabled
        ):
            # precedence contract: the CPU twin is single-device, so in
            # mesh mode it must be the LAST resort — after the downsize
            # ladder is exhausted — never the first response to a trip.
            # Without the fault-domain subsystem there is no ladder, and
            # a watchdog trip would collapse the whole mesh onto one CPU.
            raise ValueError(
                "DEVICE_WATCHDOG_CPU_FALLBACK=1 with MESH_ENABLED=1 needs "
                "MESH_FAULT_ENABLED=1: the CPU twin is single-device, so "
                "in mesh mode it is the last resort AFTER the downsize "
                "ladder is exhausted — enabling it without the ladder "
                "would collapse the mesh to one CPU on the first trip"
            )
        if config.warmup_r and not config.warmup:
            # same loud-failure contract as _parse_warmup: WARMUP_R names
            # concurrency buckets *per WARMUP shape* — without shapes it
            # would silently warm nothing
            raise ValueError(
                "WARMUP_R is set but WARMUP is empty: the grouped-path "
                "warmup needs NxS shapes to compile (set WARMUP, e.g. "
                "WARMUP=64x112 WARMUP_R=2)"
            )
        if config.fleet_peers and config.fleet_peers_file:
            raise ValueError(
                "FLEET_PEERS and FLEET_PEERS_FILE are mutually exclusive: "
                "one roster source of truth (static list OR watched file)"
            )
        if (config.fleet_peers or config.fleet_peers_file) and (
            not config.fleet_self
        ):
            raise ValueError(
                "a fleet roster is set but FLEET_SELF is not: replicas "
                "must know their own base URL to place themselves on the "
                "ownership ring (set e.g. FLEET_SELF=http://10.0.0.3:5000)"
            )
        if config.fleet_self:
            if not (config.fleet_peers or config.fleet_peers_file):
                raise ValueError(
                    "FLEET_SELF is set but no roster is: the fleet needs "
                    "FLEET_PEERS (static) or FLEET_PEERS_FILE (watched) — "
                    "a roster of one is valid but must be explicit"
                )
            if config.fleet_peers and (
                config.fleet_self.rstrip("/") not in config.fleet_peers
            ):
                raise ValueError(
                    f"FLEET_SELF={config.fleet_self} is not in FLEET_PEERS: "
                    "the static roster must include this replica, or peers "
                    "would route its owned keys elsewhere"
                )
            if config.score_cache_ttl_sec <= 0:
                raise ValueError(
                    "FLEET_SELF is set but SCORE_CACHE_TTL is 0: the fleet "
                    "tier replicates score-cache entries, so without a "
                    "cache there is nothing to own, lease, or hand off "
                    "(set SCORE_CACHE_TTL > 0)"
                )
            if config.fleet_lease_millis <= 0:
                raise ValueError(
                    f"FLEET_LEASE_MILLIS={config.fleet_lease_millis} must "
                    "be > 0 (the lease TTL bounds how long waiters trust a "
                    "possibly-dead holder)"
                )
            if config.fleet_fetch_timeout_millis <= 0:
                raise ValueError(
                    f"FLEET_FETCH_TIMEOUT_MILLIS="
                    f"{config.fleet_fetch_timeout_millis} must be > 0"
                )
            if config.fleet_probe_millis <= 0:
                raise ValueError(
                    f"FLEET_PROBE_MILLIS={config.fleet_probe_millis} must "
                    "be > 0 (how often a quarantined peer is probed for "
                    "re-admission, and the owner-side lease-wait slice)"
                )
        if config.fleet_fault_plan is not None:
            # parse eagerly so a typo fails at startup, not mid-drill
            from ..fleet.faults import FleetFaultPlan

            FleetFaultPlan.parse(config.fleet_fault_plan)
        if config.offline_enabled and config.offline_inflight < 1:
            raise ValueError(
                f"OFFLINE_INFLIGHT={config.offline_inflight} must be >= 1 "
                "(concurrent offline-lane groups; a zero-slot rescore "
                "drive can never make progress)"
            )
        return config

    def backoff_policy(self):
        from ..clients.chat import BackoffPolicy

        return BackoffPolicy(
            initial_interval_ms=self.backoff_initial_interval_millis,
            randomization_factor=self.backoff_randomization_factor,
            multiplier=self.backoff_multiplier,
            max_interval_ms=self.backoff_max_interval_millis,
            max_elapsed_ms=self.backoff_max_elapsed_time_millis,
        )

    def api_bases(self) -> list:
        from ..clients.chat import ApiBase

        return [ApiBase.from_json_obj(a) for a in self.openai_apis]

    def resilience_policy(self):
        """The configured ResiliencePolicy, or None when every knob is off
        (None keeps the clients on their pre-resilience code paths)."""
        from ..resilience import (
            BreakerConfig,
            BreakerRegistry,
            HedgePolicy,
            ResiliencePolicy,
        )

        breakers = None
        if self.resilience_breaker_threshold > 0:
            breakers = BreakerRegistry(
                BreakerConfig(
                    threshold=self.resilience_breaker_threshold,
                    window=self.resilience_breaker_window,
                    min_samples=self.resilience_breaker_min_samples,
                    cooldown_ms=self.resilience_breaker_cooldown_millis,
                )
            )
        hedge = None
        if self.resilience_hedge_millis > 0 or self.resilience_hedge_quantile > 0:
            hedge = HedgePolicy(
                delay_ms=self.resilience_hedge_millis,
                quantile=self.resilience_hedge_quantile,
            )
        if (
            breakers is None
            and hedge is None
            and self.resilience_retry_budget <= 0
            and self.resilience_quorum <= 0
            and self.resilience_deadline_millis <= 0
        ):
            return None
        return ResiliencePolicy(
            breakers=breakers,
            hedge=hedge,
            retry_budget_tokens=self.resilience_retry_budget,
            quorum_fraction=self.resilience_quorum,
            deadline_ms=self.resilience_deadline_millis,
        )

    def admission_config(self):
        """The AdmissionConfig for the gateway's admission gate.  Always
        returns one (unlike resilience_policy): with every knob at 0 the
        controller never sheds — it only tracks in-flight work, which
        the drain path needs regardless of overload configuration."""
        from ..resilience import AdmissionConfig

        return AdmissionConfig(
            max_inflight=self.admission_max_inflight,
            max_queue_depth=self.admission_max_queue_depth,
            adaptive=self.admission_adaptive,
            min_limit=self.admission_min_limit,
            latency_factor=self.admission_latency_factor,
            retry_after_ms=self.admission_retry_after_millis,
        )

    def fault_injection_plan(self):
        """Parsed FAULT_PLAN, or None (chaos runs only)."""
        if not self.fault_plan:
            return None
        from ..resilience import FaultPlan

        return FaultPlan.parse(self.fault_plan)

    def memguard(self):
        """The configured MemGuard, or None when MEMGUARD=0 or an auto
        watermark is needed but MemTotal is unreadable (the governor
        never guesses — resilience_policy() discipline)."""
        if not self.memguard_enabled:
            return None
        from ..resilience.memguard import MemGuard, resolve_watermarks

        marks = resolve_watermarks(self.mem_soft_bytes, self.mem_hard_bytes)
        if marks is None:
            return None
        return MemGuard(
            marks[0], marks[1], interval_ms=self.memguard_interval_millis
        )

    def device_fault_injection_plan(self):
        """Parsed DEVICE_FAULT_PLAN, or None (chaos/drill runs only)."""
        if not self.device_fault_plan:
            return None
        from ..resilience import DeviceFaultPlan

        return DeviceFaultPlan.parse(self.device_fault_plan)

    def judge_bias_injection_plan(self):
        """Parsed JUDGE_BIAS_PLAN, or None (quality drills only)."""
        if not self.judge_bias_plan:
            return None
        from ..resilience import JudgeBiasPlan

        return JudgeBiasPlan.parse(self.judge_bias_plan)

    def outcome_ledger(self):
        """The configured OutcomeLedger, or None when nothing enables it
        (None keeps the tally seam ledger-free — resilience_policy()
        discipline).  LEDGER_DIR alone implies the default ring of 256."""
        if self.ledger_ring <= 0 and not self.ledger_dir:
            return None
        from ..obs import OutcomeLedger

        return OutcomeLedger(
            capacity=self.ledger_ring if self.ledger_ring > 0 else 256,
            disk_dir=self.ledger_dir,
            rotate_bytes=self.ledger_rotate_bytes,
        )

    def live_weights(self):
        """The configured LiveWeightStore, or None when nothing enables
        it (None keeps the scoring path on its static-weight reads —
        resilience_policy() discipline).  WEIGHTS_PATH alone implies
        enabled: pointing at a table means serving it."""
        if not (self.weights_enabled or self.weights_path):
            return None
        from ..weights.live import LiveWeightStore

        return LiveWeightStore(path=self.weights_path)

    def trace_sink(self):
        """The configured TraceSink, or None when nothing enables
        tracing (None keeps every instrumentation site on its one-
        contextvar-read no-op path — resilience_policy() discipline)."""
        if not (
            self.trace_enabled
            or self.trace_sample_rate > 0
            or self.trace_dir
        ):
            return None
        from ..obs import TraceSink

        return TraceSink(
            capacity=self.trace_ring,
            sample_rate=self.trace_sample_rate,
            disk_dir=self.trace_dir,
        )

    def fleet_config(self):
        """The fleet membership config (fleet/membership.py), or None
        when the fleet tier is off (single-replica behavior untouched —
        resilience_policy() discipline)."""
        if not self.fleet_self:
            return None
        from ..fleet import FleetConfig

        return FleetConfig(
            self_url=self.fleet_self.rstrip("/"),
            peers=list(self.fleet_peers),
            peers_file=self.fleet_peers_file,
            vnodes=self.fleet_vnodes,
            lease_millis=self.fleet_lease_millis,
            fetch_timeout_millis=self.fleet_fetch_timeout_millis,
            fault_plan_spec=self.fleet_fault_plan,
            quarantine_failures=self.fleet_quarantine_failures,
            probe_millis=self.fleet_probe_millis,
        )
