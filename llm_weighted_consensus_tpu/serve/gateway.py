"""The aiohttp application: SSE endpoints over the consensus engine.

Frame semantics (main.rs:142-232): streaming responses are SSE ``data:``
frames — chunk JSON, or ``{code, message}`` ResponseError JSON for
mid-stream errors — terminated by ``data: [DONE]``.  Pre-stream failures
and unary failures map to HTTP status + the error's message JSON.
"""

from __future__ import annotations

import time as _time
from typing import Optional

from aiohttp import web

from .. import obs
from ..errors import (
    OverloadedError,
    ScoreError,
    StatusError,
    to_response_error,
    with_trace_id,
)
from . import frames
from .metrics import (
    PROM_CONTENT_TYPE,
    Metrics,
    middleware,
    register_overload,
    register_performance,
    register_quality,
    register_resilience,
    render_prometheus,
)
from ..types.chat_request import ChatCompletionCreateParams as ChatParams
from ..types.embeddings import CreateEmbeddingParams
from ..types.multichat_request import (
    ChatCompletionCreateParams as MultichatParams,
)
from ..types.score_request import ChatCompletionCreateParams as ScoreParams
from ..utils import jsonutil

METRICS_KEY: web.AppKey = web.AppKey("metrics", Metrics)
# the serving micro-batcher (present when an embedder is configured)
BATCHER_KEY: web.AppKey = web.AppKey("batcher", object)
# the drain/readiness state machine (serve/lifecycle.py), when wired
LIFECYCLE_KEY: web.AppKey = web.AppKey("lifecycle", object)
# the mesh fault-domain manager (resilience/meshfault.py), when wired
MESHFAULT_KEY: web.AppKey = web.AppKey("meshfault", object)

DONE = b"data: [DONE]\n\n"
SSE_HEADERS = {
    "content-type": "text/event-stream",
    "cache-control": "no-cache",
}

# aiohttp's own default request-body cap (client_max_size), used when
# MAX_BODY_BYTES=0 — the gateway never runs uncapped
_AIOHTTP_DEFAULT_BODY_BYTES = 1024 ** 2


def payload_cap_middleware():
    """Render aiohttp's 413 (client_max_size exceeded) as the uniform
    ``{code, message}`` envelope with a machine-readable kind, instead
    of the stock HTML error page.  The body read that trips the cap
    happens inside the handler (``await request.text()``), so this sits
    anywhere above the handlers in the middleware chain."""

    @web.middleware
    async def _mw(request, handler):
        try:
            return await handler(request)
        except web.HTTPRequestEntityTooLarge:
            obs.annotate(payload_too_large=True)
            return web.Response(
                status=413,
                text=jsonutil.dumps(
                    with_trace_id(
                        {
                            "code": 413,
                            "message": {"kind": "payload_too_large"},
                        }
                    )
                ),
                content_type="application/json",
            )

    return _mw


def _error_response(e: Exception) -> web.Response:
    if isinstance(e, OverloadedError):
        # load sheds are retryable by construction — say when (same
        # header the admission middleware sets on its 503s)
        import math

        return web.Response(
            status=503,
            headers={
                "Retry-After": str(
                    max(1, math.ceil((e.retry_after_ms or 1000.0) / 1000.0))
                )
            },
            text=jsonutil.dumps(
                with_trace_id({"code": 503, "message": e.message()})
            ),
            content_type="application/json",
        )
    if isinstance(e, StatusError):
        status, message = e.status(), e.message()
        if isinstance(message, dict):
            # dict-shaped error payloads carry the request's trace id so
            # a client-reported failure names its exact trace; string
            # payloads keep the reference's constant messages untouched
            message = with_trace_id(dict(message))
        body = jsonutil.dumps(message)
    else:
        # Uniform {code, message} envelope for unexpected failures; ONE
        # policy site — errors.to_response_error — masks the detail into
        # the server log (src/error.rs:8-13 parity, VERDICT r4 weak-7),
        # same as the mid-stream frame path in _respond_streaming.
        err = to_response_error(e)
        status = err.code
        body = jsonutil.dumps(with_trace_id(err.to_json_obj()))
    return web.Response(
        status=status, text=body, content_type="application/json"
    )


def _frame(obj) -> bytes:
    # kept as the module's one-frame helper for non-loop callers; the
    # per-chunk loop below goes through frames.FrameEncoder (LWC017)
    return frames.frame_bytes(obj)


async def _respond_streaming(
    request: web.Request, stream, fastpath: bool = False
) -> web.StreamResponse:
    resp = web.StreamResponse(headers=SSE_HEADERS)
    await resp.prepare(request)
    encoder = frames.FrameEncoder(fastpath)
    try:
        async for item in stream:
            if isinstance(item, Exception):
                # a mid-stream error makes this trace worth keeping even
                # when head sampling said no (sink.py retention rule)
                obs.force_keep("stream_error")
                await resp.write(encoder.encode_error(item))
            else:
                await resp.write(encoder.encode(item))
        if encoder.fallbacks:
            # fast-lane frames that fell back to the slow path: loud in
            # the trace, invisible on the wire (bytes are identical)
            obs.annotate(fastpath_fallbacks=encoder.fallbacks)
        await resp.write(DONE)
    except (ConnectionResetError, ConnectionError):
        # the client disconnected mid-stream: nothing left to say to it,
        # but the abandoned pipeline must be torn down NOW — the finally
        # below acloses the generator chain, whose cleanup cancels the
        # upstream judge pumps and any batcher futures this request has
        # in flight (batcher._submit drops a cancelled item before its
        # group dispatches — no orphaned device work)
        obs.annotate(client_disconnect=True)
        metrics = request.app.get(METRICS_KEY)
        if metrics is not None:
            metrics.observe("http:client_disconnect", 0.0, error=True)
    finally:
        aclose = getattr(stream, "aclose", None)
        if aclose is not None:
            await aclose()
    return resp


def _parse_error_response(e: Exception) -> web.Response:
    """The parse-phase 400 policy, one definition for every endpoint.

    The EXPECTED malformed-request classes — SchemaError (path-annotated,
    types/base.py) and the json decoder's JSONDecodeError — are
    ValueErrors whose text describes the *client's input*: safe and
    useful to echo (the serde_path_to_error surface).  Anything else is a
    latent decoder bug, not client input: same masking policy as the 500
    envelope — detail to the server log only, never into the body."""
    if isinstance(e, ValueError):
        message: object = str(e)
    else:
        import logging

        from ..errors import MASKING_LOGGER

        logging.getLogger(MASKING_LOGGER).error(
            "unexpected parse-phase error", exc_info=e
        )
        message = "malformed request body"
    return web.Response(
        status=400,
        text=jsonutil.dumps(with_trace_id({"code": 400, "message": message})),
        content_type="application/json",
    )


def deadline_middleware(resilience):
    """Stamp the per-request deadline on the ambient contextvar.

    The client's ``x-deadline-ms`` header wins; the policy's
    ``deadline_ms`` is the default.  Because aiohttp runs each handler in
    its own task, the activation is naturally request-scoped and every
    task the fan-out spawns under it (judge pumps, hedge attempts)
    inherits the deadline."""
    from ..resilience import Deadline

    @web.middleware
    async def _mw(request, handler):
        ms = resilience.deadline_ms
        header = request.headers.get("x-deadline-ms")
        if header:
            try:
                ms = float(header)
            except ValueError:
                pass
        if ms <= 0:
            return await handler(request)
        token = Deadline(ms / 1000.0).activate()
        try:
            return await handler(request)
        finally:
            Deadline.deactivate(token)

    return _mw


# probes and the trace read endpoints are never themselves traced — a
# poller scraping /metrics must not churn the sampling budget, and
# reading traces must not mint traces
TRACE_EXEMPT_PATHS = frozenset({"/healthz", "/livez", "/readyz", "/metrics"})


def trace_middleware(sink):
    """The gateway door of the obs/ subsystem: extract an upstream
    ``traceparent`` (external callers stitch our tree under theirs),
    flip the head-sampling coin, run the whole request — middlewares
    included, so admission sheds land inside the root span — and offer
    the finished trace to the sink, which keeps it when sampled or when
    the outcome forced retention (5xx, shed, degraded, stream error)."""

    @web.middleware
    async def _mw(request, handler):
        if request.path in TRACE_EXEMPT_PATHS or request.path.startswith(
            ("/v1/traces", "/v1/judges")
        ):
            return await handler(request)
        upstream = obs.extract(request.headers)
        if upstream is not None:
            trace_id, parent_span_id, caller_sampled = upstream
            sampled = caller_sampled or sink.sample()
        else:
            trace_id = parent_span_id = None
            sampled = sink.sample()
        root = obs.start_trace(
            f"gateway:{request.method} {request.path}",
            sampled=sampled,
            trace_id=trace_id,
            parent_span_id=parent_span_id,
        )
        token = root.activate()
        status: Optional[int] = None
        try:
            resp = await handler(request)
            status = resp.status
            if not resp.prepared:
                resp.headers["x-trace-id"] = root.trace.trace_id
            return resp
        except Exception as e:
            root.set_error(e)
            raise
        finally:
            if status is not None:
                root.annotate(http_status=status)
                if status >= 500:
                    # sheds return their 503 rather than raising — the
                    # admission middleware annotated shed_reason already
                    root.status = "error"
                    root.trace.force(f"http_{status}")
            obs.Span.deactivate(token)
            root.finish()
            try:
                # the per-request phase attribution (obs/phases.py):
                # derived from the finished span tree and stamped on the
                # root, so every retained trace explains where its
                # milliseconds went without a second tool
                root.annotate(
                    phase_breakdown=obs.phase_breakdown(root.trace)
                )
            except Exception:
                pass  # attribution must never break serving
            sink.offer(root.trace)

    return _mw


def _trace_handlers(sink):
    """GET /v1/traces (recent index) + GET /v1/traces/{trace_id}."""

    async def index(request: web.Request):
        try:
            limit = int(request.query.get("limit", 50))
        except ValueError:
            limit = 50
        return web.json_response(
            {"traces": sink.index(limit=max(1, min(limit, sink.capacity)))}
        )

    async def get_one(request: web.Request):
        record = sink.get(request.match_info["trace_id"])
        if record is None:
            return web.json_response(
                {"code": 404, "message": "unknown trace_id"}, status=404
            )
        return web.json_response(record)

    return index, get_one


def _judge_handlers():
    """GET /v1/judges (all scorecards) + GET /v1/judges/{judge_id}.

    Reads the process-global quality aggregator (obs/quality.py), so
    the scorecards exist whether or not tracing or the ledger is
    configured — same always-on contract as the ``phases`` section."""
    from ..obs import quality as _quality

    async def index(request: web.Request):
        agg = _quality.quality_aggregator()
        return web.json_response(
            {
                "window": agg.window,
                "drift_threshold": agg.drift_threshold,
                "judges": agg.scorecards(),
            }
        )

    async def get_one(request: web.Request):
        card = _quality.quality_aggregator().scorecard(
            request.match_info["judge_id"]
        )
        if card is None:
            return web.json_response(
                {"code": 404, "message": "unknown judge id"}, status=404
            )
        return web.json_response(card)

    return index, get_one


def _weights_handlers(live_weights):
    """GET /v1/weights (active table + shadow counters) + PUT /v1/weights
    (validated atomic hot-swap — ISSUE 20 tentpole piece c).

    PUT body: ``{"weights": {judge_id: number, ...}, "version"?: str,
    "mode"?: "active"|"shadow"}``.  ``mode: "shadow"`` stages the table
    for would-have-flipped comparison without changing served verdicts;
    ``"weights": {}`` with ``"mode"`` clears that slot.  The swap is one
    assignment on the event loop, so in-flight tallies finish under the
    version they captured and the next tally sees the new one — zero
    client errors across a flip is the hot-swap drill's assertion."""

    async def get_weights(request: web.Request):
        return web.json_response(live_weights.wire())

    async def put_weights(request: web.Request):
        try:
            body = jsonutil.loads(await request.text())
        except Exception:
            return web.json_response(
                {"code": 400, "message": "body must be a JSON object"},
                status=400,
            )
        if not isinstance(body, dict) or not isinstance(
            body.get("weights"), dict
        ):
            return web.json_response(
                {"code": 400, "message": 'body needs a "weights" object'},
                status=400,
            )
        mode = body.get("mode", "active")
        try:
            if not body["weights"]:
                live_weights.clear(mode=mode)
                return web.json_response({"ok": True, "cleared": mode})
            version = live_weights.put(
                body["weights"], version=body.get("version"), mode=mode
            )
        except ValueError as e:
            return web.json_response(
                {"code": 400, "message": str(e)}, status=400
            )
        return web.json_response(
            {"ok": True, "version": version, "mode": mode}
        )

    return get_weights, put_weights


async def _weights_disabled(request: web.Request) -> web.Response:
    """/v1/weights without WEIGHTS_ENABLED/WEIGHTS_PATH: explicit 403,
    same contract as the /v1/profile guard."""
    return web.json_response(
        {
            "code": 403,
            "message": "live weights disabled: set WEIGHTS_ENABLED=1 "
            "or WEIGHTS_PATH",
        },
        status=403,
    )


def _offline_rescore_handler(batcher, default_inflight: int = 4):
    """POST /v1/train/rescore: saturate the offline priority class with
    deterministic synthetic candidate groups and report the lane stats —
    the HTTP face of ``python -m ...train rescore`` the bench drill
    drives concurrently with latency traffic.

    Body (all optional): ``{"groups": int, "n": int, "seed": int,
    "inflight": int, "temperature": float}``.  Runs the drive to
    completion in-handler and returns ``{groups, items, errors,
    offline_occupancy, lanes}`` so the caller gets the merged-interval
    occupancy gauge in the same response.  One drive at a time (409 on
    overlap) — two saturators would double-count each other's idle."""
    import asyncio

    lock = asyncio.Lock()

    async def rescore(request: web.Request):
        from ..train.feed import OfflineFeed, synthetic_groups

        if lock.locked():
            return web.json_response(
                {"code": 409, "message": "a rescore drive is already running"},
                status=409,
            )
        try:
            body = jsonutil.loads(await request.text()) if (
                request.can_read_body
            ) else {}
        except Exception:
            body = {}
        if not isinstance(body, dict):
            body = {}
        try:
            n_groups = max(1, min(int(body.get("groups", 32)), 4096))
            n = max(2, min(int(body.get("n", 8)), MAX_CONSENSUS_CANDIDATES))
            seed = int(body.get("seed", 0))
            inflight = max(1, min(int(body.get("inflight", default_inflight)), 64))
            temperature = float(body.get("temperature", 0.05))
        except (TypeError, ValueError):
            return web.json_response(
                {"code": 400, "message": "rescore params must be numeric"},
                status=400,
            )
        async with lock:
            feed = OfflineFeed(batcher, inflight=inflight)
            _results, occupancy = await feed.drive(
                synthetic_groups(n_groups, n, seed=seed),
                temperature=temperature,
            )
        return web.json_response(
            {
                "ok": True,
                "groups": feed.groups,
                "items": feed.items,
                "errors": feed.errors,
                "offline_occupancy": occupancy,
                "lanes": batcher.utilization()["lanes"],
            }
        )

    return rescore


async def _offline_rescore_disabled(request: web.Request) -> web.Response:
    """/v1/train/rescore without OFFLINE_ENABLED (or without a device
    batcher): explicit 403, same contract as the /v1/profile guard."""
    return web.json_response(
        {
            "code": 403,
            "message": "offline lane disabled: set OFFLINE_ENABLED=1 "
            "(and configure EMBED_MODEL)",
        },
        status=403,
    )


def _make_handler(params_cls, create_streaming, create_unary, fastpath=False):
    async def handler(request: web.Request):
        try:
            body = jsonutil.loads(await request.text())
            params = params_cls.from_json_obj(body)
        except web.HTTPException:
            raise  # e.g. 413 body-too-large must keep its status
        except Exception as e:  # parse phase is side-effect free: never
            # a server-state fault — 400 with the path-annotated message
            # (or masked, for non-ValueError: see _parse_error_response)
            return _parse_error_response(e)
        ctx = request.headers.get("authorization")
        if params.stream:
            try:
                stream = await create_streaming(ctx, params)
            except Exception as e:
                return _error_response(e)
            return await _respond_streaming(request, stream, fastpath)
        try:
            result = await create_unary(ctx, params)
        except Exception as e:
            return _error_response(e)
        return web.Response(
            text=result.to_json(), content_type="application/json"
        )

    return handler


async def _with_consensus_frames(stream, embedder, metrics=None, batcher=None):
    """Interleave live ``multichat.consensus`` frames into a multichat
    stream; embeds + revotes run off the loop — through the micro-batcher
    (shared dispatches across concurrent streams) when one is attached."""
    from ..clients.multichat import ConsensusUpdate, StreamingSelfConsistency

    sc = StreamingSelfConsistency(embedder, batcher=batcher)
    try:
        async for chunk in stream:
            yield chunk
            if isinstance(chunk, Exception) or sc is None:
                continue
            t0 = _time.perf_counter()
            try:
                update = await sc.push_chunk_async(chunk)
            except Exception:
                # consensus frames are an overlay on the multichat stream:
                # an embedder failure degrades to plain multichat (no more
                # consensus frames) rather than tearing the stream down
                if metrics is not None:
                    metrics.observe(
                        "device:consensus_update",
                        (_time.perf_counter() - t0) * 1e3,
                        error=True,
                    )
                sc = None
                continue
            if update is not None:
                if metrics is not None:
                    metrics.observe(
                        "device:consensus_update",
                        (_time.perf_counter() - t0) * 1e3,
                    )
                yield ConsensusUpdate(update)
    finally:
        # client disconnects surface here as GeneratorExit; the inner
        # stream's cleanup must still run
        aclose = getattr(stream, "aclose", None)
        if aclose is not None:
            await aclose()


def _multichat_streaming(multichat_client, embedder, metrics, batcher=None):
    async def create_streaming(ctx, params):
        stream = await multichat_client.create_streaming(ctx, params)
        if params.consensus and embedder is not None:
            return _with_consensus_frames(stream, embedder, metrics, batcher)
        return stream

    return create_streaming


def _multichat_unary(multichat_client, embedder, batcher):
    """Unary multichat with ``consensus: true``: after the fold, embed all
    finished candidates + consensus-vote in ONE fused dispatch and attach
    the confidence distribution (the unary view of the streaming
    ``multichat.consensus`` frames).  The batcher coalesces concurrent
    requests with the same candidate count into one device batch
    (``consensus_confidence_tokens_many``)."""

    async def create_unary(ctx, params):
        result = await multichat_client.create_unary(ctx, params)
        if not (params.consensus and embedder is not None and batcher):
            return result
        slots, texts = [], []
        for choice in result.choices:
            content = getattr(choice.message, "content", None)
            if choice.error is None and isinstance(content, str) and content:
                slots.append(choice.index)
                texts.append(content)
        if len(texts) >= 2:
            try:
                conf, _tokens = await batcher.consensus(texts)
            except Exception:
                # the consensus is an overlay on the multichat result: an
                # embedder failure degrades to plain multichat (no
                # `consensus` field) rather than discarding N completed
                # generations with a 5xx — mirrors the streaming path
                return result
            result.consensus = {
                str(slot): float(c) for slot, c in zip(slots, conf)
            }
        return result

    return create_unary


def _profile_handlers(profile_dir: str):
    """JAX profiler control (SURVEY §5 tracing row): traces land under
    ``profile_dir`` in xprof format.  One trace at a time; stop without
    start is a 400 rather than a crash."""
    import asyncio

    # one lock serializes start/stop end-to-end: the JAX profiler is a
    # process-global singleton, so overlapping operations (a start racing
    # an in-flight stop's serialization) must queue, and a concurrent
    # duplicate gets the clean 400 once the lock frees
    state = {"active": False, "lock": asyncio.Lock()}

    async def start(request: web.Request):
        import jax

        async with state["lock"]:
            if state["active"]:
                return web.json_response(
                    {"code": 400, "message": "trace already active"},
                    status=400,
                )
            try:
                # profiler IO runs on the executor; the loop keeps serving
                await asyncio.get_running_loop().run_in_executor(
                    None, jax.profiler.start_trace, profile_dir
                )
            except Exception as e:
                return _error_response(e)
            state["active"] = True
        return web.json_response({"ok": True, "dir": profile_dir})

    async def stop(request: web.Request):
        import jax

        async with state["lock"]:
            if not state["active"]:
                return web.json_response(
                    {"code": 400, "message": "no active trace"}, status=400
                )
            # cleared regardless of outcome so a failed serialization
            # can't wedge the endpoints; the error still surfaces
            state["active"] = False
            try:
                # trace serialization can be hundreds of MB — never on
                # the loop
                await asyncio.get_running_loop().run_in_executor(
                    None, jax.profiler.stop_trace
                )
            except Exception as e:
                return _error_response(e)
        return web.json_response({"ok": True, "dir": profile_dir})

    async def capture(request: web.Request):
        """POST /v1/profile: one-shot capture — start, sleep the
        requested window while live traffic runs, stop.  Bounded so a
        fat-fingered duration can't leave the profiler running; the
        admission middleware exempts this path (profiling an overload
        is the point), so the guard here is PROFILE_DIR alone."""
        import asyncio

        import jax

        try:
            body = jsonutil.loads(await request.text() or "{}")
        except Exception:
            body = {}
        duration_ms = float(body.get("duration_ms", 500.0) or 500.0)
        duration_ms = min(10_000.0, max(10.0, duration_ms))
        async with state["lock"]:
            if state["active"]:
                return web.json_response(
                    {"code": 400, "message": "trace already active"},
                    status=400,
                )
            state["active"] = True
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(
                None, jax.profiler.start_trace, profile_dir
            )
            # capture window: the loop keeps serving, so in-flight and
            # new requests land inside the trace
            await asyncio.sleep(duration_ms / 1e3)
            await loop.run_in_executor(None, jax.profiler.stop_trace)
        except Exception as e:
            return _error_response(e)
        finally:
            async with state["lock"]:
                state["active"] = False
        return web.json_response(
            {"ok": True, "dir": profile_dir, "duration_ms": duration_ms}
        )

    return start, stop, capture


async def _profile_disabled(request: web.Request) -> web.Response:
    """POST /v1/profile without PROFILE_DIR: a clear 403, not a 404 —
    the endpoint exists, the operator just hasn't enabled it."""
    return web.json_response(
        {"code": 403, "message": "profiling disabled: set PROFILE_DIR"},
        status=403,
    )


def _roofline_gauge(embedder):
    """Wire the live roofline-attainment gauge (ISSUE 11 tentpole piece
    3) when a device path exists: committed per-bucket ceilings from
    analysis/roofline.json against the live device-time histograms.
    Import-guarded — the gauge is observability, never a serving
    dependency."""
    if embedder is None:
        return None
    try:
        import jax

        from ..analysis.roofline import (
            RooflineGauge,
            default_roofline_path,
            load_roofline,
        )

        roofline = load_roofline(default_roofline_path())
        if not roofline:
            return None
        return RooflineGauge(roofline, jax.default_backend())
    except Exception:
        return None


def build_app(
    chat_client,
    score_client,
    multichat_client=None,
    embedder=None,
    metrics=None,
    profile_dir=None,
    batcher=None,
    batch_window_ms: float = 3.0,
    batch_max: int = 64,
    packing: bool = False,
    packing_row_tokens: int = 512,
    packing_max_rows: int = 8,
    packing_max_segments: int = 64,
    prefix_dedup: bool = True,
    prefix_dedup_min_chars: int = 48,
    reranker=None,
    embed_cache=None,
    resilience=None,
    fault_plan=None,
    admission=None,
    lifecycle=None,
    watchdog=None,
    meshfault=None,
    trace_sink=None,
    ledger=None,
    fleet=None,
    host_fastpath: bool = False,
    memguard=None,
    max_body_bytes: int = 0,
    live_weights=None,
    offline_enabled: bool = False,
    offline_inflight: int = 4,
) -> web.Application:
    metrics = metrics or Metrics()
    register_resilience(metrics, resilience, fault_plan)
    register_overload(metrics, admission, watchdog, lifecycle, memguard)
    register_performance(metrics, _roofline_gauge(embedder))
    register_quality(metrics, ledger, live_weights)
    if embedder is not None and batcher is None:
        from .batcher import DeviceBatcher

        batcher = DeviceBatcher(
            embedder,
            metrics,
            window_ms=batch_window_ms,
            max_batch=batch_max,
            packing=packing,
            packing_row_tokens=packing_row_tokens,
            packing_max_rows=packing_max_rows,
            packing_max_segments=packing_max_segments,
            prefix_dedup=prefix_dedup,
            prefix_dedup_min_chars=prefix_dedup_min_chars,
            embed_cache=embed_cache,
            watchdog=watchdog,
            max_queue_depth=(
                admission.config.max_queue_depth
                if admission is not None
                else 0
            ),
        )
    # consensus result cache counters (hits/misses/evictions + in-flight
    # collapses) surface as the `score_cache` section of GET /metrics;
    # the score client may arrive wrapped (_ArchivingClient delegates)
    inner_score = getattr(score_client, "_inner", score_client)
    score_cache = getattr(inner_score, "cache", None)
    if score_cache is not None:
        score_flights = getattr(inner_score, "flights", None)

        def _score_cache_stats():
            stats = score_cache.stats()
            stats["inflight_collapses"] = (
                score_flights.collapses if score_flights is not None else 0
            )
            return stats

        metrics.register_provider("score_cache", _score_cache_stats)
    middlewares = []
    if trace_sink is not None:
        # outermost: the root span brackets everything, and the metrics
        # middleware inside it observes with the ambient trace active
        # (that read is where the per-series trace_id exemplars come from)
        middlewares.append(trace_middleware(trace_sink))
        metrics.register_provider("traces", trace_sink.snapshot)
    middlewares.append(middleware(metrics))
    # inside metrics (413s are observable per route), outside admission
    # (an oversized body should not burn an admission slot's error
    # accounting on its way out)
    middlewares.append(payload_cap_middleware())
    if admission is not None:
        # inside metrics (sheds are observable per route), outside the
        # deadline stamp (shed work should not even start a budget)
        from ..resilience.admission import admission_middleware

        middlewares.append(admission_middleware(admission))
    if resilience is not None:
        middlewares.append(deadline_middleware(resilience))
    elif fleet is not None:
        # fleet peer calls forward their clamped budget as x-deadline-ms
        # (fleet/client.py); honoring it server-side needs the deadline
        # stamp even with the resilience subsystem off.  No default
        # budget — header-only, so non-fleet requests are untouched
        class _HeaderOnlyDeadline:
            deadline_ms = 0.0

        middlewares.append(deadline_middleware(_HeaderOnlyDeadline()))
    # MAX_BODY_BYTES → aiohttp's own pre-parse body cap; covers every
    # route on this app, /fleet/v1 included.  0 keeps aiohttp's default
    # rather than lifting the cap — the gateway never runs unbounded
    app = web.Application(
        middlewares=middlewares,
        client_max_size=(
            max_body_bytes if max_body_bytes > 0 else _AIOHTTP_DEFAULT_BODY_BYTES
        ),
    )
    app[METRICS_KEY] = metrics
    if fleet is not None:
        # the replica-to-replica surface (/fleet/v1/*, fleet/handlers.py)
        # plus the `fleet` metrics section (membership, leases, peer
        # fetch and handoff counters)
        from ..fleet import register_fleet_routes

        register_fleet_routes(app, fleet)
        metrics.register_provider("fleet", fleet.stats)
    if lifecycle is not None:
        app[LIFECYCLE_KEY] = lifecycle
    if meshfault is not None:
        app[MESHFAULT_KEY] = meshfault
    if batcher is not None:
        app[BATCHER_KEY] = batcher

        async def _close_batcher(app):
            batcher.close()

        app.on_cleanup.append(_close_batcher)
    app.router.add_post(
        "/chat/completions",
        _make_handler(
            ChatParams,
            chat_client.create_streaming,
            chat_client.create_unary,
            fastpath=host_fastpath,
        ),
    )
    app.router.add_post(
        "/score/completions",
        _make_handler(
            ScoreParams,
            score_client.create_streaming,
            score_client.create_unary,
            fastpath=host_fastpath,
        ),
    )
    if multichat_client is not None:
        app.router.add_post(
            "/multichat/completions",
            _make_handler(
                MultichatParams,
                _multichat_streaming(
                    multichat_client, embedder, metrics, batcher
                ),
                _multichat_unary(multichat_client, embedder, batcher),
                fastpath=host_fastpath,
            ),
        )
    if embedder is not None:
        app.router.add_post(
            "/embeddings", _embeddings_handler(embedder, metrics, batcher)
        )
    if embedder is not None or reranker is not None:
        app.router.add_post(
            "/consensus",
            _consensus_handler(embedder, metrics, batcher, reranker),
        )

    async def healthz(request):
        # deprecated alias for the /livez + /readyz split: kept
        # byte-identical for pre-split probers
        return web.json_response({"ok": True})

    async def metrics_handler(request):
        # ?format=prometheus flips the same data into OpenMetrics text
        # (histogram families + exemplars); the default JSON snapshot
        # keeps its PR 5 shape for existing scrapers and the bench tools
        if request.query.get("format") == "prometheus":
            return web.Response(
                body=render_prometheus(metrics).encode("utf-8"),
                headers={"Content-Type": PROM_CONTENT_TYPE},
            )
        return web.json_response(metrics.snapshot())

    from .lifecycle import health_handlers

    livez, readyz = health_handlers(lifecycle)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/livez", livez)
    app.router.add_get("/readyz", readyz)
    app.router.add_get("/metrics", metrics_handler)
    if trace_sink is not None:
        traces_index, traces_get = _trace_handlers(trace_sink)
        app.router.add_get("/v1/traces", traces_index)
        app.router.add_get("/v1/traces/{trace_id}", traces_get)
    judges_index, judges_get = _judge_handlers()
    app.router.add_get("/v1/judges", judges_index)
    app.router.add_get("/v1/judges/{judge_id}", judges_get)
    if live_weights is not None:
        weights_get, weights_put = _weights_handlers(live_weights)
        app.router.add_get("/v1/weights", weights_get)
        app.router.add_put("/v1/weights", weights_put)
    else:
        # registered either way so the guard is an explicit 403, not a
        # confusable 404 (same contract as /v1/profile below)
        app.router.add_get("/v1/weights", _weights_disabled)
        app.router.add_put("/v1/weights", _weights_disabled)
    if offline_enabled and batcher is not None:
        app.router.add_post(
            "/v1/train/rescore",
            _offline_rescore_handler(batcher, default_inflight=offline_inflight),
        )
    else:
        app.router.add_post("/v1/train/rescore", _offline_rescore_disabled)
    if profile_dir:
        start, stop, capture = _profile_handlers(profile_dir)
        app.router.add_post("/profile/start", start)
        app.router.add_post("/profile/stop", stop)
        app.router.add_post("/v1/profile", capture)
    else:
        # registered either way so the guard is an explicit 403, not a
        # confusable 404
        app.router.add_post("/v1/profile", _profile_disabled)
    return app


# /consensus request-size ceiling: bounds the device batch a single
# request can demand, and — because the candidate count is a jit-static
# shape — bounds the total set of compiled specializations a client can
# force (temperature is traced, so it can never force one)
MAX_CONSENSUS_CANDIDATES = 256


def _consensus_handler(embedder, metrics=None, batcher=None, reranker=None):
    """POST /consensus: the device scorer as a direct service — N
    candidate texts in, a confidence distribution out.

    Two scorers: ``"cosine"`` (default) is the embedding self-consistency
    vote (one fused embed+vote dispatch; concurrent requests coalesce via
    the micro-batcher — the HTTP analog of the headline bench path);
    ``"rm"`` re-ranks by reward model: softmax(reward/T) over the
    candidates, each scored against the optional ``prompt`` (BASELINE
    config 3 as a service).  No reference analog (its scoring always
    goes through judge LLMs; SURVEY §2.6).

    Body: {"input": [texts...], "scorer"?: "cosine"|"rm",
    "prompt"?: str, "temperature"?: float}.  Response: {"model",
    "scorer", "confidence": [...], "usage": {prompt_tokens,
    total_tokens}}.
    """
    import asyncio

    async def handler(request: web.Request):
        try:
            body = jsonutil.loads(await request.text())
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            texts = body.get("input")
            if (
                not isinstance(texts, list)
                or len(texts) < 2
                or not all(isinstance(t, str) for t in texts)
            ):
                raise ValueError(
                    "`input` must be a list of >= 2 candidate strings"
                )
            if len(texts) > MAX_CONSENSUS_CANDIDATES:
                raise ValueError(
                    f"`input` accepts at most {MAX_CONSENSUS_CANDIDATES} "
                    "candidates per request"
                )
            scorer = body.get("scorer", "cosine")
            if scorer not in ("cosine", "rm"):
                raise ValueError(
                    "`scorer` must be 'cosine' or 'rm'"
                )
            if scorer == "cosine" and embedder is None:
                raise ValueError(
                    "cosine scorer unavailable: no EMBEDDER_MODEL configured"
                )
            if scorer == "rm" and reranker is None:
                raise ValueError(
                    "rm scorer unavailable: no RM_MODEL configured"
                )
            prompt = body.get("prompt")
            if prompt is not None and not isinstance(prompt, str):
                raise ValueError("`prompt` must be a string")
            traw = body.get(
                "temperature", 0.05 if scorer == "cosine" else 1.0
            )
            # explicit type check, not bare float(): a non-numeric value
            # must raise the ValueError the 400 policy echoes, never a
            # TypeError the policy masks as a server bug (jsonutil.loads
            # parses JSON floats as Decimal)
            from decimal import Decimal as _Decimal

            if isinstance(traw, bool) or not isinstance(
                traw, (int, float, _Decimal)
            ):
                raise ValueError("`temperature` must be a number")
            temperature = float(traw)
            import math

            if not math.isfinite(temperature) or temperature <= 0:
                raise ValueError(
                    "`temperature` must be a finite positive number"
                )
        except web.HTTPException:
            raise  # e.g. 413 body-too-large must keep its status
        except Exception as e:  # parse phase is side-effect free
            return _parse_error_response(e)
        loop = asyncio.get_running_loop()
        try:
            if scorer == "rm":
                t0 = _time.perf_counter()
                conf, tokens = await loop.run_in_executor(
                    None,
                    lambda: reranker.rerank_confidence(
                        texts, prompt=prompt, temperature=temperature
                    ),
                )
                if metrics is not None:
                    metrics.observe(
                        "device:rm_vote",
                        (_time.perf_counter() - t0) * 1e3,
                    )
                model_name = reranker.model_name
            elif batcher is not None:
                conf, tokens = await batcher.consensus(texts, temperature)
                model_name = embedder.model_name
            else:

                def run():
                    ids, mask = embedder.tokenize(texts)
                    return (
                        embedder.consensus_confidence_tokens(
                            ids, mask, temperature
                        ),
                        int(mask.sum()),
                    )

                t0 = _time.perf_counter()
                conf, tokens = await loop.run_in_executor(None, run)
                if metrics is not None:
                    metrics.observe(
                        "device:consensus",
                        (_time.perf_counter() - t0) * 1e3,
                    )
                model_name = embedder.model_name
        except Exception as e:
            return _error_response(e)
        import numpy as np

        conf = np.asarray(conf)
        return web.Response(
            text=jsonutil.dumps(
                {
                    "model": model_name,
                    "scorer": scorer,
                    "confidence": [float(c) for c in conf],
                    "usage": {
                        "prompt_tokens": tokens,
                        "total_tokens": tokens,
                    },
                }
            ),
            content_type="application/json",
        )

    return handler


def _embeddings_handler(embedder, metrics=None, batcher=None):
    async def handler(request: web.Request):
        try:
            params = CreateEmbeddingParams.from_json_obj(
                jsonutil.loads(await request.text())
            )
        except web.HTTPException:
            raise  # e.g. 413 body-too-large must keep its status
        except Exception as e:  # parse phase is side-effect free
            return _parse_error_response(e)
        if params.model and params.model != embedder.model_name:
            return web.Response(
                status=400,
                text=jsonutil.dumps(
                    {
                        "code": 400,
                        "message": f"unknown embeddings model {params.model!r}; "
                        f"this gateway serves {embedder.model_name!r}",
                    }
                ),
                content_type="application/json",
            )
        import asyncio

        try:
            if batcher is not None:
                # the micro-batcher coalesces concurrent requests' texts
                # into one tokenize + one embed_tokens dispatch; response
                # assembly (per-row tolist over possibly thousands of
                # vectors) still stays off the event loop
                emb, tokens = await batcher.embed(params.inputs())
                resp = await asyncio.get_running_loop().run_in_executor(
                    None, embedder.wire_response, emb, tokens
                )
            else:
                # the device forward blocks; keep the event loop responsive
                t0 = _time.perf_counter()
                resp = await asyncio.get_running_loop().run_in_executor(
                    None, embedder.embeddings_response, params.inputs()
                )
                if metrics is not None:
                    metrics.observe(
                        "device:embed", (_time.perf_counter() - t0) * 1e3
                    )
        except Exception as e:
            return _error_response(e)
        return web.Response(
            text=resp.to_json(), content_type="application/json"
        )

    return handler
