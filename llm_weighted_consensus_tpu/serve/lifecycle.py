"""Serving lifecycle: liveness/readiness split + graceful drain.

The reference binary dies however tokio dies; a serving edge behind a
load balancer needs the standard Kubernetes-shaped lifecycle instead:

* ``GET /livez``  — process liveness: 200 as long as the event loop
  answers.  Restarting on livez failure is the supervisor's job.
* ``GET /readyz`` — traffic readiness: 200 only while the service is
  ``READY`` *and* the device watchdog (when configured) holds the
  device healthy.  Flips to 503 the instant a drain begins or the
  device wedges, so the balancer routes away while in-flight work
  finishes.  (``/healthz`` stays, byte-identical, as the deprecated
  pre-split alias.)
* SIGTERM/SIGINT → ``begin_drain()``: readiness flips, admission stops
  (new requests shed with ``shed_reason: "draining"``), in-flight
  streams run to their ``[DONE]`` and the device batcher's queue
  empties — all bounded by ``DRAIN_TIMEOUT_MILLIS`` — then the cache
  disk tier is flushed exactly once and the process exits 0.

State machine: READY → DRAINING → STOPPED, one way.  ``begin_drain`` is
idempotent (a supervisor re-sending SIGTERM joins the drain already in
progress rather than restarting it).
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

READY = "ready"
DRAINING = "draining"
STOPPED = "stopped"


class Lifecycle:
    def __init__(
        self,
        *,
        admission=None,
        batcher=None,
        caches=(),
        watchdog=None,
        memguard=None,
        meshfault=None,
        fleet=None,
        drain_timeout_ms: float = 10000.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.admission = admission
        self.batcher = batcher
        # cache stores with a flush() hook (cache/store.py); flushed
        # exactly once, after the queues drain, so the disk tier holds
        # everything the final dispatches produced
        self.caches = [c for c in caches if c is not None]
        self.watchdog = watchdog
        # host memory governor (resilience/memguard.py): pressure keeps
        # /readyz at 200 with a degraded_mem flag — shedding under hard
        # pressure is admission's job, and a replica recovering memory
        # is still the best home for its in-flight work
        self.memguard = memguard
        # mesh fault domains (resilience/meshfault.py): a downsized-but-
        # serving mesh stays READY — /readyz reports 200 with a
        # degraded_mesh flag, never 503, because proportional capacity
        # is still capacity
        self.meshfault = meshfault
        # fleet coordinator (fleet/): the drain pushes this replica's hot
        # cache entries to their post-drain owners before the process
        # exits — a departing replica's hot set survives it
        self.fleet = fleet
        self.handoff_entries: Optional[int] = None
        self.drain_timeout_ms = float(drain_timeout_ms)
        self.clock = clock
        self.state = READY
        self.drained_clean: Optional[bool] = None
        self.drain_elapsed_ms: Optional[float] = None
        self.cache_flushes = 0
        self._drain_task: Optional[asyncio.Task] = None

    # -- readiness ------------------------------------------------------------

    def ready(self):
        """(is_ready, reason) — the /readyz decision."""
        if self.state != READY:
            return False, self.state
        if self.watchdog is not None and not self.watchdog.healthy():
            return False, "device_unhealthy"
        return True, None

    # -- drain ----------------------------------------------------------------

    def begin_drain(self) -> asyncio.Task:
        """Start (or join) the drain; the returned task completes when
        the drain does.  Idempotent — every SIGTERM after the first
        awaits the same drain."""
        if self._drain_task is None:
            self._drain_task = asyncio.get_running_loop().create_task(
                self._drain()
            )
        return self._drain_task

    async def _drain(self) -> bool:
        t0 = self.clock()
        deadline = t0 + self.drain_timeout_ms / 1e3
        # 0. fleet hot-set handoff BEFORE readiness flips: the entries
        #    this replica owns move to their post-drain owners while
        #    peers can still fetch from us, so a fleet-wide hot key
        #    stays a cache hit across the departure.  Bounded work
        #    (HANDOFF_MAX_ENTRIES, per-peer timeouts); any failure is
        #    skipped — the fleet re-computes what it must
        #    The handoff may spend at most HALF the drain budget: under
        #    a partition every push times out serially-ish even with the
        #    concurrent gather, and the in-flight streams' share of the
        #    budget must survive a fully dark fleet
        if self.fleet is not None:
            try:
                self.handoff_entries = await asyncio.wait_for(
                    self.fleet.handoff(
                        self.caches[0] if self.caches else None
                    ),
                    timeout=max(0.05, (deadline - self.clock()) * 0.5),
                )
            except Exception:
                self.handoff_entries = 0
        # 1. stop admitting BEFORE waiting: readiness flips (the LB
        #    routes away) and the admission gate sheds everything new
        #    with a retryable 503, so the in-flight set only shrinks
        self.state = DRAINING
        if self.admission is not None:
            self.admission.draining = True
        # 2. in-flight requests run to completion (streams hold their
        #    admission slot until the [DONE] frame is written)
        clean = True
        if self.admission is not None:
            while self.admission.inflight > 0:
                if self.clock() >= deadline:
                    clean = False
                    break
                await asyncio.sleep(0.01)
        # 3. the device batcher's queue empties (nothing refills it —
        #    admission already stopped)
        if self.batcher is not None:
            remaining = max(0.0, deadline - self.clock())
            clean = await self.batcher.drain(remaining) and clean
        # 4. flush the cache disk tier exactly once: the last dispatched
        #    results must be on disk before the process exits
        for cache in self.caches:
            cache.flush()
            self.cache_flushes += 1
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.memguard is not None:
            self.memguard.stop()
        self.state = STOPPED
        self.drained_clean = clean
        self.drain_elapsed_ms = (self.clock() - t0) * 1e3
        return clean

    # -- observability --------------------------------------------------------

    def snapshot(self) -> dict:
        out = {
            "state": self.state,
            "drain_timeout_ms": self.drain_timeout_ms,
            "cache_flushes": self.cache_flushes,
        }
        if self.drained_clean is not None:
            out["drained_clean"] = self.drained_clean
            out["drain_elapsed_ms"] = round(self.drain_elapsed_ms, 1)
        if self.handoff_entries is not None:
            out["fleet_handoff_entries"] = self.handoff_entries
        return out


def health_handlers(lifecycle: Optional[Lifecycle]):
    """(livez, readyz) aiohttp handlers; a ``lifecycle`` of None (apps
    built without the lifecycle wiring, e.g. unit-test gateways) is
    always ready — the pre-split /healthz semantics."""
    from aiohttp import web

    async def livez(request):
        return web.json_response({"ok": True})

    async def readyz(request):
        if lifecycle is None:
            return web.json_response({"ready": True})
        ok, reason = lifecycle.ready()
        if ok:
            body = {"ready": True}
            mf = lifecycle.meshfault
            if mf is not None and mf.degraded:
                # still 200: the downsized mesh serves real traffic at
                # proportional capacity — the balancer must keep routing
                # here, operators read the flag (and the meshfault
                # /metrics section) for the degradation
                body["degraded_mesh"] = True
                body["mesh_shape"] = list(mf.current_shape)
            mg = lifecycle.memguard
            if mg is not None and mg.degraded:
                # still 200 for the same reason as degraded_mesh: soft
                # pressure serves everything, hard pressure sheds at
                # admission with a retryable 503 — either way in-flight
                # work is finishing and the balancer should keep probing
                body["degraded_mem"] = True
                body["mem_level"] = mg.snapshot()["level"]
            if lifecycle.fleet is not None:
                # the balancer-facing view of fleet membership: who this
                # replica is, the roster it sees, and the key-space share
                # it currently owns (full counters live in /metrics)
                body["fleet"] = lifecycle.fleet.membership.snapshot()
            return web.json_response(body)
        return web.json_response(
            {"ready": False, "reason": reason}, status=503
        )

    return livez, readyz
