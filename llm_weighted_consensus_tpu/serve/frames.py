"""SSE frame assembly for the streaming gateway — the fast lane's door.

This is the ONLY module allowed to serialize response frames inside the
per-chunk merge loop (enforced by analyzer rule LWC017): the gateway's
``async for`` bodies call :class:`FrameEncoder` and never touch
``to_json_obj``/``jsonutil.dumps`` themselves, so the whole per-chunk
byte path is auditable in one place.

Two lanes, one output:

* slow (default): ``dumps(item.to_json_obj())`` per frame — exactly the
  pre-fast-lane behavior.
* fast (``HOST_FASTPATH``): splice serialization over the byte templates
  compiled next to the codec plans (types/base.py ``SpliceEncoder``) —
  per-stream caches patch only the fields that changed.  Any frame the
  splicer cannot prove byte-identical falls back to the slow lane for
  that frame and counts the fallback (``FrameEncoder.fallbacks``), so
  divergence is impossible and silent degradation is observable.

Byte-identity of the two lanes is property-tested across seeded chunk
orders, degraded frames, and per-judge errors in
tests/test_host_fastpath.py.
"""

from __future__ import annotations

from ..errors import to_response_error, with_trace_id
from ..types.base import SpliceEncoder
from ..utils import jsonutil

DONE = b"data: [DONE]\n\n"
_PREFIX = b"data: "
_SUFFIX = b"\n\n"


def frame_bytes(obj) -> bytes:
    """One SSE ``data:`` frame around an already-encoded JSON object —
    the slow lane's rendering, shared by both lanes' fallbacks."""
    return _PREFIX + jsonutil.dumps(obj).encode("utf-8") + _SUFFIX


class FrameEncoder:
    """Per-stream encoder of SSE ``data:`` frames.

    One instance serves one response stream — the splice caches key on
    per-stream stable values (response id, choice metadata) and must not
    leak across requests.
    """

    __slots__ = ("_splicer", "fallbacks")

    def __init__(self, fastpath: bool = False):
        self._splicer = SpliceEncoder() if fastpath else None
        # frames the fast lane handed back to the slow lane (0 on the
        # slow lane itself); the gateway annotates the trace when >0
        self.fallbacks = 0

    def encode(self, item) -> bytes:
        """Frame for a response chunk (a Struct)."""
        splicer = self._splicer
        if splicer is not None:
            try:
                return _PREFIX + splicer.encode(item) + _SUFFIX
            except Exception:
                # loud fallback: counted here, annotated by the caller;
                # whatever the splicer choked on, the slow lane below
                # either renders it or raises the slow path's own error
                self.fallbacks += 1
        return frame_bytes(item.to_json_obj())

    def encode_error(self, exc: Exception) -> bytes:
        """Frame for a mid-stream error item (always the slow lane:
        errors are rare and carry the trace id)."""
        return frame_bytes(with_trace_id(to_response_error(exc).to_json_obj()))
