"""Out-of-band service metrics (SURVEY §5 metrics row: "add ordinary
service metrics (qps, p50, device util) out-of-band").

The reference keeps all observability in-band (per-choice
``completion_metadata`` + usage/cost accounting); that is preserved
bit-exact in the wire types.  This module adds the service-level view the
reference lacks: per-endpoint request counts and latency percentiles plus
device dispatch timings, exposed at ``GET /metrics``.
"""

from __future__ import annotations

import time
from collections import deque

_RESERVOIR = 1024  # recent samples kept per series

# Every provider-section name that may appear in the /metrics snapshot.
# The registry the LWC010 lint checks both ways: a `register_provider`
# call with a name not listed here fails lint (dashboards/tests grep
# these keys, so ad-hoc names silently vanish from alerting), and a
# listed name no call site registers is a stale entry to delete.
KNOWN_SECTIONS = (
    "resilience",
    "admission",
    "device_watchdog",
    "lifecycle",
    "device_batcher",
    "embed_cache",
    "score_cache",
    "traces",
    "jit",
    "mesh",
    "meshfault",
)


class Metrics:
    def __init__(self) -> None:
        self._counts: dict = {}
        self._errors: dict = {}
        self._latencies: dict = {}
        self._providers: dict = {}
        self._exemplars: dict = {}
        self._started = time.time()

    def observe(
        self,
        series: str,
        ms: float,
        *,
        error: bool = False,
        trace_id=None,
    ) -> None:
        self._counts[series] = self._counts.get(series, 0) + 1
        if error:
            self._errors[series] = self._errors.get(series, 0) + 1
        self._latencies.setdefault(series, deque(maxlen=_RESERVOIR)).append(ms)
        if trace_id is not None:
            # trace-id exemplar (Prometheus-exemplar analog): the most
            # recent traced request on this series — an aggregate that
            # looks wrong links straight to one concrete span tree.
            # Passed EXPLICITLY by call sites that know the right trace
            # (ambient reads here would pick up stale contexts from
            # long-lived tasks like the batcher's flusher).
            self._exemplars[series] = trace_id

    def register_provider(self, name: str, fn) -> None:
        """Attach a live gauge section to the snapshot (e.g. the device
        batcher's queue depth / busy fraction — SURVEY §5 "device util")."""
        self._providers[name] = fn

    def snapshot(self) -> dict:
        out = {}
        for series, count in sorted(self._counts.items()):
            lat = sorted(self._latencies.get(series, ()))
            entry = {"count": count, "errors": self._errors.get(series, 0)}
            if lat:
                entry["p50_ms"] = round(lat[len(lat) // 2], 2)
                entry["p99_ms"] = round(
                    lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2
                )
            exemplar = self._exemplars.get(series)
            if exemplar is not None:
                entry["trace_id"] = exemplar
            out[series] = entry
        snap = {
            "uptime_sec": round(time.time() - self._started, 1),
            "series": out,
        }
        for name, fn in self._providers.items():
            try:
                snap[name] = fn()
            except Exception as e:  # a broken gauge must not break /metrics
                snap[name] = {"error": str(e)}
        return snap


def register_resilience(metrics: Metrics, policy, fault_plan=None) -> None:
    """Surface the resilience subsystem as the ``resilience`` section of
    ``GET /metrics``: per-upstream breaker states (the breaker-state
    gauge), retry/hedge/degraded counters, the effective hedge delay, and
    — on chaos runs — the fault-injection tallies."""

    if policy is None and fault_plan is None:
        return

    def _snapshot() -> dict:
        snap = policy.snapshot() if policy is not None else {}
        if fault_plan is not None:
            snap["fault_plan"] = fault_plan.snapshot()
        return snap

    metrics.register_provider("resilience", _snapshot)


def register_overload(
    metrics: Metrics, admission=None, watchdog=None, lifecycle=None
) -> None:
    """Surface the overload/lifecycle subsystem on ``GET /metrics``:
    the ``admission`` section (inflight gauge, adaptive limit, per-reason
    shed counters), ``device_watchdog`` (health, active dispatches,
    trip/recovery counters), and ``lifecycle`` (state, drain outcome,
    cache flushes).  The batcher's own queue-depth gauge and shed
    counters ride its existing ``device_batcher`` provider."""
    if admission is not None:
        metrics.register_provider("admission", admission.snapshot)
    if watchdog is not None:
        metrics.register_provider("device_watchdog", watchdog.snapshot)
    if lifecycle is not None:
        metrics.register_provider("lifecycle", lifecycle.snapshot)


def _series(request) -> str:
    """Series key = the MATCHED route, so unmatched-path probes can't mint
    unbounded series (they all bucket under ``http:unmatched``)."""
    resource = getattr(request.match_info.route, "resource", None)
    canonical = getattr(resource, "canonical", None)
    return f"http:{canonical}" if canonical else "http:unmatched"


def middleware(metrics: Metrics):
    """aiohttp middleware timing every request by matched route.  Runs
    inside the trace middleware (serve/gateway.py orders it so), hence
    the ambient trace — when one is active — becomes the series'
    exemplar."""
    from aiohttp import web

    from ..obs import current_trace_id

    @web.middleware
    async def _mw(request, handler):
        t0 = time.perf_counter()
        try:
            resp = await handler(request)
        except Exception:
            metrics.observe(
                _series(request),
                (time.perf_counter() - t0) * 1e3,
                error=True,
                trace_id=current_trace_id(),
            )
            raise
        metrics.observe(
            _series(request),
            (time.perf_counter() - t0) * 1e3,
            error=resp.status >= 400,
            trace_id=current_trace_id(),
        )
        return resp

    return _mw
