"""Out-of-band service metrics (SURVEY §5 metrics row: "add ordinary
service metrics (qps, p50, device util) out-of-band").

The reference keeps all observability in-band (per-choice
``completion_metadata`` + usage/cost accounting); that is preserved
bit-exact in the wire types.  This module adds the service-level view the
reference lacks: per-endpoint request counts and latency histograms plus
device dispatch timings, exposed at ``GET /metrics``.

Two expositions off one store (ISSUE 11):

* the original JSON snapshot — shape-compatible with the pre-histogram
  dashboards (``count``/``errors``/``p50_ms``/``p99_ms``/``trace_id``
  per series, provider sections keyed by ``KNOWN_SECTIONS``);
* ``GET /metrics?format=prometheus`` — OpenMetrics text with full
  ``_bucket``/``_sum``/``_count`` histogram families and trace-id
  exemplars on hot series, fed by the same mergeable log-bucket
  histograms (obs/histogram.py) that replaced the old 1024-sample
  reservoir, so percentiles no longer silently describe only the last
  1024 requests.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from ..obs.histogram import Histogram, le_for

# Every provider-section name that may appear in the /metrics snapshot.
# The registry the LWC010 lint checks both ways: a `register_provider`
# call with a name not listed here fails lint (dashboards/tests grep
# these keys, so ad-hoc names silently vanish from alerting), and a
# listed name no call site registers is a stale entry to delete.
KNOWN_SECTIONS = (
    "resilience",
    "admission",
    "device_watchdog",
    "lifecycle",
    "device_batcher",
    "embed_cache",
    "score_cache",
    "traces",
    "jit",
    "mesh",
    "meshfault",
    "phases",
    "roofline",
    "quality",
    "ledger",
    "lock_witness",
    "fleet",
    "memguard",
    "weights",
)

# Every Prometheus family the text exposition may emit.  Same contract
# as KNOWN_SECTIONS, enforced by LWC012 both ways: a `prom_family(...)`
# call with an unlisted name fails lint, and a listed family no call
# site emits is stale.  Counter families are declared WITHOUT the
# `_total` sample suffix (OpenMetrics convention).
KNOWN_PROM_FAMILIES = (
    "lwc_uptime_seconds",
    "lwc_series_requests",
    "lwc_series_errors",
    "lwc_series_latency_ms",
    "lwc_phase_latency_ms",
    "lwc_device_latency_ms",
    "lwc_roofline_sol_ms",
    "lwc_roofline_attainment",
    "lwc_confidence_margin",
    "lwc_consensus_outcomes",
    "lwc_judge_agreement",
    "lwc_judge_drift",
    "lwc_fleet_peer_fetches",
    "lwc_fleet_leases",
    "lwc_fleet_disruptions",
    "lwc_memguard_rss_bytes",
    "lwc_memguard_level",
    "lwc_memguard_trips",
    "lwc_lane_dispatches",
    "lwc_lane_items",
    "lwc_lane_busy_fraction",
    "lwc_weights_swaps",
    "lwc_weights_shadow",
)


class _Series:
    __slots__ = ("count", "errors", "hist", "exemplar")

    def __init__(self) -> None:
        self.count = 0
        self.errors = 0
        self.hist = Histogram()
        # (trace_id, latency_ms, unix_ts) — enough to render an
        # OpenMetrics exemplar on the right bucket line
        self.exemplar: Optional[Tuple[str, float, float]] = None


class Metrics:
    def __init__(self) -> None:
        self._series_store: Dict[str, _Series] = {}
        self._providers: dict = {}
        # monotonic: wall-clock steps (NTP, leap smear) must not skew
        # reported uptime
        self._started = time.monotonic()

    def observe(
        self,
        series: str,
        ms: float,
        *,
        error: bool = False,
        trace_id=None,
    ) -> None:
        s = self._series_store.get(series)
        if s is None:
            s = self._series_store[series] = _Series()
        s.count += 1
        if error:
            s.errors += 1
        s.hist.observe(ms)
        if trace_id is not None:
            # trace-id exemplar: the most recent traced request on this
            # series — an aggregate that looks wrong links straight to
            # one concrete span tree.  Passed EXPLICITLY by call sites
            # that know the right trace (ambient reads here would pick
            # up stale contexts from long-lived tasks like the
            # batcher's flusher).
            s.exemplar = (trace_id, ms, time.time())

    def register_provider(self, name: str, fn) -> None:
        """Attach a live gauge section to the snapshot (e.g. the device
        batcher's queue depth / busy fraction — SURVEY §5 "device util")."""
        self._providers[name] = fn

    def snapshot(self) -> dict:
        out = {}
        for series, s in sorted(self._series_store.items()):
            entry = {"count": s.count, "errors": s.errors}
            if s.hist.count:
                entry["p50_ms"] = round(s.hist.quantile(0.5), 2)
                entry["p99_ms"] = round(s.hist.quantile(0.99), 2)
            if s.exemplar is not None:
                entry["trace_id"] = s.exemplar[0]
            out[series] = entry
        snap = {
            "uptime_sec": round(time.monotonic() - self._started, 1),
            "series": out,
        }
        for name, fn in self._providers.items():
            try:
                snap[name] = fn()
            except Exception as e:  # a broken gauge must not break /metrics
                snap[name] = {"error": str(e)}
        return snap

    # -- prometheus exposition ----------------------------------------------

    def provider_section(self, name: str):
        """One provider section by registry name (None when absent or
        broken) — the Prometheus renderer pulls ``roofline`` this way."""
        fn = self._providers.get(name)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            return None

    def uptime_sec(self) -> float:
        return time.monotonic() - self._started

    def series_items(self) -> List[Tuple[str, "_Series"]]:
        return sorted(self._series_store.items())


PROM_CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def prom_family(name: str, typ: str, help_text: str) -> List[str]:
    """The ``# HELP``/``# TYPE`` header for one family.  Call sites MUST
    pass the family name as a string literal drawn from
    KNOWN_PROM_FAMILIES — the LWC012 lint checks the two both ways so
    the text exposition can't drift from what dashboards scrape."""
    return [f"# HELP {name} {help_text}", f"# TYPE {name} {typ}"]


def _esc(label_value: str) -> str:
    return (
        label_value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _render_hist(
    name: str,
    label: str,
    value: str,
    hist: Histogram,
    exemplar: Optional[Tuple[str, float, float]] = None,
) -> List[str]:
    """One labelled histogram as ``_bucket``/``_sum``/``_count`` lines,
    with the exemplar (when given) attached to the bucket whose range
    contains the exemplar's own latency (OpenMetrics requires the
    exemplar value to lie inside its bucket)."""
    sel = f'{label}="{_esc(value)}"'
    lines = []
    ex_le = le_for(exemplar[1]) if exemplar is not None else None
    for le, cum in hist.cumulative():
        line = f'{name}_bucket{{{sel},le="{le}"}} {cum}'
        if ex_le is not None and le == ex_le:
            trace_id, ms, ts = exemplar
            line += f' # {{trace_id="{_esc(trace_id)}"}} {ms:.6g} {ts:.3f}'
            ex_le = None  # first matching line only
        lines.append(line)
    lines.append(f"{name}_sum{{{sel}}} {hist.sum:.6g}")
    lines.append(f"{name}_count{{{sel}}} {hist.count}")
    return lines


def render_prometheus(metrics: Metrics) -> str:
    """The whole process as OpenMetrics text: uptime, per-series request
    counters + latency histograms (with trace-id exemplars), the phase
    and per-bucket device-time histograms from the global phase
    aggregator, and the roofline attainment gauges when the roofline
    section is registered.  Ends with the mandatory ``# EOF``."""
    from ..obs import phases as _phases

    lines: List[str] = []
    lines += prom_family("lwc_uptime_seconds", "gauge", "Process uptime (monotonic).")
    lines.append(f"lwc_uptime_seconds {metrics.uptime_sec():.3f}")

    items = metrics.series_items()
    lines += prom_family(
        "lwc_series_requests", "counter", "Requests observed per series."
    )
    for series, s in items:
        lines.append(f'lwc_series_requests_total{{series="{_esc(series)}"}} {s.count}')
    lines += prom_family(
        "lwc_series_errors", "counter", "Errored requests per series."
    )
    for series, s in items:
        lines.append(f'lwc_series_errors_total{{series="{_esc(series)}"}} {s.errors}')
    lines += prom_family(
        "lwc_series_latency_ms",
        "histogram",
        "Per-series latency, fixed log buckets (obs/histogram.py).",
    )
    for series, s in items:
        lines += _render_hist(
            "lwc_series_latency_ms", "series", series, s.hist, s.exemplar
        )

    phase_hists, device_hists = _phases.aggregator().raw_histograms()
    lines += prom_family(
        "lwc_phase_latency_ms",
        "histogram",
        "Request time attributed per phase (admission_wait .. upstream_judge).",
    )
    for phase in _phases.PHASES:
        hist = phase_hists.get(phase)
        if hist is not None:
            lines += _render_hist("lwc_phase_latency_ms", "phase", phase, hist)
    lines += prom_family(
        "lwc_device_latency_ms",
        "histogram",
        "Enqueue-to-ready device time per (mesh-shape, bucket).",
    )
    for bucket, hist in sorted(device_hists.items()):
        lines += _render_hist("lwc_device_latency_ms", "bucket", bucket, hist)

    roofline = metrics.provider_section("roofline")
    if isinstance(roofline, dict):
        rows = roofline.get("buckets", {})
        lines += prom_family(
            "lwc_roofline_sol_ms",
            "gauge",
            "Speed-of-light time per AOT bucket from analysis/roofline.json.",
        )
        for bucket, row in sorted(rows.items()):
            sol = row.get("sol_ms")
            if sol is not None:
                lines.append(
                    f'lwc_roofline_sol_ms{{bucket="{_esc(bucket)}"}} {sol:.6g}'
                )
        lines += prom_family(
            "lwc_roofline_attainment",
            "gauge",
            "sol_ms / measured device p50 per AOT bucket (1.0 = roofline).",
        )
        for bucket, row in sorted(rows.items()):
            att = row.get("attainment")
            if att is not None:
                lines.append(
                    f'lwc_roofline_attainment{{bucket="{_esc(bucket)}"}} {att:.6g}'
                )

    from ..obs import quality as _quality

    qsnap = _quality.quality_aggregator().prom_snapshot()
    lines += prom_family(
        "lwc_confidence_margin",
        "histogram",
        "Consensus confidence margin (top1 - top2) per scored request.",
    )
    lines += _render_hist(
        "lwc_confidence_margin",
        "kind",
        "margin",
        qsnap["margin"],
        qsnap["exemplar"],
    )
    lines += prom_family(
        "lwc_consensus_outcomes",
        "counter",
        "Scored requests by consensus outcome (scored/degraded/...).",
    )
    for outcome, count in qsnap["outcomes"].items():
        lines.append(
            f'lwc_consensus_outcomes_total{{outcome="{_esc(outcome)}"}} {count}'
        )
    lines += prom_family(
        "lwc_judge_agreement",
        "gauge",
        "Per-judge agreement-with-final-consensus rate.",
    )
    for judge, rate in qsnap["agreement"].items():
        lines.append(
            f'lwc_judge_agreement{{judge="{_esc(judge)}"}} {rate:.6g}'
        )
    lines += prom_family(
        "lwc_judge_drift",
        "gauge",
        "1 when the drift detector currently flags the judge, else 0.",
    )
    for judge, flagged in qsnap["drift_flagged"].items():
        lines.append(
            f'lwc_judge_drift{{judge="{_esc(judge)}"}} {flagged:.0f}'
        )

    fleet = metrics.provider_section("fleet")
    if isinstance(fleet, dict):
        fetch = fleet.get("peer_fetch", {})
        lines += prom_family(
            "lwc_fleet_peer_fetches",
            "counter",
            "Peer cache fetches by result (hit/miss/error).",
        )
        for result in ("hits", "misses", "errors"):
            lines.append(
                f'lwc_fleet_peer_fetches_total{{result="{result}"}} '
                f"{fetch.get(result, 0)}"
            )
        leases = fleet.get("leases", {})
        lines += prom_family(
            "lwc_fleet_leases",
            "gauge",
            "Cross-replica single-flight leases active on this owner.",
        )
        lines.append(f"lwc_fleet_leases {leases.get('active', 0)}")
        health = fleet.get("health", {})
        lines += prom_family(
            "lwc_fleet_disruptions",
            "counter",
            "Fleet failure-plane events by kind (partition tolerance).",
        )
        for kind, value in (
            ("ring_divergence", fleet.get("ring_divergences", 0)),
            ("ring_reject", fleet.get("ring_rejects", 0)),
            ("early_takeover", fleet.get("early_takeovers", 0)),
            (
                "late_publish",
                leases.get("late_publishes", 0),
            ),
            ("quarantine", health.get("quarantines", 0)),
            ("readmission", health.get("readmissions", 0)),
        ):
            lines.append(
                f'lwc_fleet_disruptions_total{{kind="{kind}"}} {value}'
            )

    memguard = metrics.provider_section("memguard")
    if isinstance(memguard, dict):
        lines += prom_family(
            "lwc_memguard_rss_bytes",
            "gauge",
            "Process RSS as last sampled by the memory governor.",
        )
        if "rss_bytes" in memguard:
            lines.append(f"lwc_memguard_rss_bytes {memguard['rss_bytes']}")
        lines += prom_family(
            "lwc_memguard_level",
            "gauge",
            "Memory pressure level (0 ok, 1 soft, 2 hard).",
        )
        level_num = {"ok": 0, "soft": 1, "hard": 2}.get(
            memguard.get("level"), 0
        )
        lines.append(f"lwc_memguard_level {level_num}")
        lines += prom_family(
            "lwc_memguard_trips",
            "counter",
            "Watermark crossings by kind (soft/hard/recovery).",
        )
        for kind, key in (
            ("soft", "soft_trips"),
            ("hard", "hard_trips"),
            ("recovery", "recoveries"),
        ):
            lines.append(
                f'lwc_memguard_trips_total{{kind="{kind}"}} '
                f"{memguard.get(key, 0)}"
            )

    batcher = metrics.provider_section("device_batcher")
    if isinstance(batcher, dict) and isinstance(batcher.get("lanes"), dict):
        lanes = sorted(batcher["lanes"].items())
        lines += prom_family(
            "lwc_lane_dispatches",
            "counter",
            "Device dispatches per priority class (latency/offline).",
        )
        for lane, row in lanes:
            lines.append(
                f'lwc_lane_dispatches_total{{lane="{_esc(lane)}"}} '
                f"{row.get('dispatches', 0)}"
            )
        lines += prom_family(
            "lwc_lane_items",
            "counter",
            "Items dispatched per priority class.",
        )
        for lane, row in lanes:
            lines.append(
                f'lwc_lane_items_total{{lane="{_esc(lane)}"}} '
                f"{row.get('items', 0)}"
            )
        lines += prom_family(
            "lwc_lane_busy_fraction",
            "gauge",
            "Device busy fraction attributed per priority class.",
        )
        for lane, row in lanes:
            lines.append(
                f'lwc_lane_busy_fraction{{lane="{_esc(lane)}"}} '
                f"{row.get('busy_fraction', 0.0):.6g}"
            )

    weights = metrics.provider_section("weights")
    if isinstance(weights, dict):
        lines += prom_family(
            "lwc_weights_swaps",
            "counter",
            "Live weight-table installs (active + shadow).",
        )
        lines.append(f"lwc_weights_swaps_total {weights.get('swaps', 0)}")
        lines += prom_family(
            "lwc_weights_shadow",
            "counter",
            "Shadow-table comparisons by kind (compared/would_flip).",
        )
        for kind, key in (
            ("compared", "shadow_compared"),
            ("would_flip", "shadow_would_flip"),
        ):
            lines.append(
                f'lwc_weights_shadow_total{{kind="{kind}"}} '
                f"{weights.get(key, 0)}"
            )

    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def register_resilience(metrics: Metrics, policy, fault_plan=None) -> None:
    """Surface the resilience subsystem as the ``resilience`` section of
    ``GET /metrics``: per-upstream breaker states (the breaker-state
    gauge), retry/hedge/degraded counters, the effective hedge delay, and
    — on chaos runs — the fault-injection tallies."""

    if policy is None and fault_plan is None:
        return

    def _snapshot() -> dict:
        snap = policy.snapshot() if policy is not None else {}
        if fault_plan is not None:
            snap["fault_plan"] = fault_plan.snapshot()
        return snap

    metrics.register_provider("resilience", _snapshot)


def register_overload(
    metrics: Metrics,
    admission=None,
    watchdog=None,
    lifecycle=None,
    memguard=None,
) -> None:
    """Surface the overload/lifecycle subsystem on ``GET /metrics``:
    the ``admission`` section (inflight gauge, adaptive limit, per-reason
    shed counters), ``device_watchdog`` (health, active dispatches,
    trip/recovery counters), ``lifecycle`` (state, drain outcome,
    cache flushes), and ``memguard`` (RSS, pressure level, watermark
    trip counters).  The batcher's own queue-depth gauge and shed
    counters ride its existing ``device_batcher`` provider."""
    if admission is not None:
        metrics.register_provider("admission", admission.snapshot)
    if watchdog is not None:
        metrics.register_provider("device_watchdog", watchdog.snapshot)
    if lifecycle is not None:
        metrics.register_provider("lifecycle", lifecycle.snapshot)
    if memguard is not None:
        metrics.register_provider("memguard", memguard.snapshot)


def register_performance(metrics: Metrics, roofline=None) -> None:
    """Surface the ISSUE 11 performance-observability sections: the
    ``phases`` aggregate (per-phase histograms + device-time share) and,
    when a gauge is supplied, the ``roofline`` per-bucket attainment
    table."""
    from ..obs import phases as _phases

    metrics.register_provider("phases", _phases.phases_snapshot)
    if roofline is not None:
        metrics.register_provider("roofline", roofline.snapshot)


def register_quality(metrics: Metrics, ledger=None, live_weights=None) -> None:
    """Surface the ISSUE 12 consensus-quality sections: the ``quality``
    aggregate (per-judge scorecards, pairwise kappa, drift flags,
    margin histogram, outcome rates), plus — when configured — the
    outcome ledger's ``ledger`` retention counters and the live
    weight-table's ``weights`` section (active/shadow versions, swap
    and shadow-comparison counters)."""
    from ..obs import quality as _quality

    metrics.register_provider("quality", _quality.quality_snapshot)
    if ledger is not None:
        metrics.register_provider("ledger", ledger.snapshot)
    if live_weights is not None:
        metrics.register_provider("weights", live_weights.snapshot)


def _series(request) -> str:
    """Series key = the MATCHED route, so unmatched-path probes can't mint
    unbounded series (they all bucket under ``http:unmatched``)."""
    resource = getattr(request.match_info.route, "resource", None)
    canonical = getattr(resource, "canonical", None)
    return f"http:{canonical}" if canonical else "http:unmatched"


def middleware(metrics: Metrics):
    """aiohttp middleware timing every request by matched route.  Runs
    inside the trace middleware (serve/gateway.py orders it so), hence
    the ambient trace — when one is active — becomes the series'
    exemplar."""
    from aiohttp import web

    from ..obs import current_trace_id

    @web.middleware
    async def _mw(request, handler):
        t0 = time.perf_counter()
        try:
            resp = await handler(request)
        except Exception:
            metrics.observe(
                _series(request),
                (time.perf_counter() - t0) * 1e3,
                error=True,
                trace_id=current_trace_id(),
            )
            raise
        metrics.observe(
            _series(request),
            (time.perf_counter() - t0) * 1e3,
            error=resp.status >= 400,
            trace_id=current_trace_id(),
        )
        return resp

    return _mw
