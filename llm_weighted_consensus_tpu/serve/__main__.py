"""Service entry point: ``python -m llm_weighted_consensus_tpu.serve``.

Wires env config into the client stack (main.rs wiring parity: default
clients + unimplemented fetchers unless stores are configured) and serves.
``--fake-upstream`` starts a loopback scripted provider and points the
chat client at it — the zero-key local demo / verification mode.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random

from aiohttp import web

from .. import archive, registry
from ..clients.chat import AiohttpTransport, ApiBase, DefaultChatClient
from ..clients.multichat import MultichatClient
from ..clients.score import ScoreClient
from ..weights import WeightFetchers
from .config import Config, enable_compile_cache, load_dotenv
from .gateway import LIFECYCLE_KEY, _parse_error_response, build_app

FAKE_PORT = 5990

# the service's archive store, exposed for introspection/tests
ARCHIVE_KEY: web.AppKey = web.AppKey("archive", object)
# the live judge training tables (when an embedder is configured)
TABLES_KEY: web.AppKey = web.AppKey("tables", object)


def _rescore_handler(store, lock, mesh=None):
    """POST /archive/rescore: re-tally archived score completions on device
    (BASELINE config 4 as a service operation), dp-sharded when the
    service has a mesh.

    Body (all optional): {"weight_overrides": {judge id: weight},
    "ids": [completion ids], "revote": bool (re-extract soft votes from
    stored logprobs), "apply": bool (write results back into the archive),
    "include_results": bool}.

    Locking: the device compute runs on an executor WITHOUT the lock —
    it reads only fields no other writer touches (judge votes/weights;
    ``apply`` writes candidate fields, ``learn`` writes tables), so a 10k
    re-score doesn't block archiving writes.  ``apply`` then runs ON THE
    EVENT LOOP under the lock: sync code on the loop is atomic w.r.t.
    every request handler, so no reader can observe a half-applied
    completion (weight updated, confidence not).
    """
    from ..archive.rescore import apply_rescore, rescore_archive
    from ..utils import jsonutil

    def bad_request(message):
        return web.Response(
            status=400,
            text=jsonutil.dumps({"code": 400, "message": message}),
            content_type="application/json",
        )

    async def handler(request: web.Request):
        try:
            body = jsonutil.loads(await request.text() or "{}")
            if not isinstance(body, dict):
                return bad_request("body must be a JSON object")
            oraw = body.get("weight_overrides") or {}
            if not isinstance(oraw, dict):
                raise ValueError(
                    "`weight_overrides` must map judge ids to numbers"
                )
            from decimal import Decimal as _Decimal

            overrides = {}
            for judge, w in oraw.items():
                if isinstance(w, bool) or not isinstance(
                    w, (int, float, _Decimal)
                ):
                    raise ValueError(
                        f"`weight_overrides[{judge!r}]` must be a number"
                    )
                overrides[str(judge)] = float(w)
            ids = body.get("ids")
            revote = bool(body.get("revote", False))
            apply = bool(body.get("apply", False))
            include = bool(body.get("include_results", False))
        except web.HTTPException:
            raise  # e.g. 413 body-too-large must keep its status
        except Exception as e:  # parse phase: malformed input, not a fault
            return _parse_error_response(e)
        # validation beyond parsing stays OUTSIDE the blanket except: a
        # store fault must surface as a 500, not masquerade as a 400
        if ids is not None:
            if not isinstance(ids, list):
                return bad_request("`ids` must be a list")
            unknown = [
                cid for cid in ids if store.score_completion(cid) is None
            ]
            if unknown:
                return bad_request(
                    f"unknown score completion ids: {unknown[:5]}"
                )

        def run():
            return rescore_archive(
                store,
                mesh=mesh,
                weight_overrides=overrides or None,
                ids=ids,
                revote=revote,
            )

        results = await asyncio.get_running_loop().run_in_executor(None, run)
        applied = 0
        if apply:
            # on-loop + locked: atomic for readers, serialized vs learn
            async with lock:
                applied = apply_rescore(store, results)
        out = {"rescored": len(results), "applied": applied}
        if include:
            out["results"] = results
        return web.Response(
            text=jsonutil.dumps(out), content_type="application/json"
        )

    return handler


def _learn_handler(store, embedder, tables, lock):
    """POST /weights/learn: build training-table rows from the archive.

    Body: {"model": <inline panel JSON>, "labels": {completion_id: correct
    candidate index}?, "ids": [completion ids]?}.  Runs on an executor (it
    embeds prompts on device) and returns {"rows_added": N}.  Idempotent —
    already-ingested completions are skipped.  The shared lock serializes
    learn passes against each other (both would pass the is_ingested check
    before either marks) and against archive mutations (rescore apply).
    """
    from ..identity.model import ModelBase
    from ..utils import jsonutil
    from ..weights.learning import populate_from_archive

    async def handler(request: web.Request):
        try:
            body = jsonutil.loads(await request.text())
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            if "model" not in body:
                raise ValueError("missing required field `model`")
            model = ModelBase.from_json_obj(
                body["model"]
            ).into_model_validate()
            lraw = body.get("labels") or {}
            if not isinstance(lraw, dict):
                raise ValueError(
                    "`labels` must map completion ids to candidate indexes"
                )
            labels = {}
            for cid, idx in lraw.items():
                if isinstance(idx, bool) or not isinstance(idx, int):
                    raise ValueError(
                        f"`labels[{cid!r}]` must be an integer index"
                    )
                labels[str(cid)] = int(idx)
            ids = body.get("ids")
        except web.HTTPException:
            raise  # e.g. 413 body-too-large must keep its status
        except Exception as e:  # parse phase: malformed input, not a fault
            return _parse_error_response(e)
        async with lock:
            added = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: populate_from_archive(
                    store, embedder, model, tables, ids=ids, labels=labels
                ),
            )
        return web.json_response({"rows_added": added})

    return handler


async def _fake_upstream(request: web.Request) -> web.StreamResponse:
    """A scripted judge provider: finds the ballot in the system prompt and
    votes for a random key; plain chat otherwise.

    ``FAKE_UPSTREAM_DELAY_MS`` (process env, read per request) adds a
    judge-latency sleep before the first frame, so load/drain scenarios
    (bench_http.py --overload, the chaos SIGTERM drill) exercise requests
    that HOLD their admission slot for a realistic interval instead of
    completing in microseconds."""
    import os

    delay_ms = float(os.environ.get("FAKE_UPSTREAM_DELAY_MS", "0") or 0.0)
    if delay_ms > 0:
        await asyncio.sleep(delay_ms / 1e3)
    body = await request.json()
    content = "This is a fake upstream completion."
    for message in reversed(body.get("messages", [])):
        if message.get("role") == "system" and "Select the response:" in str(
            message.get("content", "")
        ):
            text = message["content"]
            ballot = json.loads(
                text.split("Select the response:\n\n", 1)[1].split(
                    "\n\nOutput", 1
                )[0]
            )
            content = f"I select {random.choice(list(ballot))}"
            break
    resp = web.StreamResponse(
        headers={"content-type": "text/event-stream"}
    )
    await resp.prepare(request)
    for i, frag in enumerate((content[: len(content) // 2], content[len(content) // 2 :])):
        chunk = {
            "id": "fake-1",
            "object": "chat.completion.chunk",
            "created": 0,
            "model": body.get("model", "fake"),
            "choices": [
                {
                    "index": 0,
                    "delta": (
                        {"role": "assistant", "content": frag}
                        if i == 0
                        else {"content": frag}
                    ),
                    "finish_reason": None,
                }
            ],
        }
        await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
    final = {
        "id": "fake-1",
        "object": "chat.completion.chunk",
        "created": 0,
        "model": body.get("model", "fake"),
        "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
        "usage": {"prompt_tokens": 10, "completion_tokens": 10, "total_tokens": 20},
    }
    await resp.write(f"data: {json.dumps(final)}\n\ndata: [DONE]\n\n".encode())
    return resp


def _synthetic_params_allowed(allow_synthetic: bool) -> bool:
    import os

    from ..utils import env_truthy

    return allow_synthetic or env_truthy(
        os.environ.get("LWC_ALLOW_RANDOM_PARAMS", "")
    )


def build_embedder(config: Config, allow_synthetic: bool = False):
    """The service's device side: an embedder from env config.  With
    MESH_ENABLED it serves in first-class mesh mode — params placed once
    by the partition-rule tables, batches sharded over dp, encoder params
    Megatron-split over tp, per-(mesh-shape, bucket) AOT executables
    (parallel/sharding.py shard_embedder_mesh); the legacy MESH_DP /
    MESH_TP knobs keep the older put_batch hook path.

    Serving synthetic state — random-init weights (no EMBEDDER_WEIGHTS) or
    the hash tokenizer (no real vocab) — produces embeddings that LOOK
    valid but are garbage; it is refused unless explicitly opted into via
    ``allow_synthetic`` (set for --fake-upstream demo mode) or
    ``LWC_ALLOW_RANDOM_PARAMS=1``, and logged loudly even then."""
    if config.compile_cache_dir:
        enable_compile_cache(config.compile_cache_dir)
    if not config.embedder_model:
        return None
    from ..models.configs import PRESETS
    from ..models.embedder import TpuEmbedder
    from ..models.spm import scheme_for_model
    from ..models.tokenizer import load_tokenizer

    if config.embedder_model not in PRESETS:
        raise ValueError(
            f"EMBEDDER_MODEL={config.embedder_model!r} is not a known "
            f"preset; valid values: {', '.join(sorted(PRESETS))}"
        )

    params = None
    vocab_path = config.embedder_vocab
    if config.embedder_weights:
        from ..models.loading import find_vocab, load_params

        params = load_params(
            config.embedder_weights, PRESETS[config.embedder_model]
        )
        if not vocab_path:
            vocab_path = find_vocab(config.embedder_weights)
    max_tokens = config.embedder_max_tokens
    if max_tokens is None:
        # MESH_SP exists to serve long inputs — defaulting to 512 would
        # silently truncate exactly the documents it's configured for
        from ..models.configs import usable_positions

        max_tokens = (
            usable_positions(PRESETS[config.embedder_model])
            if config.mesh_sp is not None
            else 512
        )
    embedder = TpuEmbedder(
        config.embedder_model,
        params=params,
        # only override the tokenizer when a real vocab is available;
        # TpuEmbedder's default hash fallback sizes to the model vocab.
        # scheme matters only for spm protos (bge-m3 -> xlmr convention)
        tokenizer=(
            load_tokenizer(
                vocab_path,
                scheme=scheme_for_model(config.embedder_model),
            )
            if vocab_path
            else None
        ),
        max_tokens=max_tokens,
        quantize=config.embedder_quantize,
    )
    from ..models.tokenizer import HashTokenizer

    synthetic = []
    if params is None:
        synthetic.append("random-init weights (no EMBEDDER_WEIGHTS)")
    if isinstance(embedder.tokenizer, HashTokenizer):
        synthetic.append(
            "hash tokenizer (no EMBEDDER_VOCAB and no vocab/spm file "
            "beside EMBEDDER_WEIGHTS)"
        )
    if synthetic:
        detail = (
            f"EMBEDDER_MODEL={config.embedder_model} would serve "
            + " and ".join(synthetic)
            + " — embeddings and trained-weight lookups would be garbage "
            "that looks valid."
        )
        if not _synthetic_params_allowed(allow_synthetic):
            raise ValueError(
                detail
                + " Point EMBEDDER_WEIGHTS at a checkpoint, or opt into "
                "synthetic params explicitly with LWC_ALLOW_RANDOM_PARAMS=1 "
                "(tests/demo only)."
            )
        import logging

        logging.getLogger("lwc.serve").warning(
            "SYNTHETIC EMBEDDER PARAMS: %s Serving anyway "
            "(LWC_ALLOW_RANDOM_PARAMS / fake-upstream demo mode).",
            detail,
        )
    if config.mesh_enabled:
        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.sharding import shard_embedder_mesh

        # host-local mesh, same rationale as the legacy branch below;
        # MESH_SHAPE unset = every local device on dp (tp=1)
        shape = config.mesh_shape
        mesh = make_mesh(
            dp=shape[0] if shape else None,
            tp=shape[1] if shape else 1,
            sp=shape[2] if shape and len(shape) > 2 else 1,
            devices=jax.local_devices(),
        )
        shard_embedder_mesh(embedder, mesh)
    elif config.mesh_sp is not None:
        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.ring import shard_embedder_sp

        if config.mesh_tp > 1:
            raise ValueError(
                "MESH_SP and MESH_TP are mutually exclusive (sequence "
                "parallelism replicates encoder params)"
            )
        # MESH_DP unset = auto-fill (every device not consumed by sp),
        # matching the documented dp/tp semantics
        mesh = make_mesh(
            dp=config.mesh_dp,
            tp=config.mesh_sp,
            devices=jax.local_devices(),
            names=("dp", "sp"),
        )
        dp = mesh.shape["dp"]
        shard_embedder_sp(
            embedder, mesh, dp_axis="dp" if dp > 1 else None
        )
    elif config.mesh_dp is not None or config.mesh_tp > 1:
        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.sharding import shard_embedder

        # the serving mesh is HOST-LOCAL: a request lands on one host and
        # must be executable without the other hosts' cooperation (they
        # serve their own traffic).  Single-host: local == global.  See
        # DESIGN.md §multi-host.
        mesh = make_mesh(
            dp=config.mesh_dp,
            tp=config.mesh_tp,
            devices=jax.local_devices(),
        )
        shard_embedder(embedder, mesh, tp=config.mesh_tp > 1)
    return embedder


def build_reranker(config: Config, allow_synthetic: bool = False):
    """The RM-scoring device side (POST /consensus {"scorer": "rm"}):
    a DeBERTa reward model from env config.  Same synthetic-params
    discipline as ``build_embedder``."""
    if not config.rm_model:
        return None
    from ..models.reranker import RM_PRESETS, TpuReranker, load_rm_params
    from ..models.spm import scheme_for_model
    from ..models.tokenizer import HashTokenizer, load_tokenizer

    if config.rm_model not in RM_PRESETS:
        raise ValueError(
            f"RM_MODEL={config.rm_model!r} is not a known preset; "
            f"valid values: {', '.join(sorted(RM_PRESETS))}"
        )
    params = None
    head_loaded = False
    vocab_path = config.rm_vocab
    if config.rm_weights:
        from ..models.loading import find_vocab

        params, head_loaded = load_rm_params(
            config.rm_weights, RM_PRESETS[config.rm_model]
        )
        if not vocab_path:
            vocab_path = find_vocab(config.rm_weights)
    reranker = TpuReranker(
        config.rm_model,
        params=params,
        tokenizer=(
            load_tokenizer(
                vocab_path, scheme=scheme_for_model(config.rm_model)
            )
            if vocab_path
            else None
        ),
        max_tokens=config.rm_max_tokens,
        quantize=config.rm_quantize,
    )
    synthetic = []
    if params is None:
        synthetic.append("random-init RM weights (no RM_WEIGHTS)")
    elif not head_loaded:
        synthetic.append(
            "a RANDOM-INIT reward head (encoder-only checkpoint — no "
            "pooler/classifier weights in RM_WEIGHTS)"
        )
    if isinstance(reranker.tokenizer, HashTokenizer):
        synthetic.append(
            "hash tokenizer (no RM_VOCAB and no vocab/spm file beside "
            "RM_WEIGHTS)"
        )
    if synthetic:
        detail = (
            f"RM_MODEL={config.rm_model} would serve "
            + " and ".join(synthetic)
            + " — reward re-ranking would be garbage that looks valid."
        )
        if not _synthetic_params_allowed(allow_synthetic):
            raise ValueError(
                detail
                + " Point RM_WEIGHTS at a checkpoint, or opt in with "
                "LWC_ALLOW_RANDOM_PARAMS=1 (tests/demo only)."
            )
        import logging

        logging.getLogger("lwc.serve").warning(
            "SYNTHETIC RM PARAMS: %s Serving anyway "
            "(LWC_ALLOW_RANDOM_PARAMS / fake-upstream demo mode).",
            detail,
        )
    if config.mesh_enabled:
        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.sharding import shard_reranker_mesh

        shape = config.mesh_shape
        mesh = make_mesh(
            dp=shape[0] if shape else None,
            tp=shape[1] if shape else 1,
            devices=jax.local_devices(),
        )
        shard_reranker_mesh(reranker, mesh)
    return reranker


class _ArchivingClient:
    """Wraps a client so every served UNARY completion is archived (its id
    becomes referenceable by later requests); everything else delegates.
    ``put(result, params)`` receives the request too — the score path
    archives it beside the completion, feeding training-table learning
    (weights/learning.py).

    Streaming: by default streamed responses are consumed by the HTTP
    caller chunk-by-chunk and are NOT archived (the reference archives
    nothing, so parity holds; only unary callers feed rescore/learning).
    With ``stream_fold`` set (ARCHIVE_STREAMING=1), the chunk stream is
    teed into the merge algebra — each chunk ``push``ed into a running
    aggregate, the folded unary archived at clean stream end (``unary =
    fold(chunks)``, the types/base.py contract, mirroring how unary is
    *defined* in the reference, chat client.rs:170-191).  A stream the
    client abandons mid-way archives nothing: a partial fold would be
    indistinguishable from a complete completion."""

    def __init__(self, inner, put, stream_fold=None):
        self._inner = inner
        self._put = put
        self._stream_fold = stream_fold

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def create_unary(self, ctx, params):
        result = await self._inner.create_unary(ctx, params)
        self._put(result, params)
        return result

    async def create_streaming(self, ctx, params):
        stream = await self._inner.create_streaming(ctx, params)
        if self._stream_fold is None:
            return stream
        return self._tee(stream, params)

    async def _tee(self, stream, params):
        aggregate = None
        foldable = True
        completed = False
        try:
            async for chunk in stream:
                # the fold is a side-channel and must NEVER break the
                # client-facing stream: error items (e.g. ChatError
                # frames the chat stream yields mid-stream) and any
                # clone/push failure poison the fold — nothing gets
                # archived — while every chunk still reaches the client.
                # Error isolation is identical with and without the tee.
                if foldable:
                    try:
                        if isinstance(chunk, Exception):
                            foldable = False
                        elif aggregate is None:
                            aggregate = chunk.clone()
                        else:
                            aggregate.push(chunk)
                    except Exception:
                        foldable = False
                        aggregate = None
                yield chunk
            completed = True
        finally:
            # propagate close (client disconnects surface as
            # GeneratorExit here) so the upstream connection is released
            # promptly — same contract as gateway._respond_streaming
            aclose = getattr(stream, "aclose", None)
            if aclose is not None:
                await aclose()
        if completed and foldable and aggregate is not None:
            try:
                self._put(self._stream_fold(aggregate), params)
            except Exception:
                import logging

                logging.getLogger("lwc.serve").warning(
                    "streamed completion could not be archived "
                    "(fold/store failure); the response was served intact",
                    exc_info=True,
                )


def _warmup_embedder(
    embedder,
    specs: list,
    r_buckets: list = (),
    aot: bool = True,
    packed_buckets: list = (),
    ring_buckets: list = (),
) -> None:
    """Pre-compile the consensus path for the given ``NxS`` shapes at
    startup (WARMUP env, serve/config.py) so the first real request
    doesn't pay a multi-second jit compile.  Each spec warms the
    single-request dispatch at exactly that (candidate count, seq
    bucket); invalid specs fail startup loudly (a silently skipped
    warmup defeats its purpose).  S snaps to the serving seq bucket the
    tokenizer would pick, so the compiled shape is the one traffic
    actually hits.

    ``r_buckets`` (WARMUP_R) additionally warms the batcher's grouped
    dispatch (``consensus_confidence_tokens_many``) at each concurrency
    bucket per shape — a distinct XLA specialization per power-of-two R,
    which the single-request warm does NOT cover (ADVICE r4): without it
    the first concurrent burst at a warmed NxS still pays the compile.

    ``aot`` (WARMUP_AOT, default on) compiles each bucket ahead-of-time
    (``TpuEmbedder.aot_warmup``: ``.lower().compile()``, no device
    dispatch) and serves warmed buckets from the embedder's executable
    table — zero jit specializations after startup.  First-class mesh
    embedders (MESH_ENABLED) take the AOT branch too: their buckets
    lower with sharded avals into per-(mesh-shape, bucket) executables.
    Only the legacy hook-sharded embedders (MESH_DP/MESH_TP/MESH_SP)
    fall back to the dispatch loop below (the plain-aval AOT lowering
    doesn't carry their input shardings).

    ``packed_buckets`` ((B, L, K) triples, wired from the PACKING_*
    knobs) additionally warms the continuous-batching entry
    (``bert.embed_packed``) at each packed-capacity bucket — the small
    fixed set replacing the (R, N, S) lattice on the packed path.  AOT
    only: packing requires the single-device or mesh-mode embedder.

    ``ring_buckets`` (LONG_CONTEXT_WARMUP NxS specs) warms the
    sequence-parallel ring dispatch on an sp-bearing mesh — AOT only,
    and a no-op unless the embedder's mesh carries an sp axis."""
    import logging
    import time as _time

    import numpy as np

    from ..models.embedder import _seq_bucket

    log = logging.getLogger("lwc.serve")
    # dedup AFTER bucket snapping: 64x100 and 64x112 are the same
    # compiled shape, and a second dispatch of it is pure wasted startup
    snapped = list(
        dict.fromkeys(
            (n, _seq_bucket(s, embedder.max_tokens)) for n, s in specs
        )
    )
    if aot and embedder._aot_ready():
        for label, dt in embedder.aot_warmup(
            snapped,
            r_buckets,
            packed_buckets=packed_buckets,
            ring_buckets=ring_buckets,
        ):
            log.info("warmup AOT %s compiled in %.1fs", label, dt)
        return
    for n, s in snapped:
        ids = np.zeros((n, s), dtype=np.int32)
        mask = np.zeros((n, s), dtype=np.int32)
        mask[:, 0] = 1  # one real token per row: a clean forward
        t0 = _time.perf_counter()
        np.asarray(embedder.consensus_confidence_tokens(ids, mask))
        log.info(
            "warmup %dx%d compiled in %.1fs",
            n, s, _time.perf_counter() - t0,
        )
        for r in r_buckets:
            if r < 2:
                continue  # R=1 groups dispatch the single-request path
            ids_r = np.zeros((r, n, s), dtype=np.int32)
            mask_r = np.zeros((r, n, s), dtype=np.int32)
            mask_r[:, :, 0] = 1
            t0 = _time.perf_counter()
            np.asarray(
                embedder.consensus_confidence_tokens_many(ids_r, mask_r)
            )
            log.info(
                "warmup grouped R=%d %dx%d compiled in %.1fs",
                r, n, s, _time.perf_counter() - t0,
            )


def _build_cpu_fallback(config: Config, fake_upstream: bool):
    """(embedder, device-context factory) for DEVICE_WATCHDOG_CPU_FALLBACK:
    a CPU twin of the serving embedder, built at startup (weights reload
    from the same checkpoint) while the device is still healthy.  Mesh
    flags and int8 quantization are stripped — the fallback's whole job
    is to exist off the wedged device, not to be fast — and every
    dispatch through it runs under ``jax.default_device(cpu)`` so its
    computations never queue behind the hung dispatch.  Failure to build
    one degrades to watchdog-without-fallback (device endpoints shed
    while unhealthy) rather than failing startup."""
    import dataclasses
    import logging

    log = logging.getLogger("lwc.serve")
    try:
        import jax

        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            fallback = build_embedder(
                dataclasses.replace(
                    config,
                    mesh_dp=None,
                    mesh_tp=1,
                    mesh_sp=None,
                    mesh_enabled=False,
                    mesh_shape=None,
                    embedder_quantize="none",
                ),
                allow_synthetic=fake_upstream,
            )
    except Exception:
        log.warning(
            "DEVICE_WATCHDOG_CPU_FALLBACK: could not build the CPU "
            "fallback embedder; device endpoints will shed while the "
            "watchdog holds the device unhealthy",
            exc_info=True,
        )
        return None, None

    def fallback_context():
        import jax

        return jax.default_device(jax.devices("cpu")[0])

    log.info(
        "device watchdog CPU fallback ready (%s)", config.embedder_model
    )
    return fallback, fallback_context


def build_service(
    config: Config,
    fake_upstream: bool = False,
    fake_upstream_port: int = FAKE_PORT,
):
    import os

    api_bases = config.api_bases()
    if fake_upstream:
        api_bases = [
            ApiBase(f"http://127.0.0.1:{fake_upstream_port}/v1", "fake-key")
        ]
    if config.archive_path and os.path.exists(config.archive_path):
        store = archive.InMemoryArchive.load(config.archive_path)
    else:
        store = archive.InMemoryArchive()
    # bound service memory growth (ARCHIVE_MAX_COMPLETIONS; 0 = unbounded)
    store.max_completions = config.archive_max_completions or None
    store.enforce_cap()  # an over-cap loaded snapshot trims at startup
    if config.archive_path:
        # fail FAST on an unwritable path: the shutdown save is the last
        # moment we could find out, and by then the archive would be lost.
        # A tiny probe, not a full save — re-serializing a just-loaded
        # multi-GB snapshot would double startup IO for nothing.
        from ..utils.io import probe_writable_config

        probe_writable_config(
            config.archive_path,
            "ARCHIVE_PATH",
            "snapshots would be lost at shutdown",
        )
        if not os.path.exists(config.archive_path):
            store.save(config.archive_path)
    transport = AiohttpTransport(
        connect_timeout_ms=config.connect_timeout_millis
    )
    # FAULT_PLAN (chaos runs): wrap the real transport in the seeded
    # fault injector; the wrapper's close() closes the inner session
    fault_plan = config.fault_injection_plan()
    if fault_plan is not None:
        from ..resilience import FaultInjectionTransport

        transport = FaultInjectionTransport(transport, fault_plan)
    resilience = config.resilience_policy()
    chat_client = DefaultChatClient(
        transport,
        api_bases,
        backoff=config.backoff_policy(),
        user_agent=config.openai_user_agent,
        x_title=config.openai_x_title,
        referer=config.openai_referer,
        first_chunk_timeout_ms=config.first_chunk_timeout_millis,
        other_chunk_timeout_ms=config.other_chunk_timeout_millis,
        archive_fetcher=store,
        resilience=resilience,
        # hostile-upstream byte budgets (JUDGE_STREAM_MAX_BYTES /
        # SSE_MAX_EVENT_BYTES): cap trips degrade the judge leg instead
        # of growing host memory without bound
        judge_stream_max_bytes=config.judge_stream_max_bytes,
        sse_max_event_bytes=config.sse_max_event_bytes,
    )
    model_registry = registry.InMemoryModelRegistry()
    # --fake-upstream is demo/test mode: synthetic embedder params are
    # allowed (still logged); production startup refuses them
    embedder = build_embedder(config, allow_synthetic=fake_upstream)
    if embedder is not None:
        # per-bucket device timing (phases/roofline sections), measured
        # enqueue-to-ready: under the batcher the readiness wait runs on
        # a waiter thread (models/dispatch_seam.py), so timing no longer
        # serializes the dispatch pipeline; =0 only darkens the device
        # rows, roofline attainment and the overlap gauge
        embedder.device_timing = config.metrics_device_timing
    if embedder is not None and config.aot_cache_dir:
        # AOT_CACHE_DIR: fleet-shared serialized-executable store — the
        # warmup below deserializes any bucket a peer (or a previous run
        # of this replica) already compiled, and persists what it
        # compiles itself.  Attached before warmup so the very first
        # _aot_compile call can restore.
        from ..models.aot_store import AotStore

        embedder.aot_store = AotStore(
            config.aot_cache_dir, meta=embedder.aot_cache_meta()
        )
    packed_buckets = []
    if embedder is not None and config.warmup:
        if config.packing_enabled and embedder.supports_packing():
            # the hot packed-capacity buckets (serve/packing.py): every
            # pow2 row count up to the per-call cap at full seq width
            # (saturated bursts), plus the single-row call at each
            # narrower seq bucket (lone small requests).  Cold (B, L)
            # pairs off this set ride the jit path — log-bounded by the
            # pow2 x ladder lattice
            from .packing import _L_BUCKETS

            l_top = config.packing_row_tokens
            k = config.packing_max_segments
            b = 1
            while b <= config.packing_max_rows:
                packed_buckets.append((b, l_top, k))
                b *= 2
            packed_buckets.extend(
                (1, l, k) for l in _L_BUCKETS if l < l_top
            )
        _warmup_embedder(
            embedder,
            config.warmup,
            config.warmup_r,
            aot=config.warmup_aot,
            packed_buckets=packed_buckets,
            ring_buckets=config.long_context_warmup,
        )
    # mesh fault domains (MESH_FAULT_ENABLED, resilience/meshfault.py):
    # the downsize ladder is declared — and every fallback rung AOT-warmed
    # under its own ("mesh", dp, tp) key namespace — at startup, so a
    # mid-traffic downsize is a param re-shard + executable-table swap,
    # never a compile storm
    meshfault = None
    if (
        config.mesh_fault_enabled
        and embedder is not None
        and getattr(embedder, "mesh_mode", False)
    ):
        import logging

        from ..resilience import MeshFaultManager

        meshfault = MeshFaultManager(
            embedder,
            shape=embedder.mesh_shape,
            transient_retries=config.mesh_fault_transient_retries,
            probe_millis=config.mesh_fault_probe_millis,
            fault_plan=config.device_fault_injection_plan(),
        )
        _mf_log = logging.getLogger("lwc.serve")
        _mf_log.info(
            "mesh fault ladder: %s",
            " -> ".join(f"{d}x{t}" for d, t in meshfault.build_ladder()),
        )
        if config.warmup and config.warmup_aot and embedder._aot_ready():
            from ..models.embedder import _seq_bucket

            snapped = list(
                dict.fromkeys(
                    (n, _seq_bucket(s, embedder.max_tokens))
                    for n, s in config.warmup
                )
            )
            for label, dt in meshfault.warm_ladder(
                snapped,
                config.warmup_r,
                packed_buckets,
                config.long_context_warmup,
            ):
                _mf_log.info(
                    "mesh fault ladder AOT %s compiled in %.1fs", label, dt
                )
        if config.mesh_fault_probe_millis > 0:
            # real recovery validation: try_recover re-shards to the
            # full mesh and runs this tiny dispatch across it BEFORE
            # reporting recovered; a device-classified raise rolls the
            # upsize back.  Without it (and without a fault plan) the
            # prober would blindly upsize and flap down again on the
            # next real dispatch.  Uses the first warmed consensus spec
            # so in a warmed service the probe hits an AOT executable.
            import numpy as _np

            from ..models.embedder import _seq_bucket

            if config.warmup:
                _pn, _ps = config.warmup[0]
                _probe_shape = (_pn, _seq_bucket(_ps, embedder.max_tokens))
            else:
                _probe_shape = (2, _seq_bucket(8, embedder.max_tokens))

            def _mesh_probe(shape=_probe_shape):
                n, s = shape
                ids = _np.zeros((n, s), dtype=_np.int32)
                mask = _np.zeros((n, s), dtype=_np.int32)
                mask[:, 0] = 1  # one real token per row: a clean forward
                _np.asarray(
                    embedder.consensus_confidence_tokens(ids, mask)
                )

            meshfault.probe_fn = _mesh_probe
    reranker = build_reranker(config, allow_synthetic=fake_upstream)
    from .metrics import Metrics

    # metrics exist regardless of the device side: the result cache's
    # counters (and the HTTP series) are host-only observability
    metrics = Metrics()
    if embedder is not None:
        # jit-cache introspection on /metrics: AOT bucket count + live
        # specialization counts (asserting "zero new specializations
        # post-warmup" is observable in production, not just in tests)
        metrics.register_provider("jit", embedder.jit_stats)
    if embedder is not None and getattr(embedder, "mesh_mode", False):
        # mesh-serving introspection: the shape traffic shards over and
        # the per-(mesh-shape, bucket) AOT coverage

        def _mesh_stats():
            dp, tp = embedder.mesh_shape
            sp = getattr(embedder, "mesh_sp", 1)
            return {
                "enabled": True,
                "dp": dp,
                "tp": tp,
                "sp": sp,
                "devices": dp * tp * sp,
                "ring": bool(embedder.ring_available()),
                "ring_max_tokens": embedder.ring_max_tokens,
                "aot_buckets": sum(
                    1 for key in embedder._aot if key and key[0] == "mesh"
                ),
            }

        metrics.register_provider("mesh", _mesh_stats)
    if meshfault is not None:
        # degraded-mesh introspection: current/full shape, epoch,
        # downsize/upsize/re-dispatch counters, faulted device ids
        metrics.register_provider("meshfault", meshfault.snapshot)
    score_cache = None
    embed_cache = None
    if config.score_cache_ttl_sec > 0:
        from ..cache import EmbeddingCache, ScoreCache

        score_cache = ScoreCache(
            config.score_cache_ttl_sec,
            config.score_cache_max_bytes,
            config.score_cache_dir,
        )
        if config.score_cache_embed:
            embed_cache = EmbeddingCache(
                config.score_cache_ttl_sec,
                config.score_cache_embed_max_bytes,
            )
    # FLEET_*: the replicated-cache tier (fleet/).  Config validation
    # guarantees the score cache exists whenever the fleet is on; the
    # coordinator serves owner-side state from it and peers publish
    # into it (fleet/handlers.py)
    fleet = None
    fleet_cfg = config.fleet_config()
    if fleet_cfg is not None and score_cache is not None:
        from ..fleet import FleetCoordinator

        fleet = FleetCoordinator(fleet_cfg)
        fleet.cache = score_cache
    # device watchdog (DEVICE_WATCHDOG_MILLIS > 0): brackets every
    # batched dispatch; a hung PJRT call flips readiness and — with the
    # CPU fallback built below — reroutes device work off the chip
    watchdog = None
    if config.device_watchdog_millis > 0:
        from ..resilience import DeviceWatchdog

        watchdog = DeviceWatchdog(
            config.device_watchdog_millis,
            interval_ms=config.device_watchdog_interval_millis,
        )
    fallback_embedder = None
    fallback_context = None
    if (
        watchdog is not None
        and config.device_watchdog_cpu_fallback
        and embedder is not None
    ):
        fallback_embedder, fallback_context = _build_cpu_fallback(
            config, fake_upstream
        )
    batcher = None
    if embedder is not None:
        from .batcher import DeviceBatcher

        batcher = DeviceBatcher(
            embedder,
            metrics,
            window_ms=config.batch_window_ms,
            max_batch=config.batch_max,
            pipeline_depth=config.batch_pipeline,
            max_rows=config.batch_max_rows,
            packing=config.packing_enabled,
            packing_row_tokens=config.packing_row_tokens,
            packing_max_rows=config.packing_max_rows,
            packing_max_segments=config.packing_max_segments,
            prefix_dedup=config.prefix_dedup,
            prefix_dedup_min_chars=config.prefix_dedup_min_chars,
            host_tokenizer_workers=config.host_tokenizer_workers,
            staging_buffers=config.staging_buffers,
            embed_cache=embed_cache,
            max_queue_depth=config.admission_max_queue_depth,
            watchdog=watchdog,
            fallback_embedder=fallback_embedder,
            fallback_context=fallback_context,
            meshfault=meshfault,
        )
    if watchdog is not None:
        import logging

        _log = logging.getLogger("lwc.serve")
        _batcher = batcher
        _meshfault = meshfault

        def _mesh_absorbs() -> bool:
            # MESH_FAULT_ENABLED precedence (serve/config.py): the wedge
            # goes to the downsize ladder, not straight to the CPU twin —
            # the twin is the post-exhaustion last resort, and the
            # batcher's fault handler flips it only when downsize()
            # reports the ladder spent
            return (
                _meshfault is not None
                and _batcher is not None
                and not _batcher._use_fallback
            )

        def _on_trip(kind: str, overdue_ms: float) -> None:
            _log.error(
                "device watchdog TRIPPED: %s dispatch overdue after "
                "%.0f ms%s",
                kind,
                overdue_ms,
                (
                    "; escalating to the mesh fault ladder"
                    if _mesh_absorbs()
                    else "; routing device work to the CPU fallback"
                    if _batcher is not None
                    and _batcher.fallback_embedder is not None
                    else "; device endpoints will shed until it completes"
                ),
            )
            if _mesh_absorbs():
                _meshfault.note_watchdog_trip()
                return
            if _batcher is not None:
                _batcher.use_fallback(True)

        def _on_recover() -> None:
            _log.warning(
                "device watchdog recovered: the overdue dispatch "
                "completed, device traffic resumes"
            )
            if _meshfault is not None:
                # mesh-fault mode never flipped the fallback on trip, and
                # a post-exhaustion fallback must survive the recovery —
                # a completed wedge does not un-exhaust the ladder
                return
            if _batcher is not None:
                _batcher.use_fallback(False)

        watchdog.on_trip = _on_trip
        watchdog.on_recover = _on_recover
        watchdog.start()

    # LOCK_WITNESS=1: runtime lockdep (analysis/witness.py) — wrap the
    # registered threading primitives so real acquisition order is
    # validated against the declared DAG (analysis/concurrency_model.py)
    # while the server runs; the snapshot rides /metrics and the drain
    # path prints the summary the soak drill asserts on
    witness = None
    if config.lock_witness:
        from ..analysis.witness import LockWitness
        from ..obs import phases as _obs_phases
        from ..obs import quality as _obs_quality

        witness = LockWitness()
        _obs_phases._AGG._lock = witness.wrap_lock(
            "PhaseAggregator._lock", _obs_phases._AGG._lock
        )
        _obs_quality._AGG._lock = witness.wrap_lock(
            "QualityAggregator._lock", _obs_quality._AGG._lock
        )
        if watchdog is not None:
            watchdog._lock = witness.wrap_lock(
                "DeviceWatchdog._lock", watchdog._lock
            )
        if batcher is not None:
            batcher._stats_lock = witness.wrap_lock(
                "DeviceBatcher._stats_lock", batcher._stats_lock
            )
        if meshfault is not None:
            meshfault._lock = witness.wrap_lock(
                "MeshFaultManager._lock", meshfault._lock
            )
            witness.wrap_gate(meshfault._shape_gate)
        pool = getattr(embedder, "staging_pool", None)
        if pool is not None:
            pool._lock = witness.wrap_lock("StagingPool._lock", pool._lock)
        metrics.register_provider("lock_witness", witness.snapshot)

    # admission gate: always present (with every knob 0 it never sheds,
    # it only tracks in-flight work for the drain path); device-
    # dependent endpoints additionally shed while the watchdog holds
    # the device unhealthy and no CPU fallback can absorb the work
    from ..resilience import AdmissionController

    def _device_gate():
        if watchdog is not None and not watchdog.healthy():
            if batcher is None or batcher.fallback_embedder is None:
                return "device_unhealthy"
        return None

    # MEMGUARD: host memory governor (resilience/memguard.py) — soft
    # pressure shrinks cache/trace budgets and decays the AIMD limit,
    # hard pressure sheds at admission with shed_reason "memory".  None
    # when disabled or when /proc/meminfo is unreadable and no explicit
    # watermarks were given (the governor never guesses)
    memguard = config.memguard()
    admission = AdmissionController(
        config.admission_config(),
        device_gate=_device_gate,
        mem_gate=memguard.gate if memguard is not None else None,
    )
    if meshfault is not None:
        # every shape change rescales admission (hard cap + AIMD limit)
        # and the batcher's group capacity to the surviving chip fraction
        meshfault.rescale_hooks.append(admission.rescale)
        if batcher is not None:
            meshfault.rescale_hooks.append(batcher.rescale_capacity)
    weight_fetchers = WeightFetchers()
    tables = None
    if embedder is not None:
        from ..weights.training_table import (
            TpuTrainingTableFetcher,
            TrainingTableStore,
        )

        if config.tables_path and os.path.exists(config.tables_path):
            tables = TrainingTableStore.load(config.tables_path)
        else:
            tables = TrainingTableStore()
        if config.tables_path:
            from ..utils.io import probe_writable_config

            probe_writable_config(
                config.tables_path,
                "TABLES_PATH",
                "learned weights would be lost at shutdown",
            )
        weight_fetchers = WeightFetchers(
            training_table_fetcher=TpuTrainingTableFetcher(
                embedder, tables, batcher=batcher
            )
        )
    # QUALITY_*: drift-window knobs applied to the process-global
    # consensus-quality aggregator (always on, like the phase aggregate)
    from ..obs import configure_quality

    configure_quality(
        window=config.quality_window,
        drift_threshold=config.quality_drift_threshold,
    )
    # LEDGER_*: per-request consensus-outcome records (obs/ledger.py);
    # None keeps the tally ledger-free
    ledger = config.outcome_ledger()
    # WEIGHTS_*: versioned live judge-weight tables behind atomic
    # hot-swap (weights/live.py); None keeps static-weight behavior
    live_weights = config.live_weights()
    if live_weights is not None and config.weights_path:
        from ..utils.io import probe_writable_config

        probe_writable_config(
            config.weights_path,
            "WEIGHTS_PATH",
            "hot-swapped weight tables would be lost at shutdown",
        )
    score_client = ScoreClient(
        chat_client,
        model_registry,
        weight_fetchers=weight_fetchers,
        archive_fetcher=store,
        # ballots stored alongside enable logprob re-extraction in batch
        # re-score (archive/rescore.py revote)
        ballot_sink=store.put_ballot if config.archive_write else None,
        # SCORE_CACHE_TTL > 0: content-addressed result cache with
        # single-flight dedup (cache/); None preserves pre-cache behavior
        cache=score_cache,
        # RESILIENCE_*: shared retry budget + weight-quorum degradation
        resilience=resilience,
        # JUDGE_BIAS_PLAN: deterministic vote perturbation (drills only)
        bias_plan=config.judge_bias_injection_plan(),
        ledger=ledger,
        # FLEET_*: cross-replica peer fetch + single-flight leases; None
        # preserves single-replica behavior
        fleet=fleet,
        # HOST_FASTPATH: fixed-point vectorized tally (clients/tally.py)
        host_fastpath=config.host_fastpath,
        live_weights=live_weights,
    )
    multichat_client = MultichatClient(
        chat_client, model_registry, archive_fetcher=store
    )
    gw_chat, gw_score, gw_multichat = chat_client, score_client, multichat_client
    if config.archive_write:
        from ..types import chat_response, multichat_response, score_response

        def put_score(result, params):
            store.put_score(result)
            store.put_score_request(result.id, params)

        def fold(unary_cls):
            # ARCHIVE_STREAMING: tee streams into the merge-algebra fold
            if not config.archive_streaming:
                return None
            return unary_cls.from_streaming

        gw_chat = _ArchivingClient(
            chat_client,
            lambda result, params: store.put_chat(result),
            stream_fold=fold(chat_response.ChatCompletion),
        )
        gw_score = _ArchivingClient(
            score_client,
            put_score,
            stream_fold=fold(score_response.ChatCompletion),
        )
        gw_multichat = _ArchivingClient(
            multichat_client,
            lambda result, params: store.put_multichat(result),
            stream_fold=fold(multichat_response.ChatCompletion),
        )
    # the drain/readiness state machine: SIGTERM flips /readyz, stops
    # admission, drains in-flight streams + the batcher queue (bounded
    # by DRAIN_TIMEOUT_MILLIS), flushes the cache disk tier once
    from .lifecycle import Lifecycle

    # TRACE_*: request tracing (obs/); None preserves untraced behavior.
    # Hoisted so the memory governor can shrink the ring under pressure
    trace_sink = config.trace_sink()
    if memguard is not None:
        memguard.govern(
            caches=[c for c in (score_cache, embed_cache) if c is not None],
            sinks=[s for s in (trace_sink,) if s is not None],
            admission=admission,
        )
        memguard.start()
    lifecycle = Lifecycle(
        admission=admission,
        batcher=batcher,
        caches=(score_cache, embed_cache),
        watchdog=watchdog,
        memguard=memguard,
        meshfault=meshfault,
        drain_timeout_ms=config.drain_timeout_millis,
        # FLEET_*: the drain hands this replica's hot set to its
        # post-drain owners before /readyz flips
        fleet=fleet,
    )
    app = build_app(
        gw_chat,
        gw_score,
        gw_multichat,
        embedder,
        metrics=metrics,
        profile_dir=config.profile_dir,
        batcher=batcher,
        reranker=reranker,
        resilience=resilience,
        fault_plan=fault_plan,
        admission=admission,
        lifecycle=lifecycle,
        watchdog=watchdog,
        meshfault=meshfault,
        trace_sink=trace_sink,
        ledger=ledger,
        fleet=fleet,
        # HOST_FASTPATH: splice-serialized SSE frames (serve/frames.py)
        host_fastpath=config.host_fastpath,
        memguard=memguard,
        # MAX_BODY_BYTES: aiohttp client_max_size — every route,
        # /fleet/v1 included, 413s render the payload_too_large envelope
        max_body_bytes=config.max_body_bytes,
        # WEIGHTS_* / OFFLINE_*: live weight hot-swap endpoints and the
        # offline-lane rescore driver (ISSUE 20)
        live_weights=live_weights,
        offline_enabled=config.offline_enabled,
        offline_inflight=config.offline_inflight,
    )
    app[ARCHIVE_KEY] = store
    # one lock for every handler that mutates the archive/tables
    archive_lock = asyncio.Lock()
    app.router.add_post(
        "/archive/rescore",
        _rescore_handler(
            store,
            archive_lock,
            # MESH_SP serving exposes sp_mesh, dp/tp serving exposes mesh;
            # the batched tally shards over every axis of either
            mesh=getattr(embedder, "mesh", None)
            or getattr(embedder, "sp_mesh", None),
        ),
    )
    if tables is not None:
        app[TABLES_KEY] = tables
        app.router.add_post(
            "/weights/learn",
            _learn_handler(store, embedder, tables, archive_lock),
        )
    if config.archive_path:
        path = config.archive_path

        async def _save_archive(app):
            store.save(path)

        app.on_cleanup.append(_save_archive)
    if tables is not None and config.tables_path:
        tables_path = config.tables_path

        async def _save_tables(app):
            tables.save(tables_path)

        app.on_cleanup.append(_save_tables)

    async def _close_transport(app):
        await transport.close()

    app.on_cleanup.append(_close_transport)
    if fleet is not None:

        async def _close_fleet(app):
            await fleet.close()

        app.on_cleanup.append(_close_fleet)
    if watchdog is not None:
        # signal-free shutdowns (tests, embedding into another runner)
        # must still stop the monitor thread; stop() is idempotent with
        # the drain path's
        async def _stop_watchdog(app):
            watchdog.stop()

        app.on_cleanup.append(_stop_watchdog)
    if witness is not None:
        # the soak drill greps this line after SIGTERM: a clean run
        # reports its real acquisition evidence on the way out
        async def _report_witness(app):
            print(witness.summary_line(), flush=True)

        app.on_cleanup.append(_report_witness)
    if (
        meshfault is not None
        and config.mesh_fault_probe_millis > 0
        and batcher is not None
    ):
        # recovery prober (MESH_FAULT_PROBE_MILLIS > 0): while degraded,
        # periodically re-validate the full mesh (probe_fn above: a real
        # full-mesh dispatch) and upsize back.  try_recover holds the
        # shape gate's exclusive side across the re-shard + probe, so it
        # is serialized with in-flight dispatches regardless of which
        # executor thread runs it; repeated probe failures back off
        # exponentially (each failed probe is a re-shard + rollback —
        # work worth not repeating every interval against a dead chip).
        probe_sec = config.mesh_fault_probe_millis / 1e3
        prober_tasks: list = []

        async def _start_mesh_prober(app):
            loop = asyncio.get_running_loop()

            async def _probe_loop():
                while True:
                    await asyncio.sleep(
                        probe_sec * meshfault.probe_backoff_scale()
                    )
                    if meshfault.degraded:
                        await loop.run_in_executor(
                            batcher._executor, meshfault.try_recover
                        )

            prober_tasks.append(loop.create_task(_probe_loop()))

        async def _stop_mesh_prober(app):
            for task in prober_tasks:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass

        app.on_startup.append(_start_mesh_prober)
        app.on_cleanup.append(_stop_mesh_prober)
    return app


async def _serve(config: Config, fake_upstream: bool) -> None:
    if fake_upstream:
        fake_app = web.Application()
        fake_app.router.add_post("/v1/chat/completions", _fake_upstream)
        fake_runner = web.AppRunner(fake_app)
        await fake_runner.setup()
        await web.TCPSite(fake_runner, "127.0.0.1", FAKE_PORT).start()

    app = build_service(config, fake_upstream=fake_upstream)
    runner = web.AppRunner(app)
    await runner.setup()
    await web.TCPSite(runner, config.address, config.port).start()
    print(f"listening on {config.address}:{config.port}", flush=True)

    # SIGINT/SIGTERM set a stop event instead of raising KeyboardInterrupt
    # mid-coroutine: cleanup (archive/tables snapshots, session close) then
    # runs to completion with no interrupt in flight — asyncio's default
    # handling can fire KeyboardInterrupt INSIDE a cleanup hook and lose
    # whichever snapshot hadn't been written yet
    import logging
    import signal

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    lifecycle = app.get(LIFECYCLE_KEY)

    def _drained(task: asyncio.Task) -> None:
        if not task.cancelled() and task.exception() is not None:
            logging.getLogger("lwc.serve").error(
                "graceful drain failed; shutting down anyway",
                exc_info=task.exception(),
            )
        stop.set()

    def _on_signal() -> None:
        if lifecycle is None:
            stop.set()
            return
        # graceful drain: /readyz flips and admission stops BEFORE the
        # listener closes (runner.cleanup runs only after the drain
        # task completes and sets the stop event).  begin_drain is
        # idempotent — repeated signals join the drain in progress.
        print("draining (SIGTERM/SIGINT received)...", flush=True)
        lifecycle.begin_drain().add_done_callback(_drained)

    handled = []
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, _on_signal)
            handled.append(sig)
        except (NotImplementedError, RuntimeError):
            pass
    try:
        await stop.wait()
    finally:
        # handlers stay installed THROUGH cleanup: a repeated signal
        # (operator mashing ctrl-C, a supervisor forwarding the signal)
        # must not interrupt a snapshot mid-write
        await runner.cleanup()
        for sig in handled:
            loop.remove_signal_handler(sig)


def main() -> None:
    parser = argparse.ArgumentParser("llm-weighted-consensus-tpu gateway")
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--address", default=None)
    parser.add_argument(
        "--fake-upstream",
        action="store_true",
        help="serve against a loopback scripted provider (no API keys)",
    )
    args = parser.parse_args()
    load_dotenv()
    # must precede any jax backend use (mesh construction in build_service)
    from ..parallel.dist import maybe_initialize_distributed

    maybe_initialize_distributed()
    config = Config.from_env()
    if args.port is not None:
        config.port = args.port
    if args.address is not None:
        config.address = args.address
    if not args.fake_upstream and not config.openai_apis:
        raise SystemExit(
            "Either OPENAI_APIS or both OPENAI_API_BASE and OPENAI_API_KEY "
            "must be set (or pass --fake-upstream)"
        )
    asyncio.run(_serve(config, args.fake_upstream))


if __name__ == "__main__":
    main()
