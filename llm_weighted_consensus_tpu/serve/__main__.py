"""Service entry point: ``python -m llm_weighted_consensus_tpu.serve``.

Wires env config into the client stack (main.rs wiring parity: default
clients + unimplemented fetchers unless stores are configured) and serves.
``--fake-upstream`` starts a loopback scripted provider and points the
chat client at it — the zero-key local demo / verification mode.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random

from aiohttp import web

from .. import archive, registry
from ..clients.chat import AiohttpTransport, ApiBase, DefaultChatClient
from ..clients.multichat import MultichatClient
from ..clients.score import ScoreClient
from ..weights import WeightFetchers
from .config import Config, load_dotenv
from .gateway import build_app

FAKE_PORT = 5990

# the service's archive store, exposed for introspection/tests
ARCHIVE_KEY: web.AppKey = web.AppKey("archive", object)


async def _fake_upstream(request: web.Request) -> web.StreamResponse:
    """A scripted judge provider: finds the ballot in the system prompt and
    votes for a random key; plain chat otherwise."""
    body = await request.json()
    content = "This is a fake upstream completion."
    for message in reversed(body.get("messages", [])):
        if message.get("role") == "system" and "Select the response:" in str(
            message.get("content", "")
        ):
            text = message["content"]
            ballot = json.loads(
                text.split("Select the response:\n\n", 1)[1].split(
                    "\n\nOutput", 1
                )[0]
            )
            content = f"I select {random.choice(list(ballot))}"
            break
    resp = web.StreamResponse(
        headers={"content-type": "text/event-stream"}
    )
    await resp.prepare(request)
    for i, frag in enumerate((content[: len(content) // 2], content[len(content) // 2 :])):
        chunk = {
            "id": "fake-1",
            "object": "chat.completion.chunk",
            "created": 0,
            "model": body.get("model", "fake"),
            "choices": [
                {
                    "index": 0,
                    "delta": (
                        {"role": "assistant", "content": frag}
                        if i == 0
                        else {"content": frag}
                    ),
                    "finish_reason": None,
                }
            ],
        }
        await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
    final = {
        "id": "fake-1",
        "object": "chat.completion.chunk",
        "created": 0,
        "model": body.get("model", "fake"),
        "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}],
        "usage": {"prompt_tokens": 10, "completion_tokens": 10, "total_tokens": 20},
    }
    await resp.write(f"data: {json.dumps(final)}\n\ndata: [DONE]\n\n".encode())
    return resp


def build_embedder(config: Config):
    """The service's device side: an embedder from env config, placed on a
    (dp, tp) mesh when MESH_DP / MESH_TP are set (batches shard over dp,
    encoder params Megatron-split over tp — parallel/sharding.py)."""
    if not config.embedder_model:
        return None
    from ..models.embedder import TpuEmbedder
    from ..models.tokenizer import load_tokenizer

    embedder = TpuEmbedder(
        config.embedder_model,
        # only override the tokenizer when a real vocab is configured;
        # TpuEmbedder's default hash fallback sizes to the model vocab
        tokenizer=(
            load_tokenizer(config.embedder_vocab)
            if config.embedder_vocab
            else None
        ),
        max_tokens=config.embedder_max_tokens,
    )
    if config.mesh_dp is not None or config.mesh_tp > 1:
        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.sharding import shard_embedder

        # the serving mesh is HOST-LOCAL: a request lands on one host and
        # must be executable without the other hosts' cooperation (they
        # serve their own traffic).  Single-host: local == global.  See
        # DESIGN.md §multi-host.
        mesh = make_mesh(
            dp=config.mesh_dp,
            tp=config.mesh_tp,
            devices=jax.local_devices(),
        )
        shard_embedder(embedder, mesh, tp=config.mesh_tp > 1)
    return embedder


class _ArchivingClient:
    """Wraps a client so every served UNARY completion is archived (its id
    becomes referenceable by later requests); everything else delegates.
    Streaming responses are consumed by the HTTP caller chunk-by-chunk and
    are not teed into the archive — unary-only, by design."""

    def __init__(self, inner, put):
        self._inner = inner
        self._put = put

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def create_unary(self, ctx, params):
        result = await self._inner.create_unary(ctx, params)
        self._put(result)
        return result


def build_service(config: Config, fake_upstream: bool = False):
    import os

    api_bases = config.api_bases()
    if fake_upstream:
        api_bases = [ApiBase(f"http://127.0.0.1:{FAKE_PORT}/v1", "fake-key")]
    if config.archive_path and os.path.exists(config.archive_path):
        store = archive.InMemoryArchive.load(config.archive_path)
    else:
        store = archive.InMemoryArchive()
    if config.archive_path:
        # fail FAST on an unwritable path: the shutdown save is the last
        # moment we could find out, and by then the archive would be lost
        store.save(config.archive_path)
    transport = AiohttpTransport()
    chat_client = DefaultChatClient(
        transport,
        api_bases,
        backoff=config.backoff_policy(),
        user_agent=config.openai_user_agent,
        x_title=config.openai_x_title,
        referer=config.openai_referer,
        first_chunk_timeout_ms=config.first_chunk_timeout_millis,
        other_chunk_timeout_ms=config.other_chunk_timeout_millis,
        archive_fetcher=store,
    )
    model_registry = registry.InMemoryModelRegistry()
    embedder = build_embedder(config)
    weight_fetchers = WeightFetchers()
    if embedder is not None:
        from ..weights.training_table import TpuTrainingTableFetcher

        weight_fetchers = WeightFetchers(
            training_table_fetcher=TpuTrainingTableFetcher(embedder)
        )
    score_client = ScoreClient(
        chat_client,
        model_registry,
        weight_fetchers=weight_fetchers,
        archive_fetcher=store,
        # ballots stored alongside enable logprob re-extraction in batch
        # re-score (archive/rescore.py revote)
        ballot_sink=store.put_ballot if config.archive_write else None,
    )
    multichat_client = MultichatClient(
        chat_client, model_registry, archive_fetcher=store
    )
    gw_chat, gw_score, gw_multichat = chat_client, score_client, multichat_client
    if config.archive_write:
        gw_chat = _ArchivingClient(chat_client, store.put_chat)
        gw_score = _ArchivingClient(score_client, store.put_score)
        gw_multichat = _ArchivingClient(multichat_client, store.put_multichat)
    app = build_app(
        gw_chat,
        gw_score,
        gw_multichat,
        embedder,
        profile_dir=config.profile_dir,
    )
    app[ARCHIVE_KEY] = store
    if config.archive_path:
        path = config.archive_path

        async def _save_archive(app):
            store.save(path)

        app.on_cleanup.append(_save_archive)

    async def _close_transport(app):
        await transport.close()

    app.on_cleanup.append(_close_transport)
    return app


async def _serve(config: Config, fake_upstream: bool) -> None:
    if fake_upstream:
        fake_app = web.Application()
        fake_app.router.add_post("/v1/chat/completions", _fake_upstream)
        fake_runner = web.AppRunner(fake_app)
        await fake_runner.setup()
        await web.TCPSite(fake_runner, "127.0.0.1", FAKE_PORT).start()

    app = build_service(config, fake_upstream=fake_upstream)
    runner = web.AppRunner(app)
    await runner.setup()
    await web.TCPSite(runner, config.address, config.port).start()
    print(f"listening on {config.address}:{config.port}", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        # run the app's on_cleanup hooks (e.g. the ARCHIVE_PATH snapshot)
        # on SIGINT/cancellation — without this, graceful shutdown never
        # fires them in the real service path
        await runner.cleanup()


def main() -> None:
    parser = argparse.ArgumentParser("llm-weighted-consensus-tpu gateway")
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--address", default=None)
    parser.add_argument(
        "--fake-upstream",
        action="store_true",
        help="serve against a loopback scripted provider (no API keys)",
    )
    args = parser.parse_args()
    load_dotenv()
    # must precede any jax backend use (mesh construction in build_service)
    from ..parallel.dist import maybe_initialize_distributed

    maybe_initialize_distributed()
    config = Config.from_env()
    if args.port is not None:
        config.port = args.port
    if args.address is not None:
        config.address = args.address
    if not args.fake_upstream and not config.openai_apis:
        raise SystemExit(
            "Either OPENAI_APIS or both OPENAI_API_BASE and OPENAI_API_KEY "
            "must be set (or pass --fake-upstream)"
        )
    asyncio.run(_serve(config, args.fake_upstream))


if __name__ == "__main__":
    main()
