"""SSE HTTP gateway: the serving shell around the consensus engine.

Parity target: reference src/main.rs — env-first config, POST
/chat/completions and /score/completions with SSE streaming + ``[DONE]``
terminator, unary JSON when ``stream`` is false, uniform
``{code, message}`` error bodies.  Extended beyond the reference with the
endpoints its types promise but its binary never serves:
/multichat/completions (the fan-out generator) and /embeddings (the on-TPU
encoder).
"""

from .config import Config  # noqa: F401
from .gateway import build_app  # noqa: F401
