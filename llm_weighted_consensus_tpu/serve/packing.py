"""Ragged segment-id packing for the continuous batcher (pure host side).

The padded dispatch path pays one encoder ROW per sequence, padded to a
``(R, N, S)`` bucket — under mixed-length traffic most row slots multiply
padding.  The packed layout instead lays many variable-length sequences
end-to-end in each dense row:

* ``ids[B, L]``        — token streams, concatenated per row;
* ``segment_ids[B, L]``— int32 segment slot per token (0 = pad slot;
                         slot j+1 holds the row's j-th sequence);
* ``positions[B, L]``  — within-segment offsets, restarting at 0 per
                         segment (each sequence sees exactly the position
                         embeddings its padded twin would — and a row may
                         exceed the model's position table, because only
                         SEGMENTS are bounded by it);
* ``seg_starts[B, K]`` — row offset of each slot's first token (the
                         segment's [CLS], pooled where the padded path
                         reads ``hidden[:, 0]``).

``models/bert.py::embed_packed`` consumes this layout with a same-segment
attention mask, so the packed forward reproduces the per-row forward
(tests/test_packing.py asserts parity).  Capacity buckets are the small
fixed set ("packed", B, L, K) with B a power of two (calls are always
exactly full — no pad rows) and L on the coarse ``_L_BUCKETS`` ladder —
replacing the (R, N, S) lattice on the packed path — so AOT warmup can
cover the hot ones and the jit fallback stays log-bounded.

Everything here is pure and synchronous (list/ndarray in, ndarray out):
the DeviceBatcher calls it from the device thread, and the unit tests
drive it without an event loop.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence

import numpy as np

from ..utils import next_pow2


def plan_rows(
    lengths: Sequence[int], row_tokens: int, max_segments: int
) -> List[List[int]]:
    """First-fit packing: segment lengths -> rows of segment indices.

    Arrival order is preserved within a row (deterministic layout for a
    given input), every segment must satisfy ``0 < length <= row_tokens``
    (callers route oversized sequences to the padded path), and each row
    holds at most ``max_segments`` segments (the K slot dimension).
    First-fit over all open rows: O(n*rows), and within a few percent of
    optimal at serving sizes — the tail waste is bounded by one
    max-length segment per row.
    """
    open_rows: list = []  # [remaining_capacity, seg_count, indices]
    for i, n in enumerate(lengths):
        n = int(n)
        if n <= 0 or n > row_tokens:
            raise ValueError(
                f"segment {i} length {n} outside (0, {row_tokens}]"
            )
        for row in open_rows:
            if row[0] >= n and row[1] < max_segments:
                row[0] -= n
                row[1] += 1
                row[2].append(i)
                break
        else:
            open_rows.append([row_tokens - n, 1, [i]])
    return [row[2] for row in open_rows]


# seq-width buckets for one packed call (the coarse tail of the padded
# path's _SEQ_BUCKETS ladder): a call whose rows all fill below a bucket
# dispatches at that bucket's width instead of the full row_tokens, so a
# sparse dispatch doesn't pay full-width slot waste.  Coarse on purpose —
# each (B, L) pair is a compiled shape
_L_BUCKETS = (64, 128, 192, 256, 384, 512, 768, 1024, 1536, 2048)


def seq_bucket_packed(n: int, row_tokens: int) -> int:
    """Packed-call seq width for a max row fill of ``n`` tokens: the
    smallest _L_BUCKETS entry >= n, capped at ``row_tokens``."""
    for size in _L_BUCKETS:
        if size >= n:
            return min(size, row_tokens)
    return min(n, row_tokens)


def rows_bucket(n_rows: int, max_rows: int) -> int:
    """Rows in the NEXT packed device call given ``n_rows`` still to
    dispatch: the largest power of two <= min(n_rows, max_rows).  Calls
    are always exactly full — 20 rows at max_rows=8 dispatch as 8+8+4,
    never as 8+8+8-with-4-pad-rows — so the ("packed", B, L, K)
    executable set stays log-sized AND pad rows never dilute the
    real-token/slot-token efficiency."""
    n = max(1, min(n_rows, max_rows))
    b = 1
    while b * 2 <= n:
        b *= 2
    return b


class PackedCall:
    """One device call's worth of packed arrays plus the segment map."""

    __slots__ = ("ids", "segment_ids", "positions", "seg_starts", "slots",
                 "real_tokens")

    def __init__(self, ids, segment_ids, positions, seg_starts, slots,
                 real_tokens):
        self.ids = ids
        self.segment_ids = segment_ids
        self.positions = positions
        self.seg_starts = seg_starts
        # segment index -> (row, slot) within THIS call
        self.slots = slots
        self.real_tokens = real_tokens

    @property
    def slot_tokens(self) -> int:
        return int(self.ids.size)


def build_calls(
    seg_tokens: Sequence[np.ndarray],
    row_tokens: int,
    max_rows: int,
    max_segments: int,
) -> List[PackedCall]:
    """Plan + materialize: ragged token rows -> a list of PackedCalls.

    Rows are first-fit packed, sorted fullest-first, then chunked into
    exactly-full power-of-two calls (``rows_bucket``); each call's seq
    width is the ``seq_bucket_packed`` bucket of its fullest row.  Both
    choices serve the real-token/slot-token efficiency the /metrics
    ``packing`` section reports: no pad rows ever dispatch, and a call
    of lightly-filled rows (the tail of a burst, a lone small request)
    dispatches at a narrow L instead of the full ``row_tokens``.
    Unused trailing token slots keep segment id 0 — fully masked,
    pooled by nobody.
    """
    lengths = [len(t) for t in seg_tokens]
    rows = plan_rows(lengths, row_tokens, max_segments)
    # fullest-first, so each pow2 chunk groups rows of similar fill and
    # the narrow-L win lands on the sparse tail call
    rows.sort(
        key=lambda seg_list: sum(lengths[si] for si in seg_list),
        reverse=True,
    )
    calls: List[PackedCall] = []
    start = 0
    while start < len(rows):
        b = rows_bucket(len(rows) - start, max_rows)
        chunk = rows[start : start + b]
        start += b
        l_call = seq_bucket_packed(
            max(sum(lengths[si] for si in seg_list) for seg_list in chunk),
            row_tokens,
        )
        ids = np.zeros((b, l_call), np.int32)
        seg = np.zeros((b, l_call), np.int32)
        pos = np.zeros((b, l_call), np.int32)
        starts = np.zeros((b, max_segments), np.int32)
        slots = {}
        real = 0
        for r, seg_list in enumerate(chunk):
            off = 0
            for slot, si in enumerate(seg_list):
                t = np.asarray(seg_tokens[si], np.int32)
                n = len(t)
                ids[r, off : off + n] = t
                seg[r, off : off + n] = slot + 1
                pos[r, off : off + n] = np.arange(n, dtype=np.int32)
                starts[r, slot] = off
                slots[si] = (r, slot)
                off += n
                real += n
        calls.append(PackedCall(ids, seg, pos, starts, slots, real))
    return calls


# -- shared-prefix dedup ------------------------------------------------------

_LAST_WORD = re.compile(r"\s\S*$")


def shared_prefix(texts: Sequence[str], min_chars: int) -> Optional[str]:
    """Longest common prefix of all candidate texts, cut back to the last
    whitespace boundary, or None when shorter than ``min_chars``.

    The whitespace cut keeps the split tokenization-composable: both the
    WordPiece and hash tokenizers segment on whitespace/punctuation first,
    so ``tokens(prefix) + tokens(suffix)`` is ``tokens(full)`` up to the
    per-part special tokens ([CLS]/[SEP]).  The prefix-dedup embedding
    contract (serve/batcher.py::_dispatch_packed) is defined on the parts,
    so an exact token-level split is not required — only a stable one.
    """
    if len(texts) < 2 or min_chars <= 0:
        return None
    p = texts[0]
    for t in texts[1:]:
        while not t.startswith(p):
            p = p[: len(p) - 1]
            if not p:
                return None
    m = _LAST_WORD.search(p)
    if m is not None:
        p = p[: m.start()]
    if len(p) < min_chars:
        return None
    return p


def compose_prefix_suffix(
    prefix_vec: np.ndarray,
    prefix_tokens: int,
    suffix_vec: Optional[np.ndarray],
    suffix_tokens: int,
) -> np.ndarray:
    """The prefix-dedup candidate embedding: token-count-weighted sum of
    the independently pooled, l2-normalized prefix and suffix vectors,
    re-normalized.  This is the DEFINED contract (DESIGN.md "Continuous
    batching"), an approximation of the full-text embedding: a
    bidirectional encoder cannot reuse prefix states exactly, but the
    shared-prefix term is identical across a request's N candidates, so
    the consensus geometry is dominated by the suffix differences —
    which is what the vote measures."""
    if suffix_vec is None:
        return np.asarray(prefix_vec, np.float32)
    v = prefix_tokens * np.asarray(prefix_vec, np.float32) + (
        suffix_tokens * np.asarray(suffix_vec, np.float32)
    )
    return v / max(float(np.linalg.norm(v)), 1e-12)


def consensus_vote_np(vecs: np.ndarray, temperature: float) -> np.ndarray:
    """Host (numpy) twin of ``ops.similarity.dyn_cosine_vote`` for the
    packed consensus path: softmax over mean off-diagonal cosine
    similarity, f32 like the device vote.

    Host-side on purpose: the packed dispatch mixes requests of different
    N in one device call, and a device vote would either re-introduce a
    per-N jit specialization (the recompile lattice packing removes) or a
    second dispatch.  One [segments, H] transfer per packed call plus an
    O(N^2 * H) numpy contraction per request is microseconds at serving
    sizes; parity with the device vote is asserted in tests."""
    v = np.asarray(vecs, np.float32)
    n = v.shape[0]
    nrm = v / np.maximum(
        np.sqrt((v * v).sum(axis=-1, keepdims=True)), 1e-12
    )
    sims = nrm @ nrm.T
    np.fill_diagonal(sims, 0.0)
    mean_sim = sims.sum(axis=-1) / max(n - 1, 1)
    z = mean_sim / np.float32(temperature)
    z = z - z.max()
    e = np.exp(z)
    return (e / e.sum()).astype(np.float32)
