"""Shared encoder building blocks (used by bert.py and deberta.py).

Numerically-sensitive primitives live in exactly one place: dense matmuls
run in the param dtype with f32 accumulation on the MXU; layernorm always
computes in f32 regardless of the activation dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dense_init(rng, in_dim: int, out_dim: int, dtype) -> dict:
    return {
        "kernel": (
            jax.random.normal(rng, (in_dim, out_dim), jnp.float32) * 0.02
        ).astype(dtype),
        "bias": jnp.zeros((out_dim,), dtype),
    }


def ln_init(dim: int, dtype) -> dict:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layer_norm(x, params: dict, eps: float):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    # one-pass variance (E[x^2] - mean^2, clamped): both reductions fuse
    # into a single read of x, unlike jnp.var's subtract-then-reduce
    # second pass — worth ~0.3 ms/fwd at the headline shape (r4).  The
    # cancellation risk is bounded: LN inputs are O(1-10) f32, and flax
    # LayerNorm uses the same formulation.
    meansq = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    var = jnp.maximum(meansq - mean * mean, 0.0)
    normed = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (
        normed * params["scale"].astype(jnp.float32)
        + params["bias"].astype(jnp.float32)
    ).astype(x.dtype)


def dense(x, p: dict):
    return (
        jnp.einsum(
            "...i,io->...o",
            x,
            p["kernel"],
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)
        + p["bias"]
    )
