"""Shared encoder building blocks (used by bert.py and deberta.py).

Numerically-sensitive primitives live in exactly one place: dense matmuls
run in the param dtype with f32 accumulation on the MXU; layernorm always
computes in f32 regardless of the activation dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dense_init(rng, in_dim: int, out_dim: int, dtype) -> dict:
    return {
        "kernel": (
            jax.random.normal(rng, (in_dim, out_dim), jnp.float32) * 0.02
        ).astype(dtype),
        "bias": jnp.zeros((out_dim,), dtype),
    }


def ln_init(dim: int, dtype) -> dict:
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}


def layer_norm(x, params: dict, eps: float):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    # one-pass variance (E[x^2] - mean^2, clamped): both reductions fuse
    # into a single read of x, unlike jnp.var's subtract-then-reduce
    # second pass — worth ~0.3 ms/fwd at the headline shape (r4).  The
    # cancellation risk is bounded: LN inputs are O(1-10) f32, and flax
    # LayerNorm uses the same formulation.
    meansq = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    var = jnp.maximum(meansq - mean * mean, 0.0)
    normed = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (
        normed * params["scale"].astype(jnp.float32)
        + params["bias"].astype(jnp.float32)
    ).astype(x.dtype)


def dense(x, p: dict):
    return (
        jnp.einsum(
            "...i,io->...o",
            x,
            p["kernel"],
            preferred_element_type=jnp.float32,
        ).astype(x.dtype)
        + p["bias"]
    )


def dense_cfg(x, p: dict, config):
    """The layer-dense op under the config's quantize mode: param-dtype
    matmul (dense above), the W8A8 int8-MXU twin, or the packed-int4
    W4A8 twin (both models/quant.py) — selected statically by
    ``config.quantize``, so the jit sees one path.  Shared by every
    model family (bert, deberta)."""
    if config.quantize.startswith("int8"):
        from .quant import dense_int8, impl_for

        return dense_int8(x, p, impl=impl_for(config.quantize))
    if config.quantize.startswith("int4"):
        from .quant import dense_int4, impl_for

        return dense_int4(x, p, impl=impl_for(config.quantize))
    return dense(x, p)


def mlp_cfg(x, p_in: dict, p_out: dict, config):
    """The encoder MLP (dense -> GELU -> dense) under the config's
    quantize mode.  Full precision keeps the dense/gelu_erf composition;
    int8/int4 modes route BOTH matmuls through their quantized dense
    with the GELU folded into the expansion matmul's kernel epilogue
    (ops/kernels.w8a8_matmul / w4a8_matmul) — the [B*S, intermediate]
    GELU input never round-trips HBM between separate
    quant/matmul/activation passes."""
    if config.quantize.startswith("int8"):
        from .quant import dense_int8, impl_for

        impl = impl_for(config.quantize)
        h = dense_int8(x, p_in, gelu=True, impl=impl)
        return dense_int8(h, p_out, impl=impl)
    if config.quantize.startswith("int4"):
        from .quant import dense_int4, impl_for

        impl = impl_for(config.quantize)
        h = dense_int4(x, p_in, gelu=True, impl=impl)
        return dense_int4(h, p_out, impl=impl)
    return dense(gelu_erf(dense(x, p_in)), p_out)


def gelu_erf(x: jax.Array) -> jax.Array:
    """Exact (erf) GELU: HF BERT/bge/deberta checkpoints use
    hidden_act="gelu", which is erf-based — jax.nn.gelu's default tanh
    approximation would silently diverge from real checkpoints
    (tests/test_hf_parity.py): its output differs from exact-erf GELU by
    up to 257 bf16 ulps and flips the bf16 rounding of ~40% of inputs
    (measured, r4).

    f32 inputs always take XLA's exact erf; upcast from bf16 would too
    be exact — but for bf16 activations the erf lowering's ~12-op
    polynomial is the single largest non-matmul cost in the encoder
    forward (~2.7 ms of the 33.5 ms bge-large N=64/s=128 forward,
    bench_fwd.py).  The bf16 path instead uses the Abramowitz-Stegun
    7.1.26 erfc form, which rides the TPU's hardware exp: design error
    2.2e-7 absolute (f64), and after bf16 rounding it agrees with the
    exact-erf f32 GELU to <=1 bf16 ulp on ALL finite bf16 inputs
    x >= -3 (<2% of them flip by that 1 ulp — inherent to any f32
    evaluation near rounding midpoints) and to 2e-5 absolute in the deep
    tail (|gelu| < 0.005, where f32 cancellation in the polynomial
    shows).  Asserted exhaustively over every finite bf16 input in
    tests/test_models.py."""
    x32 = x.astype(jnp.float32)
    return gelu_f32(x32, approx=x.dtype == jnp.bfloat16).astype(x.dtype)


def gelu_f32(x32: jax.Array, approx: bool = False) -> jax.Array:
    """The f32 GELU core behind gelu_erf, split out so the W8A8 kernel
    epilogue (ops/kernels.py) applies the IDENTICAL math — same exact-erf
    vs A&S-7.1.26 split, same coefficients — inside the fused matmul."""
    if not approx:
        return x32 * 0.5 * (1.0 + jax.lax.erf(x32 * (2.0 ** -0.5)))
    z = jnp.abs(x32) * (2.0 ** -0.5)
    t = 1.0 / (1.0 + 0.3275911 * z)
    poly = t * (
        0.254829592
        + t
        * (
            -0.284496736
            + t * (1.421413741 + t * (-1.453152027 + t * 1.061405429))
        )
    )
    half_erfc = 0.5 * poly * jnp.exp(-z * z)
    phi = jnp.where(x32 > 0, 1.0 - half_erfc, half_erfc)
    return x32 * phi
