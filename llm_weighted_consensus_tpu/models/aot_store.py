"""Fleet-shared store of serialized compiled executables.

``aot_warmup`` (models/embedder.py) pre-compiles every configured
bucket so no request pays a jit compile.  On a single replica that cost
is paid once per process start; in a fleet it is paid once per REPLICA
— a new replica joining an autoscaled tier burns tens of seconds of
XLA compilation to produce byte-identical executables its peers
already hold.  This store closes that gap: the first replica to compile
a bucket serializes the executable (``jax.experimental
.serialize_executable``) into a shared artifact directory, and every
later replica — or the same replica after a restart — deserializes in
milliseconds instead of compiling.

Artifact layout (``aot/v1``)::

    <root>/<digest>/            one namespace per environment digest
        meta.json               the digest preimage, for humans
        <key-hash>.aotx         pickle of (payload, in_tree, out_tree)

The digest folds in everything that makes an executable non-portable:
jax version, backend, device kind and count, model name/config/dtype,
pooling, and max_tokens.  Any change lands in a fresh namespace, so a
stale artifact can never be deserialized into an incompatible runtime —
invalidation is by construction, not by cleanup.  Per-key filenames
hash the full warmup key (``("mesh", dp, tp, sp, bucket)`` prefixes
included), so single-device, mesh, and ring executables for the same
bucket shapes can never collide.

Every path fails open: an unreadable, truncated, or version-skewed
artifact returns None and the caller compiles exactly as before the
store existed.  Writes are atomic (tmp + rename) so a replica crashing
mid-save never poisons a peer.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional

from ..identity import hash_json_obj, id_string

FORMAT = "aot/v1"


def _key_name(key) -> str:
    return id_string(hash_json_obj(repr(key))) + ".aotx"


class AotStore:
    def __init__(self, root: str, *, meta: dict) -> None:
        self.meta = dict(meta, format=FORMAT)
        self.digest = id_string(hash_json_obj(self.meta))
        self.dir = os.path.join(root, self.digest)
        self.loads = 0
        self.saves = 0
        self.load_failures = 0
        self.save_failures = 0

    def _path(self, key) -> str:
        return os.path.join(self.dir, _key_name(key))

    def load(self, key):
        """The deserialized, loaded executable for ``key``, or None
        (missing, unreadable, or incompatible — the caller compiles)."""
        try:
            with open(self._path(key), "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            from jax.experimental import serialize_executable

            compiled = serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree
            )
        except FileNotFoundError:
            return None
        except Exception:
            self.load_failures += 1
            return None
        self.loads += 1
        return compiled

    def save(self, key, compiled) -> bool:
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            os.makedirs(self.dir, exist_ok=True)
            meta_path = os.path.join(self.dir, "meta.json")
            if not os.path.exists(meta_path):
                from ..utils import jsonutil

                self._atomic_write(
                    meta_path,
                    jsonutil.dumps(self.meta, pretty=True).encode("utf-8"),
                )
            self._atomic_write(
                self._path(key),
                pickle.dumps((payload, in_tree, out_tree)),
            )
        except Exception:
            self.save_failures += 1
            return False
        self.saves += 1
        return True

    def _atomic_write(self, path: str, data: bytes) -> None:
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def stats(self) -> dict:
        return {
            "dir": self.dir,
            "loads": self.loads,
            "saves": self.saves,
            "load_failures": self.load_failures,
            "save_failures": self.save_failures,
        }
