"""On-TPU models: BGE-class BERT text encoders + DeBERTa-style reward model.

The reference delegates all inference to upstream HTTP APIs and ships only
the embeddings *wire types* (SURVEY §2.9); here the encoder is a real
device model:

* ``bert``      — functional JAX BERT encoder (bge-small/base/large
  configs), bf16 matmuls with f32 layernorm/softmax, CLS/mean pooling;
* ``deberta``   — disentangled-attention encoder + scalar reward head
  (reward-model re-ranking, BASELINE config 3);
* ``tokenizer`` — host-side WordPiece (real vocab when available, a
  deterministic hash tokenizer fallback so the pipeline always runs);
* ``embedder``  — tokenize -> jitted forward -> pooled embedding, exposing
  the OpenAI embeddings wire contract (types/embeddings.py).

Params are plain nested-dict pytrees: trivially shardable with
jax.sharding, checkpointable with orbax, no framework lock-in.
"""

from . import bert, configs, deberta, embedder, tokenizer  # noqa: F401
